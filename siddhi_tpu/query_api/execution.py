"""Execution elements: queries, input streams (single/join/state), pattern state
elements, handlers, selectors, output streams/rates, partitions, store queries.

Reference: siddhi-query-api .../execution/** (Query.java, StoreQuery.java,
partition/Partition.java, query/input/state/*StateElement.java,
query/selection/Selector.java, query/output/stream/*, query/output/ratelimit/*).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Union

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.definition import SourceLocated, WindowSpec
from siddhi_tpu.query_api.expression import Expression, Variable


# ---------------------------------------------------------------------------
# stream handlers (filter / window / stream function)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Filter(SourceLocated):
    expression: Expression


@dataclasses.dataclass
class WindowHandler(SourceLocated):
    window: WindowSpec


@dataclasses.dataclass
class StreamFunctionHandler(SourceLocated):
    namespace: Optional[str]
    name: str
    parameters: list[Expression]


StreamHandler = Union[Filter, WindowHandler, StreamFunctionHandler]


# ---------------------------------------------------------------------------
# input streams
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SingleInputStream(SourceLocated):
    stream_id: str
    alias: Optional[str] = None  # `as e1`
    handlers: list[StreamHandler] = dataclasses.field(default_factory=list)
    is_inner: bool = False  # `#innerStream` inside partitions
    is_fault: bool = False  # `!faultStream`

    @property
    def ref(self) -> str:
        """Name by which expressions refer to this stream."""
        return self.alias or self.stream_id

    @staticmethod
    def fault_stream(stream_id: str) -> "SingleInputStream":
        """Programmatic `from !S` — S's fault stream (attributes + `_error`),
        auto-defined when S declares @OnError(action='STREAM')."""
        return SingleInputStream("!" + stream_id, is_fault=True)

    def filter(self, e: Expression) -> "SingleInputStream":
        self.handlers.append(Filter(e))
        return self

    def window(self, ns: Optional[str], name: str, *params: Expression) -> "SingleInputStream":
        self.handlers.append(WindowHandler(WindowSpec(ns, name, list(params))))
        return self


class JoinType(enum.Enum):
    JOIN = "join"  # inner
    LEFT_OUTER = "left outer join"
    RIGHT_OUTER = "right outer join"
    FULL_OUTER = "full outer join"


class JoinEventTrigger(enum.Enum):
    LEFT = "left"
    RIGHT = "right"
    ALL = "all"


@dataclasses.dataclass
class JoinInputStream(SourceLocated):
    left: SingleInputStream
    join_type: JoinType
    right: SingleInputStream
    on: Optional[Expression] = None
    trigger: JoinEventTrigger = JoinEventTrigger.ALL
    within: Optional[Expression] = None  # aggregation joins
    per: Optional[Expression] = None
    unidirectional: Optional[str] = None  # 'left' | 'right' | None


# ---------------------------------------------------------------------------
# pattern / sequence state elements
# (reference: execution/query/input/state/{Stream,Next,Every,Count,Logical,
#  AbsentStream}StateElement.java)
# ---------------------------------------------------------------------------


class StateElement(SourceLocated):
    """Base; every element may carry a `within_ms` bound
    (reference: query-api execution/query/input/state/StateElement.java)."""

    within_ms: Optional[int]


@dataclasses.dataclass
class StreamStateElement(StateElement):
    stream: SingleInputStream
    within_ms: Optional[int] = None


@dataclasses.dataclass
class AbsentStreamStateElement(StreamStateElement):
    waiting_time_ms: Optional[int] = None  # `not S for 5 sec`


@dataclasses.dataclass
class CountStateElement(StateElement):
    stream: StreamStateElement
    min_count: int = 0
    max_count: int = -1  # -1 == ANY / unbounded
    within_ms: Optional[int] = None

    ANY = -1


@dataclasses.dataclass
class NextStateElement(StateElement):
    state: StateElement
    next: StateElement
    within_ms: Optional[int] = None


@dataclasses.dataclass
class EveryStateElement(StateElement):
    state: StateElement
    within_ms: Optional[int] = None


class LogicalType(enum.Enum):
    AND = "and"
    OR = "or"


@dataclasses.dataclass
class LogicalStateElement(StateElement):
    left: StateElement
    type: LogicalType
    right: StateElement
    within_ms: Optional[int] = None


class StateStreamType(enum.Enum):
    PATTERN = "pattern"
    SEQUENCE = "sequence"


@dataclasses.dataclass
class StateInputStream(SourceLocated):
    type: StateStreamType
    state: StateElement
    within_ms: Optional[int] = None


InputStream = Union[SingleInputStream, JoinInputStream, StateInputStream]


def iter_state_streams(state: StateElement):
    """Yield every SingleInputStream referenced by a pattern/sequence state
    tree, in source order (used by the runtime for pre-validation and by the
    semantic analyzer for scope construction)."""
    if isinstance(state, CountStateElement):
        yield from iter_state_streams(state.stream)
    elif isinstance(state, StreamStateElement):
        yield state.stream
    elif isinstance(state, NextStateElement):
        yield from iter_state_streams(state.state)
        yield from iter_state_streams(state.next)
    elif isinstance(state, EveryStateElement):
        yield from iter_state_streams(state.state)
    elif isinstance(state, LogicalStateElement):
        yield from iter_state_streams(state.left)
        yield from iter_state_streams(state.right)


# ---------------------------------------------------------------------------
# selector
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OutputAttribute(SourceLocated):
    rename: Optional[str]
    expression: Expression

    @property
    def name(self) -> str:
        if self.rename:
            return self.rename
        if isinstance(self.expression, Variable):
            return self.expression.attribute
        raise ValueError(f"unnamed non-variable projection: {self.expression}")


class OrderDir(enum.Enum):
    ASC = "asc"
    DESC = "desc"


@dataclasses.dataclass
class OrderByAttribute:
    variable: Variable
    order: OrderDir = OrderDir.ASC


@dataclasses.dataclass
class Selector(SourceLocated):
    selection_list: list[OutputAttribute] = dataclasses.field(default_factory=list)
    group_by: list[Variable] = dataclasses.field(default_factory=list)
    having: Optional[Expression] = None
    order_by: list[OrderByAttribute] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    select_all: bool = False  # `select *`

    def select(self, rename: Optional[str], e: Expression) -> "Selector":
        self.selection_list.append(OutputAttribute(rename, e))
        return self


# ---------------------------------------------------------------------------
# output streams & rate limiting
# ---------------------------------------------------------------------------


class OutputEventsFor(enum.Enum):
    CURRENT = "current events"
    EXPIRED = "expired events"
    ALL = "all events"


@dataclasses.dataclass
class OutputStream(SourceLocated):
    output_events: OutputEventsFor = OutputEventsFor.CURRENT


@dataclasses.dataclass
class InsertIntoStream(OutputStream):
    target: str = ""
    is_inner: bool = False
    is_fault: bool = False


@dataclasses.dataclass
class ReturnStream(OutputStream):
    pass


@dataclasses.dataclass
class DeleteStream(OutputStream):
    target: str = ""
    on: Optional[Expression] = None


@dataclasses.dataclass
class UpdateSetAttribute:
    table_variable: Variable
    expression: Expression


@dataclasses.dataclass
class UpdateStream(OutputStream):
    target: str = ""
    on: Optional[Expression] = None
    set_attributes: Optional[list[UpdateSetAttribute]] = None


@dataclasses.dataclass
class UpdateOrInsertStream(OutputStream):
    target: str = ""
    on: Optional[Expression] = None
    set_attributes: Optional[list[UpdateSetAttribute]] = None


class OutputRateType(enum.Enum):
    ALL = "all"
    FIRST = "first"
    LAST = "last"


@dataclasses.dataclass
class EventOutputRate:
    events: int
    type: OutputRateType = OutputRateType.ALL


@dataclasses.dataclass
class TimeOutputRate:
    millis: int
    type: OutputRateType = OutputRateType.ALL


@dataclasses.dataclass
class SnapshotOutputRate:
    millis: int


OutputRate = Union[EventOutputRate, TimeOutputRate, SnapshotOutputRate, None]


# ---------------------------------------------------------------------------
# query / partition / store query
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Query(SourceLocated):
    input_stream: InputStream = None
    selector: Selector = dataclasses.field(default_factory=Selector)
    output_stream: OutputStream = dataclasses.field(default_factory=ReturnStream)
    output_rate: OutputRate = None
    annotations: list[Annotation] = dataclasses.field(default_factory=list)

    @staticmethod
    def query() -> "Query":
        return Query()

    def from_(self, s: InputStream) -> "Query":
        self.input_stream = s
        return self

    def select(self, sel: Selector) -> "Query":
        self.selector = sel
        return self

    def insert_into(self, target: str, for_: OutputEventsFor = OutputEventsFor.CURRENT) -> "Query":
        self.output_stream = InsertIntoStream(output_events=for_, target=target)
        return self

    def insert_into_fault(
        self, target: str, for_: OutputEventsFor = OutputEventsFor.CURRENT
    ) -> "Query":
        """Programmatic `insert into !target` (target must declare
        @OnError(action='STREAM'))."""
        self.output_stream = InsertIntoStream(
            output_events=for_, target="!" + target, is_fault=True
        )
        return self


@dataclasses.dataclass
class ValuePartitionType(SourceLocated):
    stream_id: str
    expression: Expression


@dataclasses.dataclass
class RangePartitionProperty:
    partition_key: str
    condition: Expression


@dataclasses.dataclass
class RangePartitionType(SourceLocated):
    stream_id: str
    ranges: list[RangePartitionProperty]


@dataclasses.dataclass
class Partition(SourceLocated):
    partition_types: list[Union[ValuePartitionType, RangePartitionType]] = dataclasses.field(
        default_factory=list
    )
    queries: list[Query] = dataclasses.field(default_factory=list)
    annotations: list[Annotation] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class InputStore(SourceLocated):
    store_id: str
    alias: Optional[str] = None
    on: Optional[Expression] = None
    within: Optional[tuple[Expression, Optional[Expression]]] = None
    per: Optional[Expression] = None


@dataclasses.dataclass
class StoreQuery(SourceLocated):
    """One-shot pull query (reference: execution/query/StoreQuery.java)."""

    input_store: Optional[InputStore] = None
    selector: Selector = dataclasses.field(default_factory=Selector)
    # for store insert/update/delete forms
    output_stream: Optional[OutputStream] = None
    select_expression_rows: Optional[list] = None


def assign_execution_ids(app) -> list:
    """THE query/partition id assignment for an app, shared by the runtime
    (app_runtime.py + partition.py), the semantic analyzer (analysis/
    analyzer.py), and the EXPLAIN plan builder (observability/explain.py)
    so the three can never drift: explicit @info names are reserved
    app-wide (including names on queries inside partitions), unnamed
    top-level queries take the next free `queryN`, partitions number
    `partitionM` in source order, and their unnamed inner queries take
    `{pid}_queryK` where K counts ALL inner queries (named ones included).

    Returns source-ordered entries:
      ("query", qid, query)
      ("partition", pid, partition, [(qid, query), ...])
    """
    from siddhi_tpu.query_api.annotation import find_annotation

    def info_name(q):
        info = find_annotation(q.annotations, "info")
        return info.element("name") if info else None

    taken = set()
    for elem in app.execution_elements:
        inner = (
            [elem] if isinstance(elem, Query)
            else list(getattr(elem, "queries", []) or [])
        )
        for q in inner:
            name = info_name(q)
            if name:
                taken.add(name)
    out: list = []
    unnamed = 0
    n_partitions = 0
    for elem in app.execution_elements:
        if isinstance(elem, Query):
            qid = info_name(elem)
            if not qid:
                while f"query{unnamed}" in taken:
                    unnamed += 1
                qid = f"query{unnamed}"
                unnamed += 1
            out.append(("query", qid, elem))
        elif isinstance(elem, Partition):
            pid = f"partition{n_partitions}"
            n_partitions += 1
            inner_ids = []
            p_unnamed = 0
            for q in elem.queries:
                qid = info_name(q) or f"{pid}_query{p_unnamed}"
                p_unnamed += 1
                inner_ids.append((qid, q))
            out.append(("partition", pid, elem, inner_ids))
    return out
