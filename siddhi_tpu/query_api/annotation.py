"""Annotations — the query-language flag system.

Reference: siddhi-query-api .../annotation/Annotation.java; consumed per
SURVEY.md §5 (config/flag system): @app:name, @async, @config, @source/@sink/@map,
@primaryKey/@index, @info, ...
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Annotation:
    name: str
    # Ordered (key, value) pairs; key None for positional elements
    # like @primaryKey('a','b').
    elements: list[tuple[Optional[str], str]] = dataclasses.field(default_factory=list)
    annotations: list["Annotation"] = dataclasses.field(default_factory=list)

    def element(self, key: Optional[str] = None, default: Optional[str] = None):
        for k, v in self.elements:
            if k == key:
                return v
        if key is None and len(self.elements) == 1:
            return self.elements[0][1]
        return default

    def positional(self) -> list[str]:
        return [v for k, v in self.elements if k is None]


def find_annotation(annotations: list[Annotation], name: str) -> Optional[Annotation]:
    low = name.lower()
    for a in annotations:
        if a.name.lower() == low:
            return a
    return None


def find_all(annotations: list[Annotation], name: str) -> list[Annotation]:
    low = name.lower()
    return [a for a in annotations if a.name.lower() == low]
