"""Expression AST.

Reference: siddhi-query-api .../expression/Expression.java tree — math, conditions,
constants, variables, attribute functions. Built either programmatically or by the
SiddhiQL parser; compiled to vectorized jax functions by
siddhi_tpu.core.executor (the analog of core/util/parser/ExpressionParser.java).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional

from siddhi_tpu.core.types import AttrType


class Expression:
    """AST base class. Builder helpers (`value`, `var`) are module functions,
    mirroring the reference's `Expression.value()/variable()` statics.

    `line`/`col` carry the 1-based source position of the node's first token
    when the node came out of the SiddhiQL parser (None for programmatically
    built ASTs) — semantic diagnostics (`siddhi_tpu.analysis`) report them."""

    line: Optional[int] = None
    col: Optional[int] = None


def value(v: Any, type_: Optional[AttrType] = None) -> "Constant":
    if type_ is None:
        if isinstance(v, bool):
            type_ = AttrType.BOOL
        elif isinstance(v, int):
            type_ = AttrType.INT if -(2**31) <= v < 2**31 else AttrType.LONG
        elif isinstance(v, float):
            type_ = AttrType.DOUBLE
        elif isinstance(v, str):
            type_ = AttrType.STRING
        else:
            raise TypeError(f"cannot infer constant type of {v!r}")
    return Constant(v, type_)


def var(name: str, stream_id: Optional[str] = None) -> "Variable":
    return Variable(name, stream_id=stream_id)


@dataclasses.dataclass
class Constant(Expression):
    value: Any
    type: AttrType


@dataclasses.dataclass
class TimeConstant(Constant):
    """A time literal like `1 min` — LONG milliseconds (reference: expression/constant/TimeConstant.java)."""

    def __init__(self, millis: int):
        super().__init__(millis, AttrType.LONG)


@dataclasses.dataclass
class Variable(Expression):
    """Attribute reference, optionally qualified by stream alias / pattern index.

    `stream_index` mirrors the reference's e1[0]/e1[last] indexing into
    count-state collected events (reference: expression/Variable.java).
    """

    attribute: str
    stream_id: Optional[str] = None
    stream_index: Optional[int] = None  # LAST == -1
    is_inner: bool = False
    is_fault: bool = False

    LAST = -1


@dataclasses.dataclass
class _Binary(Expression):
    left: Expression
    right: Expression


class Add(_Binary):
    pass


class Subtract(_Binary):
    pass


class Multiply(_Binary):
    pass


class Divide(_Binary):
    pass


class Mod(_Binary):
    pass


class CompareOp(enum.Enum):
    LT = "<"
    LE = "<="
    GT = ">"
    GE = ">="
    EQ = "=="
    NEQ = "!="


@dataclasses.dataclass
class Compare(Expression):
    left: Expression
    op: CompareOp
    right: Expression


@dataclasses.dataclass
class And(Expression):
    left: Expression
    right: Expression


@dataclasses.dataclass
class Or(Expression):
    left: Expression
    right: Expression


@dataclasses.dataclass
class Not(Expression):
    expression: Expression


@dataclasses.dataclass
class IsNull(Expression):
    expression: Optional[Expression] = None
    # stream-null form: `S1 is null` inside patterns
    stream_id: Optional[str] = None
    stream_index: Optional[int] = None


@dataclasses.dataclass
class In(Expression):
    """`<condition> in TableName` (reference: expression/condition/In.java)."""

    expression: Expression
    source_id: str


@dataclasses.dataclass
class AttributeFunction(Expression):
    """`ns:name(arg, ...)` — built-in or extension function / aggregator."""

    namespace: Optional[str]
    name: str
    parameters: list[Expression]
