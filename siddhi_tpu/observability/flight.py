"""Per-junction flight recorder: a bounded ring of the last N events.

The black-box analog for stream debugging (Hazelcast Jet's tail-debugging
argument, PAPERS.md): when a dispatch fails, the question is never just
"what failed" but "what flowed through immediately before". Each opted-in
junction keeps a fixed columnar arena of the last N events (timestamp +
physical attribute values) that is:

* written on every publish with NO per-event Python allocation — the arena
  is preallocated once and rows are copied in with (at most two) slice
  assignments per batch;
* decoded to host rows only on demand (`events()`), via the same vectorized
  `rows_from_arrays` path the junction's own host decode uses;
* dumped automatically into the error-store entry when a dispatch failure
  is captured by `@OnError(action='STORE')`, and readable on demand via
  `runtime.flight_record(stream_id)` or the `/flight` endpoint.

Enabled per stream with `@flightRecorder(size='256')` or process-wide with
`SIDDHI_TPU_FLIGHT=N`. When not enabled the junction's hot path pays one
`is None` check (the same contract as the statistics wiring).

Cost when ENABLED: the fused send_columns path records from the host-side
wire columns (free), but the per-batch publish path must read the device
batch back (`np.asarray` per lane) — one d2h sync per publish. That is the
price of the black box: negligible on CPU, a real per-batch readback on
accelerators, and on transfer-degraded relay backends
(utils/backend.transfer_degrades_dispatch) the first such read permanently
slows dispatch — there, prefer arming only ingress streams fed by
columnar sends, or accept the relay's synchronous mode while debugging.
"""

from __future__ import annotations

import os
import threading

import numpy as np

DEFAULT_FLIGHT_SIZE = 256
_MAX_FLIGHT_SIZE = 65536

FLIGHT_ENV = "SIDDHI_TPU_FLIGHT"


def flight_env_size() -> int:
    """Process-wide flight-recorder override: N > 0 enables a ring of N
    events on EVERY junction; 0/unset defers to the stream's
    `@flightRecorder` annotation. A malformed value warns LOUDLY instead
    of silently disarming — an operator who believes the black box is
    armed must not discover otherwise at the next crash; oversized values
    clamp to the maximum."""
    import logging

    v = os.environ.get(FLIGHT_ENV, "").strip()
    if not v:
        return 0
    try:
        n = int(v)
    except ValueError:
        logging.getLogger(__name__).warning(
            "%s=%r is not an integer — the flight recorder is NOT armed",
            FLIGHT_ENV, v,
        )
        return 0
    if n < 0:
        logging.getLogger(__name__).warning(
            "%s=%d is negative — the flight recorder is NOT armed",
            FLIGHT_ENV, n,
        )
        return 0
    if n > _MAX_FLIGHT_SIZE:
        logging.getLogger(__name__).warning(
            "%s=%d exceeds the maximum; clamping the ring to %d events",
            FLIGHT_ENV, n, _MAX_FLIGHT_SIZE,
        )
        return _MAX_FLIGHT_SIZE
    return n


def iter_flight_annotation_problems(ann):
    """Yield one message per malformed `@flightRecorder` element — THE
    validation rules, shared by the runtime resolver (raises on the first)
    and the analyzer's SA114 diagnostics (reports them all)."""
    for k, v in ann.elements:
        if k == "size" or (k is None and len(ann.elements) == 1):
            try:
                ok = 1 <= int(v) <= _MAX_FLIGHT_SIZE
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@flightRecorder size '{v}' must be an integer in "
                    f"1..{_MAX_FLIGHT_SIZE}"
                )
        else:
            yield (
                f"unknown @flightRecorder option '{k if k is not None else v}'"
                " (expected size)"
            )


def resolve_flight_annotation(ann) -> int:
    """Ring size for one stream from its `@flightRecorder` annotation (or
    None), before the SIDDHI_TPU_FLIGHT env override; 0 = not enabled.
    Raises SiddhiAppCreationError on malformed options — the runtime analog
    of the analyzer's SA114 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    size = 0
    if ann is not None:
        for problem in iter_flight_annotation_problems(ann):
            raise SiddhiAppCreationError(problem)
        size = int(
            ann.element("size") or ann.element(None) or DEFAULT_FLIGHT_SIZE
        )
    env = flight_env_size()
    return max(size, env)


class FlightRecorder:
    """Fixed columnar arena of the last `size` events through one junction.

    The arena (one [size] array per attribute + ts/kind lanes) is allocated
    once; `record_*` copies the batch tail in circularly, so steady-state
    recording does zero per-event allocation. Thread-safe: publishes arrive
    from sender/async-drain/scheduler threads while `events()` reads.
    """

    def __init__(self, schema, interner, size: int = DEFAULT_FLIGHT_SIZE):
        from siddhi_tpu.core.types import PHYSICAL_DTYPE

        if size <= 0:
            raise ValueError("flight recorder size must be positive")
        self.schema = schema
        self.interner = interner
        self.size = int(size)
        self._ts = np.zeros((self.size,), np.int64)
        self._kind = np.zeros((self.size,), np.int8)
        self._cols = {
            n: np.zeros((self.size,), np.dtype(PHYSICAL_DTYPE[t]))
            for n, t in schema.attrs
        }
        self._head = 0  # next write slot
        self._count = 0  # total events ever recorded
        self._lock = threading.Lock()

    # ---- recording -------------------------------------------------------

    def _write(self, ts, kind, cols, n: int) -> None:
        """Copy the last min(n, size) rows into the ring (caller holds the
        lock); `cols` maps attr -> [n] physical host array."""
        if n <= 0:
            return
        if n > self.size:  # only the tail can survive anyway
            ts = ts[n - self.size:]
            kind = None if kind is None else kind[n - self.size:]
            cols = {k: v[n - self.size:] for k, v in cols.items()}
            self._count += n - self.size
            n = self.size
        h = self._head
        first = min(n, self.size - h)
        dsts = [(h, 0, first)]
        if first < n:
            dsts.append((0, first, n))
        for dst, lo, hi in dsts:
            m = hi - lo
            self._ts[dst:dst + m] = ts[lo:hi]
            if kind is None:
                self._kind[dst:dst + m] = 0
            else:
                self._kind[dst:dst + m] = kind[lo:hi]
            for name, arena in self._cols.items():
                arena[dst:dst + m] = cols[name][lo:hi]
        self._head = (h + n) % self.size
        self._count += n

    def record_batch(self, batch) -> None:
        """Record a device batch's valid rows (the per-batch publish path)."""
        valid = np.asarray(batch.valid)
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return
        ts = np.asarray(batch.ts)[idx]
        kind = np.asarray(batch.kind)[idx]
        cols = {n: np.asarray(c)[idx] for n, c in batch.cols.items()}
        with self._lock:
            self._write(ts, kind, cols, idx.size)

    def record_columns(self, timestamps, cols, n: int) -> None:
        """Record host columnar rows (the fused-ingest path: all rows are
        valid CURRENT events and the arrays never touched the device)."""
        if n <= 0:
            return
        ts = np.asarray(timestamps)[:n]
        host = {name: np.asarray(cols[name])[:n] for name in self._cols}
        with self._lock:
            self._write(ts, None, host, n)

    # ---- reading ---------------------------------------------------------

    def events(self, limit: int | None = None) -> list[tuple[int, tuple]]:
        """Decode the recorded ring, oldest first, as (timestamp, data_tuple)
        pairs — the exact shape ErroneousEvent.events uses."""
        from siddhi_tpu.core.event import rows_from_arrays

        with self._lock:
            n = min(self._count, self.size)
            if n == 0:
                return []
            # ring order -> insertion order
            order = (np.arange(n) + (self._head - n)) % self.size
            ts = self._ts[order].copy()
            kind = self._kind[order].copy()
            cols = {name: a[order].copy() for name, a in self._cols.items()}
        if limit is not None and limit < n:
            ts, kind = ts[n - limit:], kind[n - limit:]
            cols = {k: v[n - limit:] for k, v in cols.items()}
            n = limit
        triples = rows_from_arrays(
            self.schema, ts, kind, cols, n, self.interner
        )
        return [(t, data) for t, _k, data in triples]

    def describe_state(self) -> dict:
        with self._lock:  # one atomic read: recorded/total/ts must agree
            n = min(self._count, self.size)
            total = self._count
            newest = int(self._ts[(self._head - 1) % self.size]) if n else None
            oldest = (
                int(self._ts[(self._head - n) % self.size]) if n else None
            )
        return {
            "size": self.size,
            "recorded": n,
            "total": total,
            "oldest_ts": oldest,
            "newest_ts": newest,
        }
