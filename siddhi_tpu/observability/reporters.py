"""Reporter SPI + exposition formats (console/log back-compat, JSON lines,
Prometheus text format).

Reference: util/statistics/metrics/SiddhiStatisticsManager.java:35-80 wires
Dropwizard Console/JMX reporters behind `@app:statistics(reporter=...)`;
here the SPI is a tiny `emit(report)` object so deployments can register
their own (`register_reporter`). The Prometheus reporter is pull-based: it
registers nothing periodic — `manager.serve_metrics(port)` serves the text
exposition for every app on the manager (see http_server.py).
"""

from __future__ import annotations

import json
import logging
from typing import Callable, Optional


class Reporter:
    """SPI: one `emit(report)` per interval; `close()` at shutdown."""

    def emit(self, report: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ConsoleReporter(Reporter):
    def emit(self, report: dict) -> None:
        print(f"[siddhi_tpu stats] {report}", flush=True)


class LogReporter(Reporter):
    def __init__(self, app_name: str) -> None:
        self._log = logging.getLogger(f"siddhi_tpu.statistics.{app_name}")

    def emit(self, report: dict) -> None:
        self._log.info("%s", report)


class JsonLinesReporter(Reporter):
    """Appends one JSON object per interval to `file` (default
    `<app>.metrics.jsonl` in the working directory)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, report: dict) -> None:
        self._fh.write(json.dumps(report, default=str) + "\n")
        self._fh.flush()

    def close(self) -> None:
        try:
            self._fh.close()
        except Exception:
            pass


# name -> factory(app_name, options) -> Reporter | None (None = pull-based /
# disabled: no periodic thread is started)
_REPORTERS: dict[str, Callable[[str, dict], Optional[Reporter]]] = {
    "console": lambda app, opts: ConsoleReporter(),
    "log": lambda app, opts: LogReporter(app),
    "jsonl": lambda app, opts: JsonLinesReporter(
        opts.get("file", f"{app}.metrics.jsonl")
    ),
    "none": lambda app, opts: None,
    # pull-based: the app runtime asks the manager to serve /metrics instead
    "prometheus": lambda app, opts: None,
}


def register_reporter(name: str, factory) -> None:
    """Plug a custom reporter: factory(app_name, options) -> Reporter."""
    _REPORTERS[name.lower()] = factory


def make_reporter(name: str, app_name: str, options: dict) -> Optional[Reporter]:
    factory = _REPORTERS.get(str(name).lower())
    if factory is None:
        logging.getLogger(__name__).warning(
            "unknown @app:statistics reporter '%s'; metrics are collected "
            "but not periodically reported (known: %s)",
            name, sorted(_REPORTERS),
        )
        return None
    return factory(app_name, options)


# ---------------------------------------------------------------------------
# Prometheus text exposition (format version 0.0.4)
# ---------------------------------------------------------------------------


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(**kv) -> str:
    inner = ",".join(
        f'{k}="{_esc(v)}"' for k, v in kv.items() if v is not None and v != ""
    )
    return "{" + inner + "}" if inner else ""


_FAMILIES = {
    "siddhi_events_total": ("counter", "Events published per component"),
    "siddhi_event_rate": (
        "gauge", "EWMA event rate in events/second (window label: 1m/5m)"),
    "siddhi_latency_ms": (
        "summary", "Processing latency quantiles per component (ms)"),
    "siddhi_buffered_events": (
        "gauge", "Queued depth of async ingress buffers"),
    "siddhi_errors_total": (
        "counter",
        "Failed dispatches/publishes per component "
        "(subscriber label: per-subscriber attribution)"),
    "siddhi_memory_bytes": (
        "gauge", "Device buffer bytes held by each component's carried state"),
    "siddhi_device_time_ms": (
        "summary",
        "Device-time budget per component (op label: step/fused_step/"
        "sync_stall) in ms"),
    "siddhi_h2d_bytes_total": (
        "counter", "Host-to-device wire bytes shipped per junction"),
    "siddhi_h2d_chunks_total": (
        "counter", "Host-to-device transfer chunks per junction"),
    "siddhi_h2d_events_total": (
        "counter",
        "Events shipped over the fused h2d wire per junction (the "
        "roofline denominator beside siddhi_h2d_bytes_total)"),
    "siddhi_h2d_logical_bytes_total": (
        "counter",
        "Full-width (logical) bytes the same events would have shipped "
        "with wire encoding off — the logical side of the encoded-vs-"
        "logical split (core/wire.py)"),
    "siddhi_wire_bytes_per_event": (
        "gauge",
        "Live ENCODED wire bytes per event over the fused h2d path — the "
        "roofline attribution the compact wire encodings shrink"),
    "siddhi_wire_logical_bytes_per_event": (
        "gauge",
        "Logical (full-width) bytes per event of the same stream — "
        "encoded/logical is the live wire reduction"),
    "siddhi_h2d_mb_s": (
        "gauge",
        "1-minute EWMA host-to-device wire throughput in MB/s per "
        "junction"),
    "siddhi_pipeline_occupancy": (
        "gauge",
        "Measured overlap ratio of the pipelined fused ingest (summed "
        "stage busy time / send wall time; 1.0 = fully serial stages)"),
    "siddhi_pipeline_depth": (
        "gauge",
        "Configured max in-flight chunks of the pipelined fused ingest "
        "(0 = pipeline disabled)"),
    "siddhi_shard_device_dispatches_total": (
        "counter",
        "Fused chunk dispatches per mesh device of a batch-sharded "
        "junction (parallel/shard.py; device label: mesh position)"),
    "siddhi_shard_device_events_total": (
        "counter",
        "Events routed to each mesh device of a batch-sharded junction"),
    "siddhi_shard_device_occupancy": (
        "gauge",
        "Per-device share of a batch-sharded junction's events, "
        "normalized so 1.0 = a perfectly even split across the mesh"),
    "siddhi_keyshard_device_keys": (
        "gauge",
        "Group keys owned by each mesh device of a key-sharded query "
        "(parallel/keyshard.py; device label: mesh position)"),
    "siddhi_keyshard_occupancy": (
        "gauge",
        "Per-device group-table fill of a key-sharded query "
        "(owned keys / group capacity)"),
    "siddhi_keyshard_skew": (
        "gauge",
        "Key-ownership skew of a key-sharded query: max per-device keys "
        "over the even-split mean (1.0 = perfectly balanced)"),
    "siddhi_watermark_ms": (
        "gauge",
        "Per-source-stream event-time watermark (max event time minus the "
        "@app:watermark bound) in ms since epoch"),
    "siddhi_watermark_lag_ms": (
        "gauge",
        "Watermark lag per source stream: newest event time seen minus the "
        "watermark (the reorder stage's live slack)"),
    "siddhi_reorder_buffered_events": (
        "gauge",
        "Rows held back by the @app:watermark bounded reorder stage, "
        "awaiting watermark advance"),
    "siddhi_late_events_total": (
        "counter",
        "Events behind the watermark at arrival, by outcome label: "
        "dropped (metered drop), streamed (diverted to !S), applied "
        "(aggregation bucket re-opened + correction row), expired "
        "(beyond allowed.lateness)"),
    "siddhi_lateness_ms": (
        "summary",
        "How far behind the watermark late events arrived, per stream (ms)"),
    "siddhi_traces_sampled_total": ("counter", "Traces sampled per app"),
    "siddhi_compiles_total": (
        "counter",
        "XLA compiles per program component by cause "
        "(observability/profiler.py taxonomy: first_compile, shape_change, "
        "tail_variant_k, full_width_rebuild, deliver_set_change, "
        "donation_mismatch) — alert on recompile storms"),
    "siddhi_calibration_error_ratio": (
        "gauge",
        "EWMA-smoothed live/predicted ratio per calibration pair "
        "(observability/calibration.py; 1.0 = the plan priced this "
        "component exactly; kind label: prediction kind)"),
    "siddhi_calibration_mispriced_total": (
        "counter",
        "Mispricing flags raised by the calibration ledger, by stable "
        "reason code (selectivity_off_4x, wire_full_width_fallback, "
        "unpredicted_recompile_cause, shared_state_refcount_collapsed)"),
    "siddhi_slo_burn_rate": (
        "gauge",
        "Multi-window SLO burn rate per objective (observability/slo.py; "
        "window label: fast/slow; 1.0 = consuming exactly the error "
        "budget)"),
}


def _summary_lines(out, family, app, component, summ, **extra) -> None:
    for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"),
                   ("0.999", "p999"), ("0.9999", "p9999")):
        out.append(
            f"{family}{_labels(app=app, component=component, quantile=q, **extra)}"
            f" {summ[key]}"
        )
    out.append(
        f"{family}_sum{_labels(app=app, component=component, **extra)} {summ['sum']}"
    )
    out.append(
        f"{family}_count{_labels(app=app, component=component, **extra)} {summ['count']}"
    )


def render_raw_family(name: str, ftype: str, help_text: str,
                      lines: list[str]) -> str:
    """One manager-owned exposition family from pre-rendered sample lines
    (supervisor/admission/churn/incident counters live outside the per-app
    statistics registries so they meter apps with statistics OFF too).
    Empty when there are no samples — absent families must not appear."""
    if not lines:
        return ""
    return (
        f"# HELP {name} {help_text}\n# TYPE {name} {ftype}\n"
        + "\n".join(lines) + "\n"
    )


def render_prometheus(reports: list[dict]) -> str:
    """Render the Prometheus text exposition for a list of `report()` dicts
    (one per app). Families are emitted once each with HELP/TYPE headers."""
    body: dict[str, list[str]] = {f: [] for f in _FAMILIES}
    for rep in reports:
        app = rep.get("app", "")
        for n, v in rep.get("throughput", {}).items():
            body["siddhi_events_total"].append(
                f"siddhi_events_total{_labels(app=app, component=n)} {v}"
            )
        for n, r in rep.get("rates", {}).items():
            for window, key in (("1m", "m1"), ("5m", "m5")):
                body["siddhi_event_rate"].append(
                    f"siddhi_event_rate{_labels(app=app, component=n, window=window)}"
                    f" {r[key]}"
                )
        for n, summ in rep.get("latency_ms", {}).items():
            _summary_lines(body["siddhi_latency_ms"], "siddhi_latency_ms",
                           app, n, summ)
        for n, v in rep.get("buffered", {}).items():
            body["siddhi_buffered_events"].append(
                f"siddhi_buffered_events{_labels(app=app, component=n)} {v}"
            )
        for n, ent in rep.get("errors_detail", {}).items():
            body["siddhi_errors_total"].append(
                "siddhi_errors_total"
                f"{_labels(app=app, component=ent['component'], subscriber=ent.get('subscriber'))}"
                f" {ent['count']}"
            )
        for n, v in rep.get("memory_bytes", {}).items():
            body["siddhi_memory_bytes"].append(
                f"siddhi_memory_bytes{_labels(app=app, component=n)} {v}"
            )
        dev = rep.get("device", {})
        for n, ent in dev.get("time_ms", {}).items():
            _summary_lines(
                body["siddhi_device_time_ms"], "siddhi_device_time_ms",
                app, ent["component"], ent["summary"], op=ent["op"],
            )
        for n, ent in dev.get("counters", {}).items():
            fam = f"siddhi_{ent['op']}_total"
            if fam in body:
                body[fam].append(
                    f"{fam}{_labels(app=app, component=ent['component'])}"
                    f" {ent['count']}"
                )
        for n, ent in rep.get("roofline", {}).items():
            bpe = ent.get("wire_bytes_per_event")
            if bpe is not None:
                body["siddhi_wire_bytes_per_event"].append(
                    f"siddhi_wire_bytes_per_event{_labels(app=app, component=n)}"
                    f" {bpe}"
                )
            lpe = ent.get("wire_logical_bytes_per_event")
            if lpe is not None:
                body["siddhi_wire_logical_bytes_per_event"].append(
                    "siddhi_wire_logical_bytes_per_event"
                    f"{_labels(app=app, component=n)} {lpe}"
                )
            body["siddhi_h2d_mb_s"].append(
                f"siddhi_h2d_mb_s{_labels(app=app, component=n)}"
                f" {ent.get('h2d_mb_s_1m', 0)}"
            )
        for n, ent in rep.get("shard", {}).items():
            occ = ent.get("occupancy", [])
            for d, v in enumerate(ent.get("per_device_dispatches", [])):
                body["siddhi_shard_device_dispatches_total"].append(
                    "siddhi_shard_device_dispatches_total"
                    f"{_labels(app=app, component=n, device=str(d))} {v}"
                )
            for d, v in enumerate(ent.get("per_device_events", [])):
                body["siddhi_shard_device_events_total"].append(
                    "siddhi_shard_device_events_total"
                    f"{_labels(app=app, component=n, device=str(d))} {v}"
                )
                if d < len(occ):
                    body["siddhi_shard_device_occupancy"].append(
                        "siddhi_shard_device_occupancy"
                        f"{_labels(app=app, component=n, device=str(d))}"
                        f" {occ[d]}"
                    )
            # key-sharded query entries (parallel/keyshard.py) carry
            # per_device_keys instead of dispatch counters
            kocc = ent.get("occupancy", []) if "per_device_keys" in ent else []
            for d, v in enumerate(ent.get("per_device_keys", [])):
                body["siddhi_keyshard_device_keys"].append(
                    "siddhi_keyshard_device_keys"
                    f"{_labels(app=app, component=n, device=str(d))} {v}"
                )
                if d < len(kocc):
                    body["siddhi_keyshard_occupancy"].append(
                        "siddhi_keyshard_occupancy"
                        f"{_labels(app=app, component=n, device=str(d))}"
                        f" {kocc[d]}"
                    )
            if "skew" in ent and "per_device_keys" in ent:
                body["siddhi_keyshard_skew"].append(
                    f"siddhi_keyshard_skew{_labels(app=app, component=n)}"
                    f" {ent['skew']}"
                )
        for n, ent in rep.get("pipeline", {}).items():
            body["siddhi_pipeline_occupancy"].append(
                f"siddhi_pipeline_occupancy{_labels(app=app, component=n)}"
                f" {ent['occupancy']}"
            )
            body["siddhi_pipeline_depth"].append(
                f"siddhi_pipeline_depth{_labels(app=app, component=n)}"
                f" {ent['depth']}"
            )
        for sid, ent in rep.get("watermark", {}).get("streams", {}).items():
            if ent.get("watermark_ms") is not None:
                body["siddhi_watermark_ms"].append(
                    f"siddhi_watermark_ms{_labels(app=app, stream=sid)}"
                    f" {ent['watermark_ms']}"
                )
            if ent.get("lag_ms") is not None:
                body["siddhi_watermark_lag_ms"].append(
                    f"siddhi_watermark_lag_ms{_labels(app=app, stream=sid)}"
                    f" {ent['lag_ms']}"
                )
            body["siddhi_reorder_buffered_events"].append(
                "siddhi_reorder_buffered_events"
                f"{_labels(app=app, stream=sid)} {ent.get('buffered', 0)}"
            )
            for outcome in ("dropped", "streamed", "applied", "expired"):
                body["siddhi_late_events_total"].append(
                    "siddhi_late_events_total"
                    f"{_labels(app=app, stream=sid, outcome=outcome)}"
                    f" {ent.get(outcome, 0)}"
                )
            summ = ent.get("lateness_ms")
            if summ and summ.get("count"):
                _summary_lines(
                    body["siddhi_lateness_ms"], "siddhi_lateness_ms",
                    app, None, summ, stream=sid,
                )
        for n, ent in rep.get("compiles", {}).items():
            for cause, v in sorted(ent.get("causes", {}).items()):
                body["siddhi_compiles_total"].append(
                    "siddhi_compiles_total"
                    f"{_labels(app=app, component=n, cause=cause)} {v}"
                )
        calib = rep.get("calibration", {})
        for ent in calib.get("pairs", []):
            body["siddhi_calibration_error_ratio"].append(
                "siddhi_calibration_error_ratio"
                f"{_labels(app=app, kind=ent['kind'], component=ent['component'])}"
                f" {ent['ratio']}"
            )
        for ent in calib.get("mispriced", []):
            body["siddhi_calibration_mispriced_total"].append(
                "siddhi_calibration_mispriced_total"
                f"{_labels(app=app, reason=ent['reason'], component=ent['component'])}"
                f" {ent['count']}"
            )
        for ent in rep.get("slo", {}).get("burn", []):
            body["siddhi_slo_burn_rate"].append(
                "siddhi_slo_burn_rate"
                f"{_labels(app=app, objective=ent['objective'], component=ent['component'], window=ent['window'])}"
                f" {ent['burn_rate']}"
            )
        body["siddhi_traces_sampled_total"].append(
            "siddhi_traces_sampled_total"
            f"{_labels(app=app)} {rep.get('traces_sampled', 0)}"
        )
    out: list[str] = []
    for family, lines in body.items():
        if not lines:
            continue
        ftype, help_ = _FAMILIES[family]
        out.append(f"# HELP {family} {help_}")
        out.append(f"# TYPE {family} {ftype}")
        out.extend(lines)
    return "\n".join(out) + "\n"
