"""State introspection: aggregate `describe_state()` hooks into one status.

Every stateful runtime component grows a cheap, pull-only `describe_state()
-> dict` (junction queue depth and subscriber health, window type/fill/
capacity and oldest/newest timestamps, NFA active-instance counts per state
and within-clause deadlines, aggregation bucket counts and watermarks,
table row counts and index info, ingest-pipeline depth/occupancy/slots in
flight, error-store depth). `SiddhiAppRuntime.snapshot_status()` walks
them; `SiddhiManager.snapshot_status()` adds the shared error store; the
`MetricsServer` serves both as `/status` (human text) and `/status.json`.

The hooks are PULL-only: nothing is collected, sampled, or scheduled until
a caller asks, so the hot dispatch path cost of the whole subsystem is
zero. Reads that touch device state (window fills, table occupancy, NFA
token pulls) do one host transfer per component — an on-demand operator
action, not a steady cost. EXCEPT on transfer-degraded relay backends
(utils/backend.transfer_degrades_dispatch), where the FIRST device->host
read from any thread permanently degrades every later dispatch: there the
device-touching fields degrade to None (`device_reads_ok()`), and an
operator who accepts the cost opts back in with
SIDDHI_TPU_STATUS_DEVICE=1.
"""

from __future__ import annotations

import os


def device_reads_ok() -> bool:
    """May an introspection pull read device state back to the host?

    False only on transfer-degraded relay backends (where one d2h read
    permanently poisons dispatch latency) without the explicit
    SIDDHI_TPU_STATUS_DEVICE=1 opt-in. The component describe_state()
    implementations consult this and report None for device-derived fields
    (window fill, table rows, NFA instance counts, aggregation buckets)
    instead of paying the read.
    """
    if os.environ.get("SIDDHI_TPU_STATUS_DEVICE", "").strip() == "1":
        return True
    from siddhi_tpu.utils.backend import transfer_degrades_dispatch

    return not transfer_degrades_dispatch()


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def _render_component(lines: list, name: str, d: dict, indent: str) -> None:
    flat = {k: v for k, v in d.items() if not isinstance(v, dict)}
    nested = {k: v for k, v in d.items() if isinstance(v, dict)}
    body = ", ".join(f"{k}={_fmt(v)}" for k, v in flat.items())
    lines.append(f"{indent}{name}: {body}" if body else f"{indent}{name}:")
    for k, sub in nested.items():
        _render_component(lines, k, sub, indent + "  ")


def render_status(status: dict) -> str:
    """Human-readable rendering of a manager/runtime status snapshot (the
    `/status` endpoint body)."""
    lines: list[str] = []
    apps = status.get("apps")
    if apps is None:  # a single runtime's snapshot
        apps = {status.get("app", "app"): status}
    for name, app in apps.items():
        running = "running" if app.get("running") else "stopped"
        lines.append(f"app {name} [{running}]")
        for section in (
            "streams", "queries", "windows", "tables", "aggregations",
        ):
            comps = app.get(section) or {}
            if not comps:
                continue
            lines.append(f"  {section}:")
            for cid, d in comps.items():
                _render_component(lines, cid, d, "    ")
        for extra in ("shard", "selfmon", "admission", "autopersist", "health"):
            d = app.get(extra)
            if d:
                _render_component(lines, extra, d, "  ")
    es = status.get("error_store")
    if es:
        _render_component(lines, "error_store", es, "")
    sup = status.get("supervisor")
    if sup:
        _render_component(lines, "supervisor", sup, "")
    return "\n".join(lines) + "\n"
