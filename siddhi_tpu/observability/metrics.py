"""Metric primitives: log-bucketed histograms, EWMA rates, trackers.

Reference: util/statistics/metrics/* — the Dropwizard MetricRegistry's
Meter/Timer/Histogram trio (ThroughputTracker.java, LatencyTracker.java,
BufferedEventsTracker.java). The reference leans on Dropwizard's
ExponentiallyDecayingReservoir for quantiles; here the reservoir is an
HDR-style log-bucketed histogram (fixed ~3% relative error, O(1) record,
no sampling bias at the tail — Hazelcast Jet's "measure the 99.99th
percentile" argument is exactly about reservoir tail bias).

Every tracker takes an optional `gate` (any object with a boolean
`.enabled`) so `runtime.enable_stats(False)` stops collection with one
attribute check on the hot path — the same cost as the `is None` check
paths pay when statistics were never configured.
"""

from __future__ import annotations

import math
import threading
import time

_SUB_BITS = 5
_SUB = 1 << _SUB_BITS  # 32 sub-buckets per octave -> <= ~3% relative error
_NBUCKETS = _SUB * 60  # covers the full non-negative int64 range (ns)


class _AlwaysOn:
    enabled = True


_ALWAYS_ON = _AlwaysOn()


class LogHistogram:
    """HDR-style log-bucketed histogram over non-negative integers.

    Values < 64 land in exact unit buckets; beyond that, bucket width
    doubles every octave with `_SUB` sub-buckets, so any recorded value is
    reconstructed within 1/_SUB (~3%) relative error. Recording is O(1);
    quantile reads scan the (tiny, fixed) bucket array.
    """

    __slots__ = ("counts", "count", "total", "min", "max", "_lock")

    def __init__(self) -> None:
        self.counts = [0] * _NBUCKETS
        self.count = 0
        self.total = 0
        self.min = None
        self.max = 0
        self._lock = threading.Lock()

    @staticmethod
    def _index(v: int) -> int:
        shift = v.bit_length() - _SUB_BITS - 1
        if shift <= 0:
            return v
        return shift * _SUB + (v >> shift)

    @staticmethod
    def _bucket_mid(i: int) -> float:
        if i < 2 * _SUB:
            return float(i)
        shift = i // _SUB - 1
        sub = i - shift * _SUB
        return float((sub << shift) + (1 << shift) * 0.5)

    def record(self, v) -> None:
        v = int(v)
        if v < 0:
            v = 0
        i = self._index(v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def quantile(self, q: float):
        return self.quantiles([q])[0]

    def quantiles(self, qs) -> list:
        """One pass over the buckets for many quantiles (each result is the
        midpoint of the bucket holding the q-th ranked sample)."""
        with self._lock:
            n = self.count
            if n == 0:
                return [0.0 for _ in qs]
            order = sorted(range(len(qs)), key=lambda i: qs[i])
            targets = [max(1, math.ceil(qs[i] * n)) for i in order]
            out: list = [0.0] * len(qs)
            acc = 0
            ti = 0
            for bi, c in enumerate(self.counts):
                if not c:
                    continue
                acc += c
                while ti < len(targets) and acc >= targets[ti]:
                    out[order[ti]] = self._bucket_mid(bi)
                    ti += 1
                if ti == len(targets):
                    break
            return out

    def count_over(self, v) -> int:
        """Samples recorded strictly above `v`, at bucket resolution: the
        bucket holding `v` itself counts as not-over (~3% relative slack,
        same contract as `quantiles`). Feeds SLO burn rates (slo.py), where
        "bad" = latency samples above the objective threshold."""
        v = int(v)
        if v < 0:
            v = 0
        i = self._index(v)
        with self._lock:
            return sum(self.counts[i + 1:])

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def snapshot_ms(self) -> dict:
        """Summary dict with nanosecond-recorded values scaled to ms."""
        p50, p95, p99, p999, p9999 = self.quantiles(
            [0.5, 0.95, 0.99, 0.999, 0.9999]
        )
        s = 1e6
        return {
            "count": self.count,
            "mean": round(self.mean / s, 4),
            "min": round((self.min or 0) / s, 4),
            "max": round(self.max / s, 4),
            "p50": round(p50 / s, 4),
            "p95": round(p95 / s, 4),
            "p99": round(p99 / s, 4),
            "p999": round(p999 / s, 4),
            # the extreme tail: with fewer than 10k samples this is the top
            # bucket (== max within ~3% rel err), which is still the honest
            # answer to "what did the worst chunk cost" (Hazelcast Jet's
            # measure-the-99.99th argument, PAPERS.md)
            "p9999": round(p9999 / s, 4),
            "sum": round(self.total / s, 3),
        }


_TICK_S = 5.0


class EWMA:
    """Exponentially-weighted moving average rate (events/second), ticked
    lazily on update/read (reference: Dropwizard Meter's 1m/5m EWMAs)."""

    __slots__ = ("_alpha", "_uncounted", "_rate", "_init", "_last")

    def __init__(self, window_s: float, now: float | None = None) -> None:
        self._alpha = 1.0 - math.exp(-_TICK_S / float(window_s))
        self._uncounted = 0
        self._rate = 0.0
        self._init = False
        self._last = time.monotonic() if now is None else now

    def update(self, n: int, now: float) -> None:
        self._tick(now)
        self._uncounted += n

    def _tick(self, now: float) -> None:
        ticks = int((now - self._last) // _TICK_S)
        if ticks <= 0:
            return
        inst = self._uncounted / _TICK_S
        self._uncounted = 0
        if not self._init:
            self._rate = inst
            self._init = True
        else:
            self._rate += self._alpha * (inst - self._rate)
        if ticks > 1:  # idle intervals decay toward zero in closed form
            self._rate *= (1.0 - self._alpha) ** (ticks - 1)
        self._last += ticks * _TICK_S

    def rate(self, now: float | None = None) -> float:
        self._tick(time.monotonic() if now is None else now)
        return self._rate


class ThroughputTracker:
    """Monotonic event counter + 1m/5m EWMA rates."""

    def __init__(self, name: str, gate=None):
        self.name = name
        self.count = 0
        self._gate = gate if gate is not None else _ALWAYS_ON
        self._lock = threading.Lock()
        now = time.monotonic()
        self._m1 = EWMA(60.0, now)
        self._m5 = EWMA(300.0, now)
        # set for per-subscriber error counters (Prometheus label)
        self.component: str | None = None
        self.subscriber: str | None = None

    def add(self, n: int = 1) -> None:
        if not self._gate.enabled:
            return
        now = time.monotonic()
        with self._lock:
            self.count += n
            self._m1.update(n, now)
            self._m5.update(n, now)

    @property
    def rate_1m(self) -> float:
        with self._lock:
            return self._m1.rate()

    @property
    def rate_5m(self) -> float:
        with self._lock:
            return self._m5.rate()


class LatencyTracker:
    """markIn/markOut around a processing chain, recording into a log-bucketed
    histogram (p50/p95/p99/p999 + mean, see `LogHistogram`).

    Nesting-safe for real: each thread keeps a STACK of open marks, so nested
    markIn/markOut pairs on one thread measure their own spans instead of the
    inner markIn overwriting the outer one, and a stray markOut with no open
    mark is ignored rather than double-counting a stale t0 (the pre-histogram
    implementation stored a single TLS `t0` and had both bugs).

    The enable gate is decided at markIn: a disabled markIn pushes a 0
    sentinel (markOut always pops exactly what markIn pushed), so toggling
    `enable_stats` mid-span can neither leak stack entries nor pair a stale
    t0 with the wrong markOut and record a garbage sample.
    """

    def __init__(self, name: str, gate=None):
        self.name = name
        self.hist = LogHistogram()
        self._gate = gate if gate is not None else _ALWAYS_ON
        self._tls = threading.local()

    def mark_in(self) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(
            time.perf_counter_ns() if self._gate.enabled else 0
        )

    def mark_out(self) -> None:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return  # stray mark_out: never double-count
        t0 = stack.pop()
        if t0 and self._gate.enabled:
            self.hist.record(time.perf_counter_ns() - t0)

    def time(self):
        """Context manager form: `with lt.time(): ...` (see `timed`)."""
        return _TimedSpan(self)

    def record_ns(self, dt_ns: int) -> None:
        """Direct recording for paths that measure their own interval (fused
        chunk dispatch, device-step timing)."""
        if not self._gate.enabled:
            return
        self.hist.record(dt_ns)

    # ---- back-compat surface of the pre-histogram LatencyTracker ----------

    @property
    def samples(self) -> int:
        return self.hist.count

    @property
    def total_ns(self) -> int:
        return self.hist.total

    @property
    def avg_ms(self) -> float:
        return self.hist.mean / 1e6

    def quantile_ms(self, q: float) -> float:
        return self.hist.quantile(q) / 1e6

    def summary_ms(self) -> dict:
        return self.hist.snapshot_ms()


class _TimedSpan:
    __slots__ = ("_lt",)

    def __init__(self, lt: LatencyTracker) -> None:
        self._lt = lt

    def __enter__(self):
        self._lt.mark_in()
        return self._lt

    def __exit__(self, *exc) -> None:
        self._lt.mark_out()


class _NullSpan:
    def __enter__(self):
        return None

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


def timed(tracker):
    """`with timed(lt): ...` — times the block against `lt`, exception-safe;
    a None tracker is a no-op (for the ubiquitous stats-off wiring)."""
    return _NULL_SPAN if tracker is None else _TimedSpan(tracker)


class BufferedEventsTracker:
    """Occupancy of async ingress rings (reference: BufferedEventsTracker on
    Disruptor rings, StreamJunction.java:334-345)."""

    def __init__(self, name: str, gate=None):
        self.name = name
        self.get_size = lambda: 0

    def register(self, fn) -> None:
        self.get_size = fn
