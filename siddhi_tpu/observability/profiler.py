"""Continuous profiler: JIT compile telemetry + per-chunk latency waterfalls.

Two collectors, both owned by the app's `StatisticsManager` (so the
registry's `enabled` flag is their gate — `enable_stats(False)` stops them
at one attribute check, the same contract as every tracker):

* `CompileTelemetry` — the engine's device programs are `jax.jit`-compiled
  per argument-shape signature, and a recompile mid-traffic is a silent
  multi-hundred-ms stall that the latency histograms attribute to the wrong
  place. Every profiled dispatch site reports its call wall time plus the
  program's jit-cache size before/after (`PjitFunction._cache_size()`, no
  device work); a cache-size growth IS a compile, and the cause taxonomy
  below names why it happened. Wall time is attributed to the compile only
  for compiling calls; non-compiling calls count as cache hits.

* `Profiler` — per-chunk stage waterfalls. The fused ingest path reports
  encode → h2d → dispatch → queue → device → readback → deliver spans per
  chunk (core/ingest.py + core/pipeline.py); the per-batch path reports the
  coarser encode → dispatch → device → readback breakdown via a
  thread-local active-chunk context (stream_junction.py send_columns +
  query_runtime.py). A bounded top-K ring keeps the SLOWEST chunks with
  their full breakdowns, so "what did the p99.99 chunk spend its time on"
  is answerable after the fact without logging every chunk.

Recompile-cause taxonomy (stable strings, documented in the README):

    first_compile       the program's first call (expected, once)
    shape_change        a batch/argument shape this program had not seen
                        (per-batch path: timer batches, downstream cap-64
                        re-publishes, @app:batch drift)
    tail_variant_k      fused ingest compiled a smaller-K tail variant of
                        the chunk program (core/ingest.py _chunk_K)
    full_width_rebuild  a value outgrew the sampled narrow wire and the
                        fused program was rebuilt full-width
    deliver_set_change  the set of endpoints with query callbacks changed,
                        forcing a deliver-mode rebuild
    donation_mismatch   a recompile at an ALREADY-SEEN signature: the only
                        way that happens is the carried state pytree
                        changing under the program (donated buffer dtype/
                        shape/sharding drift) — worth an alert, it means
                        every chunk may be paying it

Served as `/profile` on the MetricsServer (manager.profile_reports()) and
folded into `runtime.explain()` node annotations (observability/explain.py).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

CAUSE_FIRST = "first_compile"
CAUSE_SHAPE = "shape_change"
CAUSE_TAIL_K = "tail_variant_k"
CAUSE_FULL_WIDTH = "full_width_rebuild"
CAUSE_DELIVER_SET = "deliver_set_change"
CAUSE_DONATION = "donation_mismatch"

_RECENT_CAP = 32  # per-component ring of recent compile events


def jit_cache_size(prog) -> Optional[int]:
    """Entries in a jitted callable's trace/compile cache, or None when the
    backend object does not expose it (telemetry then falls back to the
    signature-set heuristic: first sighting of a signature = compile)."""
    try:
        return int(prog._cache_size())
    except Exception:
        return None


class _ComponentCompiles:
    """Per-component compile ledger (one per profiled program)."""

    __slots__ = (
        "compiles", "cache_hits", "wall_ms_total", "causes", "signatures",
        "last_cache_size", "last_prog_id", "recent",
    )

    def __init__(self) -> None:
        self.compiles = 0
        self.cache_hits = 0
        self.wall_ms_total = 0.0
        self.causes: dict[str, int] = {}
        self.signatures: set = set()
        self.last_cache_size = 0
        self.last_prog_id = 0  # id() of the jitted object last observed
        self.recent: list[dict] = []


class CompileTelemetry:
    """Compile ledger for every profiled jitted program in one app."""

    def __init__(self, gate) -> None:
        self._gate = gate
        self._lock = threading.Lock()
        self._components: dict[str, _ComponentCompiles] = {}

    def observe(
        self,
        component: str,
        prog,
        signature,
        wall_ns: int,
        cause_hint: Optional[str] = None,
    ) -> None:
        """Report one call of `prog` (already made): wall time + cache-size
        delta decide compile vs hit; `cause_hint` labels rebuild-driven
        compiles (fused ingest passes tail/rebuild hints). One gate check
        when statistics are disabled."""
        if not self._gate.enabled:
            return
        size = jit_cache_size(prog)
        with self._lock:
            ent = self._components.get(component)
            if ent is None:
                ent = self._components[component] = _ComponentCompiles()
            new_sig = signature not in ent.signatures
            ent.signatures.add(signature)
            if ent.last_prog_id != id(prog):
                # a REBUILT program (fused full-width/deliver-set rebuilds
                # swap the jit object) starts with an empty cache: comparing
                # its size against the old program's would count the rebuild
                # compile as a cache hit and drop its cause hint
                ent.last_prog_id = id(prog)
                ent.last_cache_size = 0
            if size is not None:
                compiled = size > ent.last_cache_size
                ent.last_cache_size = size
            else:
                compiled = new_sig  # fallback heuristic
            if not compiled:
                ent.cache_hits += 1
                return
            if cause_hint is not None and not (
                cause_hint == CAUSE_TAIL_K and ent.compiles == 0
            ):
                # rebuild hints always win; a tail hint on the program's
                # very first compile is just the first compile happening to
                # land on a short send
                cause = cause_hint
            elif ent.compiles == 0:
                cause = CAUSE_FIRST
            elif new_sig:
                cause = CAUSE_SHAPE
            else:
                cause = CAUSE_DONATION
            ent.compiles += 1
            wall_ms = round(wall_ns / 1e6, 3)
            ent.wall_ms_total += wall_ms
            ent.causes[cause] = ent.causes.get(cause, 0) + 1
            ent.recent.append({
                "cause": cause,
                "wall_ms": wall_ms,
                "signature": repr(signature),
                "at_ms": int(time.time() * 1000),
            })
            if len(ent.recent) > _RECENT_CAP:
                del ent.recent[0]

    def report(self) -> dict:
        """component -> {compiles, cache_hits, wall_ms_total, causes,
        signatures, recent[]} (recent: oldest first, bounded)."""
        with self._lock:
            return {
                name: {
                    "compiles": ent.compiles,
                    "cache_hits": ent.cache_hits,
                    "wall_ms_total": round(ent.wall_ms_total, 3),
                    "causes": dict(ent.causes),
                    "signatures": len(ent.signatures),
                    "recent": list(ent.recent),
                }
                for name, ent in self._components.items()
            }

    def component(self, name: str) -> Optional[dict]:
        """Combined ledger summary for a component and its sub-programs —
        `name` plus every `name[...]` entry (pattern per-stream steps, join
        sides each jit their own program). For explain annotations."""
        with self._lock:
            # "_" variants: fused groups compile mode-specific programs
            # under suffixed names (e.g. `...fusedgroup.0_deliver`) — same
            # logical component, so summaries and calibration pair them
            ents = [
                e for k, e in self._components.items()
                if k == name or k.startswith(name + "[")
                or k.startswith(name + "_")
            ]
            if not ents:
                return None
            causes: dict[str, int] = {}
            for e in ents:
                for c, n in e.causes.items():
                    causes[c] = causes.get(c, 0) + n
            return {
                "compiles": sum(e.compiles for e in ents),
                "cache_hits": sum(e.cache_hits for e in ents),
                "wall_ms_total": round(
                    sum(e.wall_ms_total for e in ents), 3
                ),
                "causes": causes,
            }


class StageWaterfall:
    """One chunk's stage breakdown. Stages accumulate in call order; the
    chunk's total is wall-clock begin→end (stages may nest/overlap — e.g.
    the per-batch 'device' span sits inside 'dispatch' — so the total is
    NOT the stage sum)."""

    __slots__ = (
        "stream", "seq", "events", "t0_ns", "total_ns", "stages", "t_mark",
    )

    def __init__(self, stream: str, seq: int, events: int) -> None:
        self.stream = stream
        self.seq = seq
        self.events = int(events)
        self.t0_ns = time.perf_counter_ns()
        self.total_ns = 0
        self.stages: dict[str, int] = {}
        self.t_mark = 0  # scratch timestamp (dispatch->drain queue span)

    def stage(self, name: str, ns: int) -> None:
        self.stages[name] = self.stages.get(name, 0) + int(ns)

    def to_dict(self) -> dict:
        return {
            "stream": self.stream,
            "seq": self.seq,
            "events": self.events,
            "total_ms": round(self.total_ns / 1e6, 3),
            "stages_ms": {
                k: round(v / 1e6, 3) for k, v in self.stages.items()
            },
        }


class Profiler:
    """Bounded top-K ring of the slowest chunks, with full stage
    breakdowns, plus chunk/event counters.

    `begin()` returns None when the gate is off — every downstream
    `wf.stage(...)` site is already behind an `if wf is not None` (or the
    thread-local equivalent), so a disabled profiler costs exactly one
    gate check per chunk.
    """

    def __init__(self, gate, top_k: int = 8) -> None:
        self._gate = gate
        self.top_k = int(top_k)
        self._lock = threading.Lock()
        self._seq = 0
        self.chunks = 0
        self.events = 0
        self._top: list[StageWaterfall] = []  # sorted slowest-first
        self._tls = threading.local()

    # ---- chunk lifecycle --------------------------------------------------

    def begin(self, stream: str, events: int) -> Optional[StageWaterfall]:
        if not self._gate.enabled:
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
        return StageWaterfall(stream, seq, events)

    def end(self, wf: Optional[StageWaterfall]) -> None:
        if wf is None or not self._gate.enabled:
            return
        wf.total_ns = time.perf_counter_ns() - wf.t0_ns
        with self._lock:
            self.chunks += 1
            self.events += wf.events
            top = self._top
            if len(top) < self.top_k:
                top.append(wf)
                top.sort(key=lambda w: -w.total_ns)
            elif wf.total_ns > top[-1].total_ns:
                top[-1] = wf
                top.sort(key=lambda w: -w.total_ns)

    # ---- thread-local context (per-batch path) ----------------------------

    def tls_begin(self, wf: Optional[StageWaterfall]) -> None:
        """Make `wf` the calling thread's active chunk so downstream
        components (query step, decode) can attribute sub-stages without
        plumbing the object through every call signature."""
        self._tls.wf = wf

    def tls_end(self) -> None:
        self._tls.wf = None

    def tls_stage(self, name: str, ns: int) -> None:
        wf = getattr(self._tls, "wf", None)
        if wf is not None:
            wf.stage(name, ns)

    # ---- reporting --------------------------------------------------------

    def report(self) -> dict:
        with self._lock:
            return {
                "chunks": self.chunks,
                "events": self.events,
                "slowest": [w.to_dict() for w in self._top],
            }
