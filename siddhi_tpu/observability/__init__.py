"""siddhi_tpu.observability — engine-wide metrics, exposition, and tracing.

Histogram metrics (log-bucketed p50/p95/p99/p999 + EWMA rates), a pluggable
reporter SPI with console/log/JSON-lines/Prometheus exposition, sampled
event tracing across junction -> query -> sink, and device-budget profiling
hooks (dispatch step time, h2d wire traffic, truth-sync stalls).

`siddhi_tpu.core.statistics` is a back-compat shim over this package.
"""

from siddhi_tpu.observability.metrics import (  # noqa: F401
    BufferedEventsTracker,
    EWMA,
    LatencyTracker,
    LogHistogram,
    ThroughputTracker,
    timed,
)
from siddhi_tpu.observability.registry import (  # noqa: F401
    JunctionDeviceStats,
    StatisticsManager,
)
from siddhi_tpu.observability.reporters import (  # noqa: F401
    ConsoleReporter,
    JsonLinesReporter,
    LogReporter,
    Reporter,
    register_reporter,
    render_prometheus,
)
from siddhi_tpu.observability.tracing import Tracer  # noqa: F401

__all__ = [
    "LogHistogram",
    "EWMA",
    "ThroughputTracker",
    "LatencyTracker",
    "BufferedEventsTracker",
    "StatisticsManager",
    "JunctionDeviceStats",
    "Reporter",
    "ConsoleReporter",
    "LogReporter",
    "JsonLinesReporter",
    "register_reporter",
    "render_prometheus",
    "timed",
    "Tracer",
]
