"""siddhi_tpu.observability — metrics, exposition, tracing, introspection.

Histogram metrics (log-bucketed p50/p95/p99/p999 + EWMA rates), a pluggable
reporter SPI with console/log/JSON-lines/Prometheus exposition, sampled
event tracing across junction -> query -> sink, device-budget profiling
hooks (dispatch step time, h2d wire traffic, truth-sync stalls), and the
self-observation layer: per-component state introspection
(`snapshot_status()` / `/status.json`, introspect.py), the CEP-native
`@app:selfmon` SelfMonitorStream feed (selfmon.py), per-junction
flight recorders (`@flightRecorder` / `/flight`, flight.py), the
continuous profiler (compile telemetry + chunk waterfalls, profiler.py,
`/profile`), and EXPLAIN ANALYZE plan rendering (explain.py,
`runtime.explain()` / `/explain`).

`siddhi_tpu.core.statistics` is a back-compat shim over this package.
"""

from siddhi_tpu.observability.metrics import (  # noqa: F401
    BufferedEventsTracker,
    EWMA,
    LatencyTracker,
    LogHistogram,
    ThroughputTracker,
    timed,
)
from siddhi_tpu.observability.registry import (  # noqa: F401
    JunctionDeviceStats,
    StatisticsManager,
)
from siddhi_tpu.observability.reporters import (  # noqa: F401
    ConsoleReporter,
    JsonLinesReporter,
    LogReporter,
    Reporter,
    register_reporter,
    render_prometheus,
)
from siddhi_tpu.observability.tracing import Tracer  # noqa: F401
from siddhi_tpu.observability.profiler import (  # noqa: F401
    CompileTelemetry,
    Profiler,
)
from siddhi_tpu.observability.explain import (  # noqa: F401
    build_plan,
    explain,
    explain_static,
    render_text,
)
from siddhi_tpu.observability.flight import FlightRecorder  # noqa: F401
from siddhi_tpu.observability.introspect import render_status  # noqa: F401
from siddhi_tpu.observability.selfmon import (  # noqa: F401
    SELFMON_STREAM_ID,
    SelfMonitor,
)

__all__ = [
    "LogHistogram",
    "EWMA",
    "ThroughputTracker",
    "LatencyTracker",
    "BufferedEventsTracker",
    "StatisticsManager",
    "JunctionDeviceStats",
    "Reporter",
    "ConsoleReporter",
    "LogReporter",
    "JsonLinesReporter",
    "register_reporter",
    "render_prometheus",
    "timed",
    "Tracer",
    "CompileTelemetry",
    "Profiler",
    "build_plan",
    "explain",
    "explain_static",
    "render_text",
    "FlightRecorder",
    "render_status",
    "SELFMON_STREAM_ID",
    "SelfMonitor",
]
