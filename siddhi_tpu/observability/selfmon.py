"""CEP-native self-monitoring: the engine watches itself with SiddhiQL.

Siddhi's own pitch (PAPER.md) is that CEP is the right tool for watching
event systems — so the engine's health should be observable with ordinary
SiddhiQL instead of only an external scraper. The `@app:selfmon` app
annotation injects a system stream:

    SelfMonitorStream (component string, metric string,
                       value double, p99 double)

and arms a recurring scheduler target that, every `interval`, feeds one row
per (component, metric) pair from the app's metrics registry and live
introspection state: latency summaries (`value` = mean ms, `p99` = p99 ms),
throughput counts and 1m rates, error counts, junction queue depths, window
fills, and pipeline occupancy. Users then write plain filters/patterns over
it — alerting via CEP itself:

    @app:selfmon(interval='5 sec')
    from SelfMonitorStream[metric == 'latency_ms' and p99 > 50.0]
    select component, p99 insert into AlertStream;

With no annotation nothing is injected, scheduled, or collected — the
engine pays zero cost (the same contract as `@app:statistics`).
"""

from __future__ import annotations

SELFMON_STREAM_ID = "SelfMonitorStream"
DEFAULT_INTERVAL_MS = 5_000
_MIN_INTERVAL_MS = 10


def selfmon_attrs():
    """The injected stream's schema, shared by the runtime (StreamSchema)
    and the analyzer (symbol table)."""
    from siddhi_tpu.core.types import AttrType

    return [
        ("component", AttrType.STRING),
        ("metric", AttrType.STRING),
        ("value", AttrType.DOUBLE),
        ("p99", AttrType.DOUBLE),
    ]


def _parse_interval(v) -> int | None:
    """'5 sec' / '500 millisec' / bare integer milliseconds -> ms, or None
    when malformed."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    s = str(v).strip()
    try:
        ms = int(s)
    except ValueError:
        try:
            ms = SiddhiCompiler.parse_time_constant(s)
        except Exception:
            return None
    return ms if ms >= _MIN_INTERVAL_MS else None


def iter_selfmon_annotation_problems(ann, defined_streams=()):
    """Yield one message per `@app:selfmon` problem — THE validation rules,
    shared by the runtime resolver (raises on the first) and the analyzer's
    SA113 diagnostics (reports them all)."""
    for k, v in ann.elements:
        if k == "interval" or (k is None and len(ann.elements) == 1):
            if _parse_interval(v) is None:
                yield (
                    f"@app:selfmon interval '{v}' must be a time constant of "
                    f"at least {_MIN_INTERVAL_MS} millisec (e.g. '5 sec')"
                )
        else:
            yield (
                f"unknown @app:selfmon option '{k if k is not None else v}' "
                "(expected interval)"
            )
    if SELFMON_STREAM_ID in defined_streams:
        yield (
            f"@app:selfmon reserves the stream name '{SELFMON_STREAM_ID}' "
            "(the engine injects its definition)"
        )


def resolve_selfmon_annotation(ann, defined_streams=()) -> int:
    """Interval in ms for one app's `@app:selfmon` annotation. Raises
    SiddhiAppCreationError on malformed options — the runtime analog of the
    analyzer's SA113 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for problem in iter_selfmon_annotation_problems(ann, defined_streams):
        raise SiddhiAppCreationError(problem)
    v = ann.element("interval") or ann.element(None)
    return _parse_interval(v) if v is not None else DEFAULT_INTERVAL_MS


class SelfMonitor:
    """Recurring scheduler target feeding SelfMonitorStream from the app's
    metrics registry + introspection hooks (owned by SiddhiAppRuntime)."""

    def __init__(self, runtime, interval_ms: int):
        self.runtime = runtime
        self.interval_ms = int(interval_ms)
        self.ticks = 0  # fires observed (introspection: selfmon health)
        # ONE stable target object: the scheduler dedups pending fires by
        # id(target), and `self._fire` would mint a fresh bound method per
        # notify_at call
        self._target = self._fire

    # ---- row collection --------------------------------------------------

    def rows(self) -> list[tuple]:
        """One (component, metric, value, p99) row per live metric. Never
        raises: a collection fault must not take the scheduler down."""
        rt = self.runtime
        out: list[tuple] = []
        sm = rt.statistics_manager
        if sm is not None:
            for name, lt in list(sm.latency.items()):
                if lt.samples:
                    out.append((
                        name, "latency_ms", lt.avg_ms, lt.quantile_ms(0.99)
                    ))
            for name, tt in list(sm.throughput.items()):
                out.append((name, "throughput", float(tt.count), 0.0))
                out.append((name, "rate_1m", tt.rate_1m, 0.0))
            for name, et in list(sm.errors.items()):
                if et.subscriber is None:  # aggregates only: keep rows lean
                    out.append((name, "errors", float(et.count), 0.0))
            # device-budget histograms give JUNCTION-level tails too:
            # (stream.S, device_fused_step_ms, ...) is the fused dispatch p99
            for name, dt in list(sm.device_time.items()):
                if dt.samples:
                    out.append((
                        dt.component, f"device_{dt.op}_ms",
                        dt.avg_ms, dt.quantile_ms(0.99),
                    ))
        for sid, j in list(rt.junctions.items()):
            if sid == SELFMON_STREAM_ID:
                continue  # the engine must not recurse on its own monitor
            out.append((f"stream.{sid}", "queue_depth", float(j.queued()), 0.0))
            ps = j.pipeline_stats
            if ps is not None and ps.depth:
                out.append((
                    f"stream.{sid}", "pipeline_occupancy", ps.occupancy(), 0.0
                ))
        # window fill is a device->host read; describe_state() itself skips
        # it (fill=None) on transfer-degraded relays, where a scheduler-
        # thread d2h would permanently degrade dispatch — see
        # observability/introspect.device_reads_ok
        for wid, nw in list(rt.named_windows.items()):
            d = nw.describe_state()
            if d.get("fill") is not None:
                out.append((f"window.{wid}", "fill", float(d["fill"]), 0.0))
        store = rt.manager._error_store
        if store is not None and hasattr(store, "size"):
            try:
                out.append((
                    "error_store", "depth", float(store.size()), 0.0
                ))
            except Exception:
                pass
        # supervised-runtime health: restart + admission counters ride the
        # same CEP-queryable stream (core/supervision.py, core/admission.py)
        sup = getattr(rt.manager, "_supervisor", None)
        if sup is not None:
            out.append((
                "supervisor", "restarts",
                float(sup.restarts.get(rt.name, 0)), 0.0,
            ))
        adm = getattr(rt, "_admission", None)
        if adm is not None:
            out.append(("admission", "shed", float(adm.shed), 0.0))
            out.append((
                "admission", "blocked_ms", float(adm.blocked_ms), 0.0
            ))
        ap = getattr(rt, "_autopersist", None)
        if ap is not None:
            out.append((
                "autopersist", "persists", float(ap.persists), 0.0
            ))
            out.append((
                "autopersist", "failures", float(ap.failures), 0.0
            ))
        # black-box recorder: incident counts are CEP-queryable, so an app
        # can alert on its own post-mortems (observability/blackbox.py)
        bb = getattr(rt, "_blackbox", None)
        if bb is not None:
            out.append((
                "blackbox", "incidents",
                float(sum(bb.incidents_total.values())), 0.0,
            ))
            out.append((
                "blackbox", "checkpoint_pins", float(bb.pins), 0.0
            ))
        return out

    # ---- scheduling ------------------------------------------------------

    def start(self) -> None:
        """Arm the recurring feed (mirrors the rate-limiter flush timer
        wiring in SiddhiAppRuntime._arm_rate_limiter)."""
        rt = self.runtime
        rt._scheduler.start()
        rt._scheduler.notify_at(rt.clock() + self.interval_ms, self._target)

    def _fire(self, t_ms: int) -> None:
        rt = self.runtime
        if not rt._running:
            return
        try:
            rows = self.rows()
            if rows:
                rt._junction(SELFMON_STREAM_ID).send_rows(
                    [t_ms] * len(rows), rows, now=t_ms
                )
            self.ticks += 1
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "selfmon feed for app '%s' raised", rt.name
            )
        finally:
            rt._scheduler.notify_at(t_ms + self.interval_ms, self._target)

    def describe_state(self) -> dict:
        return {"interval_ms": self.interval_ms, "ticks": self.ticks}
