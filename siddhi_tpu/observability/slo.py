"""SLO burn-rate engine: `@app:slo(...)` evaluates multi-window burn rates.

Hazelcast Jet's four-nines argument (PAPERS.md) is that stream engines
must be *operated* against tail objectives, not just measured — the
operational tool for that is the SRE multi-window burn-rate alert: an
error budget (1 - objective) is "burning" at rate R when the bad-event
fraction over a window is R times the allowed fraction. A fast window
(window/12) catches sudden regressions in minutes; the slow window (the
full budget window) catches slow leaks without paging on blips.

    @app:slo(p99.latency.ms='50', error.rate='0.001',
             window='1 hour', burn.fast='14', burn.slow='2')

Objectives (at least one required):

    p99.latency.ms=<ms>   latency samples above <ms> are bad; the implied
                          objective is "99% of dispatches under <ms>"
                          (allowed bad fraction 0.01)
    error.rate=<frac>     handler errors per input event, allowed <frac>
    shed.rate=<frac>      admission-shed events per offered event

Options: `window` (budget window, default 1 hour), `burn.fast` /
`burn.slow` (alert thresholds, SRE defaults 14.0 / 2.0), `interval`
(evaluation cadence, default 1 sec).

Alerts are CEP-native (the `@app:selfmon` precedent): the engine injects

    SloAlertStream (component string, objective string,
                    burn_rate double, budget_left double)

and every evaluation tick in breach sends one row per burning
(component, objective), so ordinary SiddhiQL subscribes to its own SLOs.
Validation is SA139 — one rule set shared by the analyzer (reports every
problem) and the runtime resolver (raises on the first), like SA125–SA134.
"""

from __future__ import annotations

from dataclasses import dataclass, field

SLO_STREAM_ID = "SloAlertStream"

DEFAULT_WINDOW_MS = 3_600_000  # 1 hour budget window
DEFAULT_BURN_FAST = 14.0  # SRE fast-burn page threshold
DEFAULT_BURN_SLOW = 2.0  # SRE slow-burn ticket threshold
DEFAULT_INTERVAL_MS = 1_000
_MIN_INTERVAL_MS = 10
_MIN_WINDOW_MS = 1_000
# the fast window is 1/12 of the budget window (the 1h/5m SRE ratio)
_FAST_DIVISOR = 12

OBJ_P99_LATENCY = "p99.latency.ms"
OBJ_ERROR_RATE = "error.rate"
OBJ_SHED_RATE = "shed.rate"
_OBJECTIVES = (OBJ_P99_LATENCY, OBJ_ERROR_RATE, OBJ_SHED_RATE)


def slo_attrs():
    """The injected alert stream's schema, shared by the runtime
    (StreamSchema) and the analyzer (symbol table)."""
    from siddhi_tpu.core.types import AttrType

    return [
        ("component", AttrType.STRING),
        ("objective", AttrType.STRING),
        ("burn_rate", AttrType.DOUBLE),
        ("budget_left", AttrType.DOUBLE),
    ]


def _parse_time_ms(v, floor_ms: int) -> int | None:
    """'1 hour' / '5 sec' / bare integer milliseconds -> ms, or None when
    malformed or below `floor_ms`."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    s = str(v).strip()
    try:
        ms = int(s)
    except ValueError:
        try:
            ms = SiddhiCompiler.parse_time_constant(s)
        except Exception:
            return None
    return ms if ms >= floor_ms else None


def _parse_positive_float(v) -> float | None:
    try:
        f = float(str(v).strip())
    except ValueError:
        return None
    return f if f > 0.0 else None


def _parse_fraction(v) -> float | None:
    f = _parse_positive_float(v)
    return f if f is not None and f < 1.0 else None


@dataclass
class SloConfig:
    """Resolved `@app:slo` options (one per app)."""

    objectives: dict = field(default_factory=dict)  # objective -> target
    window_ms: int = DEFAULT_WINDOW_MS
    burn_fast: float = DEFAULT_BURN_FAST
    burn_slow: float = DEFAULT_BURN_SLOW
    interval_ms: int = DEFAULT_INTERVAL_MS

    @property
    def fast_window_ms(self) -> int:
        return max(1, self.window_ms // _FAST_DIVISOR)


def iter_slo_annotation_problems(ann, defined_streams=()):
    """Yield one message per `@app:slo` problem — THE validation rules,
    shared by the runtime resolver (raises on the first) and the analyzer's
    SA139 diagnostics (reports them all)."""
    saw_objective = False
    for k, v in ann.elements:
        if k == OBJ_P99_LATENCY:
            saw_objective = True
            if _parse_positive_float(v) is None:
                yield (
                    f"@app:slo {OBJ_P99_LATENCY} '{v}' must be a positive "
                    "latency threshold in milliseconds (e.g. '50')"
                )
        elif k in (OBJ_ERROR_RATE, OBJ_SHED_RATE):
            saw_objective = True
            if _parse_fraction(v) is None:
                yield (
                    f"@app:slo {k} '{v}' must be a fraction in (0, 1) "
                    "(e.g. '0.001')"
                )
        elif k == "window":
            if _parse_time_ms(v, _MIN_WINDOW_MS) is None:
                yield (
                    f"@app:slo window '{v}' must be a time constant of at "
                    "least 1 sec (e.g. '1 hour')"
                )
        elif k in ("burn.fast", "burn.slow"):
            if _parse_positive_float(v) is None:
                yield (
                    f"@app:slo {k} '{v}' must be a positive burn-rate "
                    "threshold (e.g. '14')"
                )
        elif k == "interval":
            if _parse_time_ms(v, _MIN_INTERVAL_MS) is None:
                yield (
                    f"@app:slo interval '{v}' must be a time constant of at "
                    f"least {_MIN_INTERVAL_MS} millisec (e.g. '1 sec')"
                )
        else:
            yield (
                f"unknown @app:slo option '{k if k is not None else v}' "
                f"(expected one of: {', '.join(_OBJECTIVES)}, window, "
                "burn.fast, burn.slow, interval)"
            )
    if not saw_objective:
        yield (
            "@app:slo needs at least one objective "
            f"({', '.join(_OBJECTIVES)})"
        )
    if SLO_STREAM_ID in defined_streams:
        yield (
            f"@app:slo reserves the stream name '{SLO_STREAM_ID}' "
            "(the engine injects its definition)"
        )


def resolve_slo_annotation(ann, defined_streams=()) -> SloConfig:
    """SloConfig for one app's `@app:slo` annotation. Raises
    SiddhiAppCreationError on malformed options — the runtime analog of the
    analyzer's SA139 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for problem in iter_slo_annotation_problems(ann, defined_streams):
        raise SiddhiAppCreationError(problem)
    cfg = SloConfig()
    for k, v in ann.elements:
        if k == OBJ_P99_LATENCY:
            cfg.objectives[k] = _parse_positive_float(v)
        elif k in (OBJ_ERROR_RATE, OBJ_SHED_RATE):
            cfg.objectives[k] = _parse_fraction(v)
        elif k == "window":
            cfg.window_ms = _parse_time_ms(v, _MIN_WINDOW_MS)
        elif k == "burn.fast":
            cfg.burn_fast = _parse_positive_float(v)
        elif k == "burn.slow":
            cfg.burn_slow = _parse_positive_float(v)
        elif k == "interval":
            cfg.interval_ms = _parse_time_ms(v, _MIN_INTERVAL_MS)
    return cfg


class SloEngine:
    """Recurring scheduler target evaluating the app's SLOs and feeding
    SloAlertStream (owned by SiddhiAppRuntime; the SelfMonitor shape).

    Each tick appends one cumulative (t_ms, total, bad) snapshot per live
    (objective, component) series to a pruned ring, then computes the
    bad-event fraction over the fast and slow windows as deltas between
    ring endpoints — so burn rates measure the *window*, not
    process-lifetime averages."""

    def __init__(self, runtime, config: SloConfig):
        self.runtime = runtime
        self.config = config
        self.ticks = 0
        self.alerts = 0  # alert rows emitted (introspection: slo health)
        # (objective, component) -> list[(t_ms, total, bad)] cumulative ring
        self._rings: dict = {}
        self._burn: dict = {}  # last evaluation, for report()
        # ONE stable target object: the scheduler dedups pending fires by
        # id(target) (the SelfMonitor precedent)
        self._target = self._fire

    # ---- series collection -----------------------------------------------

    def _series(self) -> list:
        """Cumulative (objective, component, total, bad, allowed) tuples for
        every live series. Never raises: a collection fault must not take
        the scheduler down."""
        rt = self.runtime
        cfg = self.config
        out: list = []
        sm = rt.statistics_manager
        target = cfg.objectives.get(OBJ_P99_LATENCY)
        if target is not None and sm is not None:
            thr_ns = int(target * 1e6)
            for name, lt in list(sm.latency.items()):
                if lt.samples:
                    out.append((
                        OBJ_P99_LATENCY, name, lt.samples,
                        lt.hist.count_over(thr_ns), 0.01,
                    ))
        rate = cfg.objectives.get(OBJ_ERROR_RATE)
        if rate is not None and sm is not None:
            total_in = sum(
                tt.count for name, tt in list(sm.throughput.items())
                if name.startswith("stream.")
            )
            for name, et in list(sm.errors.items()):
                if et.subscriber is None:  # aggregates only, like selfmon
                    base = sm.throughput.get(name)
                    total = base.count if base is not None else total_in
                    out.append((
                        OBJ_ERROR_RATE, name, max(total, et.count),
                        et.count, rate,
                    ))
        rate = cfg.objectives.get(OBJ_SHED_RATE)
        adm = getattr(rt, "_admission", None)
        if rate is not None and adm is not None:
            accepted = 0
            if sm is not None:
                accepted = sum(
                    tt.count for name, tt in list(sm.throughput.items())
                    if name.startswith("stream.")
                )
            out.append((
                OBJ_SHED_RATE, "admission", accepted + adm.shed,
                adm.shed, rate,
            ))
        return out

    # ---- burn evaluation -------------------------------------------------

    @staticmethod
    def _window_burn(ring, now_ms, window_ms, allowed) -> float | None:
        """Bad fraction over [now-window, now] divided by the allowed
        fraction; None until the window holds any events."""
        start = now_ms - window_ms
        base = ring[0]
        for snap in ring:
            if snap[0] < start:
                base = snap
            else:
                break
        head = ring[-1]
        d_total = head[1] - base[1]
        d_bad = head[2] - base[2]
        if d_total <= 0:
            return None
        return (d_bad / d_total) / allowed

    def evaluate(self, now_ms: int) -> list[tuple]:
        """Append snapshots, recompute burn rates, return alert rows
        (component, objective, burn_rate, budget_left) for every series in
        breach of either threshold."""
        cfg = self.config
        rows: list[tuple] = []
        burn_out: dict = {}
        live = set()
        for objective, component, total, bad, allowed in self._series():
            key = (objective, component)
            live.add(key)
            ring = self._rings.setdefault(key, [])
            ring.append((now_ms, total, bad))
            # prune to the slow window (+1 sample of history before it, so
            # _window_burn always has a baseline at the window edge)
            start = now_ms - cfg.window_ms
            while len(ring) > 2 and ring[1][0] < start:
                ring.pop(0)
            fast = self._window_burn(
                ring, now_ms, cfg.fast_window_ms, allowed
            )
            slow = self._window_burn(ring, now_ms, cfg.window_ms, allowed)
            budget_left = (
                max(0.0, round(1.0 - slow, 4)) if slow is not None else 1.0
            )
            burn_out[key] = {
                "fast": round(fast, 4) if fast is not None else None,
                "slow": round(slow, 4) if slow is not None else None,
                "budget_left": budget_left,
            }
            breach = None
            if fast is not None and fast >= cfg.burn_fast:
                breach = fast
            elif slow is not None and slow >= cfg.burn_slow:
                breach = slow
            if breach is not None:
                rows.append((
                    component, objective, float(round(breach, 4)),
                    float(budget_left),
                ))
        # drop rings for series that disappeared (churn removed the query)
        for key in list(self._rings):
            if key not in live:
                del self._rings[key]
        self._burn = burn_out
        return rows

    # ---- scheduling ------------------------------------------------------

    def start(self) -> None:
        rt = self.runtime
        rt._scheduler.start()
        rt._scheduler.notify_at(
            rt.clock() + self.config.interval_ms, self._target
        )

    def _fire(self, t_ms: int) -> None:
        rt = self.runtime
        if not rt._running:
            return
        try:
            rows = self.evaluate(t_ms)
            if rows:
                # count BEFORE sending: subscribers observe delivery
                # synchronously inside send_rows, and introspection read
                # concurrently must never show fewer alerts than delivered
                self.alerts += len(rows)
                rt._junction(SLO_STREAM_ID).send_rows(
                    [t_ms] * len(rows), rows, now=t_ms
                )
                bb = rt._blackbox
                if bb is not None:  # an SLO burn is a black-box incident
                    bb.fire(
                        "slo",
                        "; ".join(
                            f"{r[0]}/{r[1]}" for r in rows[:4]
                        ),
                    )
            self.ticks += 1
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "slo evaluation for app '%s' raised", rt.name
            )
        finally:
            rt._scheduler.notify_at(
                t_ms + self.config.interval_ms, self._target
            )

    # ---- surfaces --------------------------------------------------------

    def report(self) -> dict:
        """The `/slo(.json)` payload for one app."""
        cfg = self.config
        return {
            "app": self.runtime.name,
            "objectives": dict(cfg.objectives),
            "window_ms": cfg.window_ms,
            "fast_window_ms": cfg.fast_window_ms,
            "burn_thresholds": {"fast": cfg.burn_fast, "slow": cfg.burn_slow},
            "interval_ms": cfg.interval_ms,
            "metered": self.runtime.statistics_manager is not None,
            "burn": [
                {
                    "objective": objective,
                    "component": component,
                    **vals,
                }
                for (objective, component), vals in sorted(self._burn.items())
            ],
            "ticks": self.ticks,
            "alerts": self.alerts,
        }

    def prometheus_section(self) -> dict:
        """The `slo` section of StatisticsManager.report(), feeding
        `siddhi_slo_burn_rate{app=,objective=}` (reporters.py)."""
        burn = []
        for (objective, component), vals in sorted(self._burn.items()):
            for window in ("fast", "slow"):
                if vals.get(window) is not None:
                    burn.append({
                        "objective": objective,
                        "component": component,
                        "window": window,
                        "burn_rate": vals[window],
                    })
        return {"burn": burn}

    def describe_state(self) -> dict:
        return {
            "interval_ms": self.config.interval_ms,
            "window_ms": self.config.window_ms,
            "objectives": sorted(self.config.objectives),
            "ticks": self.ticks,
            "alerts": self.alerts,
        }


def render_slo_text(reports: dict) -> str:
    """Plain-text `/slo` rendering over manager.slo_reports()."""
    lines = []
    for app, rep in sorted(reports.items()):
        obj = " ".join(
            f"{k}={v}" for k, v in sorted(rep["objectives"].items())
        )
        lines.append(
            f"app '{app}'  {obj}  window={rep['window_ms']}ms "
            f"(fast={rep['fast_window_ms']}ms)  thresholds "
            f"fast>={rep['burn_thresholds']['fast']} "
            f"slow>={rep['burn_thresholds']['slow']}"
        )
        for b in rep.get("burn", []):
            lines.append(
                f"  {b['objective']} {b['component']}: "
                f"fast={b['fast']} slow={b['slow']} "
                f"budget_left={b['budget_left']}"
            )
        lines.append(
            f"  ticks={rep['ticks']} alerts={rep['alerts']}"
        )
    return "\n".join(lines) + "\n"
