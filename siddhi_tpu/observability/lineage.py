"""Event lineage & provenance: explain every output back to its input events.

The missing observability layer after metrics (PR 3), introspection/selfmon
(PR 5) and the profiler/EXPLAIN (PR 6): when an alert fires, the operator's
first question is not "how fast" but **"which input events caused this
output?"** — the match-explainability axis CEP frameworks are judged on
("A Comprehensive Scalable Framework for Cloud-Native Pattern Detection",
PAPERS.md) and the per-event causality that tail-latency debugging needs
beyond aggregate histograms ("Hazelcast Jet: Low-latency Stream Processing
at the 99.99th Percentile", PAPERS.md).

Opt-in with `@app:lineage(capacity='N', mode='full|sample')`. Three layers:

1. **Ingress stamping** — every stream junction gets a `LineageArena`
   (riding the flight-recorder columnar arena: preallocated ring, circular
   slice-copy writes, zero per-event allocation) that assigns each valid
   CURRENT event a monotonically increasing per-stream sequence id and
   keeps the last `capacity` events decodable on demand. Seq ids survive
   fusion, pipelining and the sharded router because every delivery path
   in this engine is order-preserving per stream (the byte-parity CI
   contract): a consumer's k-th CURRENT row IS the junction's seq k.

2. **Per-operator provenance** — each query runtime, when armed, emits
   `__lin.*` lanes beside its normal aux outputs (extra jitted-program
   outputs; the emissions themselves are untouched, so lineage on/off is
   byte-parity-safe by construction):

   * windows: the admit mask (post-filter) plus the window flow's
     valid/kind/ts lanes drive an exact host-side membership replay —
     each emitted row records the seq range currently in the ring/bucket;
   * pattern/sequence NFAs: the per-ref capture-lane timestamps already
     materialized in the emission buffer surface per match, resolved back
     to per-stream seq ids;
   * joins: each matched output row carries (probe row index, partner
     window seq) — the (left seq, right seq) pair;
   * group-by: admitted rows carry their group key, emissions carry the
     out-row key, and the bucket is filtered per key;
   * aggregations: per time-bucket contributing seq ranges and counts.

   In fused mode the `__lin.*` lanes bypass the chunk program's boolean
   aux reduction and are stacked across the K micro-batches; the sharded
   router's chunks are re-ordered back to global batch order before the
   recorder consumes them.

3. **Serving** — `runtime.lineage(stream_or_query, index)` walks the
   recorded graph backward (multi-hop through insert-into chains) to the
   exact input events, decoded on demand from the arenas; `/lineage` +
   `/lineage.json` on the MetricsServer; `@OnError(action='STORE')`
   entries and trace spans gain the contributing seq range; and
   `runtime.explain()` query nodes render live fan-in (avg/max
   inputs-per-output).

Costs: zero when off — one `is None` / attribute check per hot-path site,
the same contract as statistics/tracing/flight. When ON, each observed
step pays one device→host read of its small `__lin.*` lanes (documented:
on transfer-degraded relay backends this is the flight-recorder caveat
again), and host memory is bounded by `capacity` per arena / recorder ring
with oldest-first eviction.

Known degradations (recorded as `approx` on the affected records instead
of guessing): order-by/limit queries (positions permuted device-side),
expired-probe join rows, join partners in windows without an admission
order (batch windows, tables, named windows), duplicate-timestamp pattern
captures, exotic windows whose host replay desynchronizes, and
evicted-arena seqs (resolution returns the seq id with `event: None`).
Stream-indexed resolution walks through a producing query only when every
stamped event is attributable to it (arena stamp count == producer publish
count); multi-writer and externally-co-fed streams are listed as `mixed`,
not walked. Partitioned queries are not recorded.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

import numpy as np

from siddhi_tpu.observability.flight import FlightRecorder

# lane-name prefix for lineage aux outputs; `__lin@l.` / `__lin@r.` tag the
# two halves of a fused self-join impl whose aux dicts merge into one
LIN = "__lin."
LIN_SIDE = "__lin@"

DEFAULT_CAPACITY = 1024
_MAX_CAPACITY = 1 << 20
_MODES = ("full", "sample")

# thread-local "current publisher" set around a lineage-recorded query's
# insert-target publish (app_runtime._wire_insert): the arena stamping
# inside StreamJunction._publish_batch reads it to attribute the seq range
# to its actual producer (multi-producer resolution)
_PUB_TLS = threading.local()


class publisher_context:
    """Context manager marking (qid, recorder) as the publisher of every
    arena stamp inside the block. Re-entrant per thread (insert-into
    chains nest): the previous publisher is restored on exit."""

    __slots__ = ("_pub", "_prev")

    def __init__(self, qid: str, recorder):
        self._pub = (qid, recorder)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_PUB_TLS, "pub", None)
        _PUB_TLS.pub = self._pub
        return self

    def __exit__(self, *exc):
        _PUB_TLS.pub = self._prev
        return False


def current_publisher() -> Optional[tuple]:
    return getattr(_PUB_TLS, "pub", None)
DEFAULT_SAMPLE_EVERY = 16

# resolution expands at most this many individual seqs per input-stream
# set; wider sets stay as ranges with counts
_EXPAND_LIMIT = 512


class LineageConfig:
    __slots__ = ("capacity", "mode", "sample_every")

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        mode: str = "full",
        sample_every: int = DEFAULT_SAMPLE_EVERY,
    ):
        self.capacity = int(capacity)
        self.mode = mode
        self.sample_every = int(sample_every)


def iter_lineage_annotation_problems(ann):
    """Yield one message per malformed `@app:lineage` element — THE rule
    set, shared by the runtime resolver (raises on the first) and the
    analyzer's SA131 diagnostics (reports them all), so the two can never
    drift (same contract as SA113/SA114/SA125-SA130)."""
    for k, v in ann.elements:
        if k == "capacity" or (k is None and len(ann.elements) == 1):
            try:
                ok = 1 <= int(v) <= _MAX_CAPACITY
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@app:lineage capacity '{v}' must be an integer in "
                    f"1..{_MAX_CAPACITY}"
                )
        elif k == "mode":
            if str(v) not in _MODES:
                yield (
                    f"@app:lineage mode '{v}' must be one of "
                    f"{'|'.join(_MODES)}"
                )
        elif k == "sample.every":
            try:
                ok = int(v) >= 1
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@app:lineage sample.every '{v}' must be a positive "
                    "integer"
                )
        else:
            yield (
                f"unknown @app:lineage option "
                f"'{k if k is not None else v}' (expected capacity, mode, "
                "sample.every)"
            )


def resolve_lineage_annotation(ann) -> Optional[LineageConfig]:
    """LineageConfig from `@app:lineage(...)` (None when absent). Raises
    SiddhiAppCreationError on malformed options — the runtime analog of the
    analyzer's SA131 diagnostic."""
    if ann is None:
        return None
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for problem in iter_lineage_annotation_problems(ann):
        raise SiddhiAppCreationError(problem)
    cap = ann.element("capacity")
    if cap is None and len(ann.elements) == 1 and ann.elements[0][0] is None:
        cap = ann.elements[0][1]
    return LineageConfig(
        capacity=int(cap) if cap is not None else DEFAULT_CAPACITY,
        mode=str(ann.element("mode") or "full"),
        sample_every=int(ann.element("sample.every") or DEFAULT_SAMPLE_EVERY),
    )


# ---------------------------------------------------------------------------
# ingress stamping: the seq-addressable arena
# ---------------------------------------------------------------------------


class LineageArena(FlightRecorder):
    """Flight-recorder arena with sequence addressing: each recorded valid
    CURRENT event gets seq id = its zero-based position in the stream's
    publish order (`_count` before the write). `next_seq` is the stamp
    high-water; seq `s` is still decodable while `next_seq - size <= s`.

    Thread-safety rides the parent's lock; `last_range` is the (base, n)
    of the most recent record — read under the junction lock by the
    @OnError STORE path and the publish trace span."""

    def __init__(self, schema, interner, size: int):
        super().__init__(schema, interner, size)
        self.last_range: tuple[int, int] = (0, 0)
        # per-publish producer capture: (base_seq, n, qid, pub_base)
        # appended when a lineage-recorded query's publish stamped the
        # range (see publisher_context / StreamJunction._publish_batch) —
        # multi-producer streams then resolve seq s to the producer whose
        # publish covered it, instead of just listing candidates
        self.pub_log: deque = deque(maxlen=max(int(size), 64))

    @property
    def next_seq(self) -> int:
        with self._lock:
            return self._count

    def note_producer(
        self, base: int, n: int, qid: str, pub_base: int
    ) -> None:
        with self._lock:
            self.pub_log.append((int(base), int(n), qid, int(pub_base)))

    def producer_for_seq(self, seq: int) -> Optional[tuple]:
        """(qid, producer pub_index) of the recorded publish covering
        `seq`, or None (unlogged: an external input handler, a fused
        commit, or an evicted log entry)."""
        s = int(seq)
        with self._lock:
            for base, n, qid, pub_base in reversed(self.pub_log):
                if base <= s < base + n:
                    return qid, pub_base + (s - base)
                if base + n <= s:
                    break  # log is base-ordered: older entries only below
        return None

    def record_batch(self, batch) -> tuple[int, int]:
        """Stamp + record a device batch's valid CURRENT rows; returns the
        (base_seq, n) range assigned (n may be 0). `last_range` is updated
        on EVERY call — a zero-CURRENT publish must not leave the previous
        batch's range for the @OnError STORE path to pick up."""
        from siddhi_tpu.core.event import KIND_CURRENT

        valid = np.asarray(batch.valid)
        kind = np.asarray(batch.kind)
        idx = np.nonzero(valid & (kind == KIND_CURRENT))[0]
        if idx.size == 0:
            with self._lock:
                self.last_range = (self._count, 0)
                return self.last_range
        ts = np.asarray(batch.ts)[idx]
        cols = {n: np.asarray(c)[idx] for n, c in batch.cols.items()}
        with self._lock:
            base = self._count
            self._write(ts, None, cols, idx.size)
            self.last_range = (base, idx.size)
        return (base, idx.size)

    def record_columns(self, timestamps, cols, n: int) -> tuple[int, int]:
        """Stamp + record host columnar rows (fused-ingest commit: all rows
        are valid CURRENT events)."""
        if n <= 0:
            with self._lock:
                self.last_range = (self._count, 0)
                return self.last_range
        ts = np.asarray(timestamps)[:n]
        host = {name: np.asarray(cols[name])[:n] for name in self._cols}
        with self._lock:
            base = self._count
            self._write(ts, None, host, n)
            self.last_range = (base, n)
        return (base, n)

    def events_for_seqs(self, seqs) -> dict:
        """Decode specific seq ids (those still in the ring) to
        (timestamp, data_tuple); evicted/future seqs map to None."""
        from siddhi_tpu.core.event import rows_from_arrays

        want = sorted({int(s) for s in seqs if s is not None and s >= 0})
        out: dict = {int(s): None for s in seqs if s is not None}
        if not want:
            return out
        with self._lock:
            count = self._count
            live = [s for s in want if count - self.size <= s < count]
            if not live:
                return out
            # slot from the write head, NOT seq % size: an oversized
            # publish trims to the tail (head advances by size while the
            # seq counter advances by n), permanently shifting the phase
            head = self._head
            slots = np.asarray(
                [(head - (count - s)) % self.size for s in live]
            )
            ts = self._ts[slots].copy()
            cols = {n: a[slots].copy() for n, a in self._cols.items()}
        kind = np.zeros((len(live),), np.int8)
        triples = rows_from_arrays(
            self.schema, ts, kind, cols, len(live), self.interner
        )
        for s, (t, _k, data) in zip(live, triples):
            out[s] = (t, data)
        return out

    def describe_state(self) -> dict:
        d = super().describe_state()
        d["next_seq"] = d.pop("total")
        return d


# ---------------------------------------------------------------------------
# seq-set compression helpers
# ---------------------------------------------------------------------------


def _ranges(seqs) -> list[list[int]]:
    """Sorted seq ids -> inclusive [lo, hi] runs."""
    runs: list[list[int]] = []
    for s in seqs:
        s = int(s)
        if runs and s == runs[-1][1] + 1:
            runs[-1][1] = s
        elif runs and s == runs[-1][1]:
            continue
        else:
            runs.append([s, s])
    return runs


def _expand(runs, limit: int = _EXPAND_LIMIT) -> list[int]:
    out: list[int] = []
    for lo, hi in runs:
        for s in range(lo, hi + 1):
            out.append(s)
            if len(out) >= limit:
                return out
    return out


def _seqset(stream: str, seqs, truncated: bool = False) -> dict:
    seqs = sorted({int(s) for s in seqs if s is not None and s >= 0})
    return {
        "stream": stream,
        "ranges": _ranges(seqs),
        "n": len(seqs),
        "truncated": bool(truncated),
    }


# ---------------------------------------------------------------------------
# per-query recorders
# ---------------------------------------------------------------------------


class _Entry:
    """One admitted input row in a recorder's shadow: (stream seq id,
    event ts, window-time, group key)."""

    __slots__ = ("seq", "ts", "wts", "key")

    def __init__(self, seq, ts, wts=None, key=None):
        self.seq = seq
        self.ts = ts
        self.wts = wts if wts is not None else ts
        self.key = key


class QueryLineage:
    """Base recorder: bounded record ring + fan-in accounting. Subclasses
    implement `_observe` per runtime shape. Observation is serialized by
    the owning runtime's receive lock (per-batch path) or the fused
    engine's in-order chunk loop; `_lock` only guards reads from scrape /
    resolution threads."""

    kind_name = "query"

    def __init__(self, cfg: LineageConfig, query_id: str, published_kinds):
        self.cfg = cfg
        self.query_id = query_id
        # kinds this query's insert-into actually publishes (the insert
        # transform re-kinds them CURRENT on the target): maps the target
        # junction's seq k back to this recorder's k-th published record
        self.published_kinds = frozenset(published_kinds)
        self.records: deque = deque(maxlen=cfg.capacity)
        self.out_count = 0
        self.pub_count = 0
        self.total_inputs = 0
        self.max_inputs = 0
        self.approx_count = 0
        self.desync = False
        # RLock: observe() holds it across the whole replay (observations
        # normally serialize on the receive lock / fused send loop, but a
        # per-batch publish CAN interleave with a fused send on another
        # thread — structure corruption is worse than best-effort order),
        # and _record() re-enters it from inside the replay
        self._lock = threading.RLock()

    # -- observation entry point (handles fused self-join side tagging) ----

    def observe(self, lanes: dict, now: int, tag=None) -> None:
        with self._lock:
            self._observe_locked(lanes, now, tag)

    def _observe_locked(self, lanes: dict, now: int, tag=None) -> None:
        if any(k.startswith(LIN_SIDE) for k in lanes):
            # a fused self-join impl ran both sides in one program; their
            # lanes arrive side-tagged in one dict — replay l then r, the
            # per-batch dispatch order
            for side in ("l", "r"):
                pre = f"{LIN_SIDE}{side}."
                sub = {
                    LIN + k[len(pre):]: v
                    for k, v in lanes.items()
                    if k.startswith(pre)
                }
                if sub:
                    self._observe(sub, now, side)
            return
        self._observe(lanes, now, tag)

    def _observe(self, lanes: dict, now: int, tag) -> None:
        raise NotImplementedError

    # -- recording ---------------------------------------------------------

    def _record(
        self, kind: int, ts, inputs: list[dict], approx: bool,
        trigger=None,
    ) -> None:
        from siddhi_tpu.core.event import KIND_CURRENT, KIND_EXPIRED

        out_index = self.out_count
        self.out_count += 1
        pub_index = None
        if kind in self.published_kinds:
            pub_index = self.pub_count
            self.pub_count += 1
        n_in = sum(s["n"] for s in inputs)
        self.total_inputs += n_in
        if n_in > self.max_inputs:
            self.max_inputs = n_in
        if approx:
            self.approx_count += 1
        if (
            self.cfg.mode == "sample"
            and out_index % self.cfg.sample_every != 0
        ):
            return
        rec = {
            "out_index": out_index,
            "pub_index": pub_index,
            "ts": int(ts),
            "kind": (
                "CURRENT" if kind == KIND_CURRENT
                else "EXPIRED" if kind == KIND_EXPIRED
                else int(kind)
            ),
            "inputs": inputs,
            "approx": bool(approx),
        }
        if trigger is not None:
            rec["trigger"] = {"stream": trigger[0], "seq": int(trigger[1])}
        with self._lock:
            self.records.append(rec)

    # -- reading -----------------------------------------------------------

    def record_for_out_index(self, k: int) -> Optional[dict]:
        with self._lock:
            for rec in reversed(self.records):
                if rec["out_index"] == k:
                    return rec
        return None

    def record_for_pub_index(self, k: int) -> Optional[dict]:
        with self._lock:
            for rec in reversed(self.records):
                if rec["pub_index"] == k:
                    return rec
        return None

    def last_record(self) -> Optional[dict]:
        with self._lock:
            return self.records[-1] if self.records else None

    def fan_in(self) -> dict:
        n = self.out_count
        return {
            "outputs": n,
            "inputs": self.total_inputs,
            "avg_inputs_per_output": (
                round(self.total_inputs / n, 3) if n else 0.0
            ),
            "max_inputs_per_output": self.max_inputs,
        }

    def describe(self) -> dict:
        d = {
            "kind": self.kind_name,
            "mode": self.cfg.mode,
            "capacity": self.cfg.capacity,
            "recorded": len(self.records),
            "approx_records": self.approx_count,
        }
        if self.desync:
            d["desync"] = True
        d.update(self.fan_in())
        return d


class SingleQueryLineage(QueryLineage):
    """Recorder for plain single-stream queries: stateless filters, sliding
    and batch windows, group-by — an exact host-side membership replay of
    the device window driven by the step's `__lin.*` lanes."""

    kind_name = "single"

    def __init__(
        self, cfg, query_id, published_kinds, *, input_stream: str,
        window=None, grouped: bool = False, aggregated: bool = False,
        order_limited: bool = False,
    ):
        super().__init__(cfg, query_id, published_kinds)
        self.input_stream = input_stream
        self.window = window
        self.is_batch = bool(window is not None and window.is_batch)
        self.sliding = window is not None and not self.is_batch
        self.grouped = grouped
        self.aggregated = aggregated
        # order-by/limit permutes out positions device-side: records become
        # step-granular approximations
        self.order_limited = order_limited
        self.in_seen = 0  # stream seq high-water for this consumer
        self.pending: deque = deque()  # admitted, not yet born in the flow
        self.live: deque = deque()  # current window/bucket members
        self.live_truncated = False

    def _observe(self, lanes: dict, now: int, tag) -> None:
        from siddhi_tpu.core.event import (
            KIND_CURRENT,
            KIND_EXPIRED,
            KIND_RESET,
        )

        in_mask = lanes.get(LIN + "in")
        if in_mask is None:
            return
        in_ts = lanes[LIN + "in_ts"]
        admit = lanes.get(LIN + "admit", in_mask)
        keys = lanes.get(LIN + "key")
        wts = lanes.get(LIN + "wts")
        base = self.in_seen
        self.in_seen += int(in_mask.sum())

        # admitted rows, in batch order, with their stream seqs
        ranks = np.cumsum(in_mask.astype(np.int64)) - in_mask.astype(np.int64)
        for p in np.nonzero(admit & in_mask)[0]:
            self.pending.append(_Entry(
                base + int(ranks[p]),
                int(in_ts[p]),
                int(wts[p]) if wts is not None else None,
                keys[p].item() if keys is not None else None,
            ))

        w_valid = lanes[LIN + "w_valid"]
        w_kind = lanes[LIN + "w_kind"]
        w_ts = lanes[LIN + "w_ts"]
        out_valid = lanes[LIN + "out_valid"]
        out_kind = lanes[LIN + "out_kind"]
        gkey = lanes.get(LIN + "gkey")
        bound = self.cfg.capacity

        step_approx = self.order_limited
        for p in np.nonzero(w_valid | out_valid)[0]:
            p = int(p)
            k = int(w_kind[p])
            e = None
            if w_valid[p]:
                if k == KIND_RESET:
                    if self.is_batch:
                        self.live.clear()
                        self.live_truncated = False
                    continue
                if k == KIND_CURRENT:
                    if self.pending:
                        e = self.pending.popleft()
                    else:
                        self.desync = True
                        step_approx = True
                    if e is not None:
                        self.live.append(e)
                        if len(self.live) > bound:
                            self.live.popleft()
                            self.live_truncated = True
                elif k == KIND_EXPIRED and self.sliding and self.live:
                    # sliding evictions are always oldest-first (the seq
                    # lane orders the candidate sort; capacity eviction
                    # rides the same path)
                    self.live.popleft()
            if not out_valid[p]:
                continue
            ok = int(out_kind[p])
            approx = step_approx
            trigger = None
            if e is not None:
                trigger = (self.input_stream, e.seq)
            if self.window is None and not self.aggregated and not self.grouped:
                # stateless: the single admitted row is the provenance
                seqs = [e.seq] if e is not None else []
                approx = approx or e is None
            else:
                members = self.live
                if self.grouped and gkey is not None:
                    kv = gkey[p].item()
                    seqs = [m.seq for m in members if m.key == kv]
                else:
                    seqs = [m.seq for m in members]
                approx = approx or self.live_truncated
            self._record(
                ok, w_ts[p] if w_valid[p] else now,
                [_seqset(self.input_stream, seqs,
                         truncated=self.live_truncated)],
                approx, trigger=trigger,
            )
        if self.sliding or self.window is None:
            # sliding/stateless semantics: every admitted row is born in
            # the same step; leftovers mean the replay desynchronized
            # (e.g. emission-buffer overflow) — absorb them so counts
            # stay aligned, and flag it
            while self.pending:
                self.desync = True
                self.live.append(self.pending.popleft())
                if len(self.live) > bound:
                    self.live.popleft()
                    self.live_truncated = True


class JoinQueryLineage(QueryLineage):
    """Recorder for two-sided joins: per matched output row the (left seq,
    right seq) pair, via the probe-row index and the partner ring's device
    seq lane surfaced by `_assemble`."""

    kind_name = "join"

    def __init__(
        self, cfg, query_id, published_kinds, *, left_stream: str,
        right_stream: str, batch_capacity: int = 0,
    ):
        super().__init__(cfg, query_id, published_kinds)
        self.streams = {"l": left_stream, "r": right_stream}
        self.in_seen = {"l": 0, "r": 0}
        # per-side shadow of the window ring keyed by the DEVICE's window
        # admission seq (the SlidingWindow `seq` lane): win seq k is the
        # k-th filter-passing row this side admitted, in arrival order
        self.win: dict[str, dict[int, _Entry]] = {"l": {}, "r": {}}
        self.win_count = {"l": 0, "r": 0}

    def _observe(self, lanes: dict, now: int, tag) -> None:
        side = tag if tag in ("l", "r") else "l"
        other = "r" if side == "l" else "l"
        in_mask = lanes.get(LIN + "in")
        if in_mask is None:
            return
        in_ts = lanes[LIN + "in_ts"]
        base = self.in_seen[side]
        self.in_seen[side] += int(in_mask.sum())
        ranks = (
            np.cumsum(in_mask.astype(np.int64)) - in_mask.astype(np.int64)
        )

        admit = lanes.get(LIN + "admit")
        if admit is not None:
            shadow = self.win[side]
            for p in np.nonzero(admit & in_mask)[0]:
                k = self.win_count[side]
                self.win_count[side] = k + 1
                shadow[k] = _Entry(base + int(ranks[p]), int(in_ts[p]))
                old = k - self.cfg.capacity
                if old in shadow:
                    del shadow[old]

        out_valid = lanes.get(LIN + "out_valid")
        if out_valid is None:
            return
        out_kind = lanes[LIN + "out_kind"]
        out_ts = lanes[LIN + "out_ts"]
        pi = lanes[LIN + "j_pi"]
        pseq = lanes[LIN + "j_pseq"]
        for p in np.nonzero(out_valid)[0]:
            p = int(p)
            approx = False
            probe = int(pi[p])
            my_seq = None
            if 0 <= probe < in_mask.shape[0] and in_mask[probe]:
                my_seq = base + int(ranks[probe])
            else:
                approx = True  # expired-probe row: not an input position
            partner = self.win[other].get(int(pseq[p]))
            inputs = []
            trigger = None
            mine: dict[str, list] = {}
            if my_seq is not None:
                mine.setdefault(self.streams[side], []).append(my_seq)
                trigger = (self.streams[side], my_seq)
            if partner is not None:
                mine.setdefault(self.streams[other], []).append(partner.seq)
            elif int(pseq[p]) >= 0:
                approx = True  # partner evicted from the bounded shadow
            elif int(pseq[p]) == -2:
                # a real matched partner whose window tracks no admission
                # order (batch window / table / named window): flagged,
                # never guessed — -1 stays "outer join, no partner"
                approx = True
            for sid, seqs in mine.items():
                inputs.append(_seqset(sid, seqs))
            self._record(
                int(out_kind[p]), out_ts[p], inputs, approx, trigger=trigger
            )


class PatternQueryLineage(QueryLineage):
    """Recorder for pattern/sequence NFAs: the per-ref capture-lane
    timestamps the emission buffer already carries, resolved back to seq
    ids through a bounded per-stream (seq, ts) shadow."""

    kind_name = "pattern"

    def __init__(
        self, cfg, query_id, published_kinds, *, refs: list[tuple[str, str]],
    ):
        super().__init__(cfg, query_id, published_kinds)
        # [(ref name, stream id)] in linearized ref order
        self.refs = list(refs)
        self.in_seen: dict[str, int] = {}
        self.shadow: dict[str, deque] = {}

    def _observe(self, lanes: dict, now: int, tag) -> None:
        stream_id = tag
        in_mask = lanes.get(LIN + "in")
        if in_mask is None:
            return
        if stream_id is not None and int(in_mask.sum()):
            in_ts = lanes[LIN + "in_ts"]
            base = self.in_seen.get(stream_id, 0)
            sh = self.shadow.get(stream_id)
            if sh is None:
                sh = self.shadow[stream_id] = deque(
                    maxlen=self.cfg.capacity
                )
            for p in np.nonzero(in_mask)[0]:
                sh.append((base, int(in_ts[p])))
                base += 1
            self.in_seen[stream_id] = base

        out_valid = lanes.get(LIN + "out_valid")
        if out_valid is None:
            return
        out_kind = lanes[LIN + "out_kind"]
        out_ts = lanes[LIN + "out_ts"]
        for p in np.nonzero(out_valid)[0]:
            p = int(p)
            per_stream: dict[str, list] = {}
            approx = False
            for i, (_ref, sid) in enumerate(self.refs):
                n_lane = lanes.get(f"{LIN}p_n{i}")
                ts_lane = lanes.get(f"{LIN}p_ts{i}")
                if n_lane is None or ts_lane is None:
                    continue
                n = int(n_lane[p])
                sh = self.shadow.get(sid, ())
                for c in range(min(n, ts_lane.shape[1])):
                    t = int(ts_lane[p, c])
                    seq = None
                    matches = 0
                    for s, sts in reversed(sh):
                        if sts == t:
                            if seq is None:
                                seq = s
                            matches += 1
                            if matches > 1:
                                break
                    if seq is None:
                        approx = True
                    else:
                        per_stream.setdefault(sid, []).append(seq)
                        if matches > 1:
                            # duplicate timestamps: the capture lane only
                            # carries ts, so the attribution is ambiguous
                            # — flagged, never guessed
                            approx = True
            inputs = [
                _seqset(sid, seqs) for sid, seqs in per_stream.items()
            ]
            self._record(int(out_kind[p]), out_ts[p], inputs, approx)


class AggregationLineage:
    """Per-bucket provenance for an incremental aggregation: contributing
    seq range + count per (finest-duration) time bucket, bounded to the
    last `capacity` buckets. Host-side only — aggregations always ride the
    per-batch path."""

    kind_name = "aggregation"

    def __init__(self, cfg: LineageConfig, agg_id: str, input_stream: str,
                 duration):
        self.cfg = cfg
        self.agg_id = agg_id
        self.input_stream = input_stream
        self.duration = duration  # the finest Duration bucketing events
        self.in_seen = 0
        self.buckets: dict = {}  # bucket_ts -> [lo, hi, count]
        self._order: deque = deque()
        self._lock = threading.Lock()

    def observe_batch(self, batch, ts_col: Optional[np.ndarray]) -> None:
        from siddhi_tpu.core.event import KIND_CURRENT

        valid = np.asarray(batch.valid)
        kind = np.asarray(batch.kind)
        mask = valid & (kind == KIND_CURRENT)
        n = int(mask.sum())
        if n == 0:
            return
        ts = (
            ts_col if ts_col is not None else np.asarray(batch.ts)
        )[np.nonzero(mask)[0]]
        base = self.in_seen
        self.in_seen += n
        from siddhi_tpu.core.aggregation import align_bucket

        bts = np.asarray(align_bucket(ts.astype(np.int64), self.duration))
        with self._lock:
            for i, b in enumerate(bts):
                b = int(b)
                ent = self.buckets.get(b)
                seq = base + i
                if ent is None:
                    self.buckets[b] = [seq, seq, 1]
                    self._order.append(b)
                    while len(self._order) > self.cfg.capacity:
                        self.buckets.pop(self._order.popleft(), None)
                else:
                    ent[0] = min(ent[0], seq)
                    ent[1] = max(ent[1], seq)
                    ent[2] += 1

    def describe(self) -> dict:
        with self._lock:
            return {
                "kind": self.kind_name,
                "stream": self.input_stream,
                "duration": getattr(self.duration, "name", str(self.duration)),
                "events": self.in_seen,
                "buckets": {
                    str(b): {
                        "seq_lo": e[0], "seq_hi": e[1], "count": e[2],
                    }
                    for b, e in self.buckets.items()
                },
            }


# ---------------------------------------------------------------------------
# the per-app ledger: resolution + reporting
# ---------------------------------------------------------------------------


class LineageLedger:
    """App-level lineage surface: owns the config, walks records backward
    through insert-into chains, and renders the /lineage payloads."""

    def __init__(self, runtime, cfg: LineageConfig):
        self.runtime = runtime
        self.cfg = cfg

    # -- wiring views ------------------------------------------------------

    def recorders(self) -> dict:
        out = {}
        for qid, qr in list(self.runtime.queries.items()):
            lin = getattr(qr, "lineage", None)
            if lin is not None:
                out[qid] = lin
        return out

    def agg_recorders(self) -> dict:
        out = {}
        for aid, ar in getattr(self.runtime, "aggregations", {}).items():
            lin = getattr(ar, "lineage", None)
            if lin is not None:
                out[aid] = lin
        return out

    def producers(self, stream_id: str) -> list[str]:
        """Queries with a lineage recorder inserting into `stream_id`."""
        from siddhi_tpu.query_api.execution import InsertIntoStream

        out = []
        for qid, qr in list(self.runtime.queries.items()):
            if getattr(qr, "lineage", None) is None:
                continue
            o = qr.query.output_stream
            if isinstance(o, InsertIntoStream) and o.target == stream_id:
                out.append(qid)
        return out

    def arena(self, stream_id: str) -> Optional[LineageArena]:
        j = self.runtime.junctions.get(stream_id)
        return getattr(j, "lineage", None) if j is not None else None

    def _sole_producer(self, stream_id: str, recs: dict):
        """(qid, producers) when every stamped event on `stream_id` is
        attributable to exactly one recorded producer query — the junction
        seq k is then that query's k-th published record. An external
        input-handler writer (or any unrecorded publisher) interleaves
        seqs the producer's pub counter knows nothing about, so the walk
        is declined unless the arena's stamp count matches the producer's
        publish count exactly."""
        prods = self.producers(stream_id)
        if len(prods) != 1:
            return None, prods
        lin = recs.get(prods[0])
        arena = self.arena(stream_id)
        if (
            lin is None
            or arena is None
            or arena.next_seq != lin.pub_count
        ):
            return None, prods
        return prods[0], prods

    # -- resolution --------------------------------------------------------

    def resolve(self, target: str, index: Optional[int] = None,
                depth: int = 6) -> dict:
        """Explain output `index` of `target` (a query id or a stream id)
        back to the exact input events. Stream indices are the junction's
        lineage seq ids (valid CURRENT events in publish order)."""
        recs = self.recorders()
        if target in recs:
            rec = (
                recs[target].record_for_out_index(index)
                if index is not None
                else recs[target].last_record()
            )
            if rec is None:
                return {
                    "query": target, "out_index": index,
                    "error": "no record (evicted, sampled out, or not yet "
                             "emitted)",
                }
            return self._resolve_record(target, rec, depth, recs)
        if target in self.runtime.junctions:
            return self._resolve_stream(target, index, depth, recs)
        raise KeyError(
            f"'{target}' is neither a lineage-recorded query nor a stream"
        )

    def _resolve_stream(self, stream_id: str, index: Optional[int],
                        depth: int, recs: Optional[dict] = None) -> dict:
        arena = self.arena(stream_id)
        if index is None:
            if arena is None or arena.next_seq == 0:
                return {"stream": stream_id, "error": "no events stamped"}
            index = arena.next_seq - 1
        node: dict = {"stream": stream_id, "seq": int(index)}
        if arena is not None:
            ev = arena.events_for_seqs([index]).get(int(index))
            if ev is not None:
                node["ts"], node["event"] = ev[0], list(ev[1])
            else:
                node["event"] = None
                node["evicted"] = index < arena.next_seq
        if recs is None:
            recs = self.recorders()
        sole, prods = self._sole_producer(stream_id, recs)
        if sole is not None and depth > 0:
            rec = recs[sole].record_for_pub_index(int(index))
            if rec is not None:
                node["via"] = self._resolve_record(sole, rec, depth - 1, recs)
            else:
                node["via"] = {
                    "query": sole,
                    "error": "record evicted or sampled out",
                }
        elif prods:
            # multi-writer stream: the arena's per-publish producer log
            # (note_producer) resolves WHICH recorded query stamped this
            # seq — walk that producer's record. Unlogged seqs (external
            # input handler interleaved, or the log entry evicted) fall
            # back to listing the candidates.
            hit = (
                arena.producer_for_seq(int(index))
                if arena is not None
                else None
            )
            if hit is not None and hit[0] in recs and depth > 0:
                qid, pub_idx = hit
                node["producer"] = qid
                rec = recs[qid].record_for_pub_index(pub_idx)
                if rec is not None:
                    node["via"] = self._resolve_record(
                        qid, rec, depth - 1, recs
                    )
                else:
                    node["via"] = {
                        "query": qid,
                        "error": "record evicted or sampled out",
                    }
            else:
                node["producers"] = prods
                node["mixed"] = True
        return node

    def _resolve_record(
        self, qid: str, rec: dict, depth: int, recs: Optional[dict] = None
    ) -> dict:
        node = {
            "query": qid,
            "out_index": rec["out_index"],
            "ts": rec["ts"],
            "kind": rec["kind"],
            "approx": rec["approx"],
            "inputs": [],
        }
        if "trigger" in rec:
            node["trigger"] = rec["trigger"]
        for ss in rec["inputs"]:
            sid = ss["stream"]
            entry: dict = {
                "stream": sid,
                "ranges": ss["ranges"],
                "n": ss["n"],
            }
            if ss.get("truncated"):
                entry["truncated"] = True
            seqs = _expand(ss["ranges"])
            arena = self.arena(sid)
            if arena is not None and seqs:
                evs = arena.events_for_seqs(seqs)
                entry["events"] = [
                    {
                        "seq": s,
                        **(
                            {"ts": evs[s][0], "event": list(evs[s][1])}
                            if evs[s] is not None
                            else {"event": None}
                        ),
                    }
                    for s in seqs
                ]
            if depth > 0:
                if recs is None:
                    recs = self.recorders()
                sole, _prods = self._sole_producer(sid, recs)
                if sole is not None:
                    ups = []
                    for s in seqs[:8]:  # bound the recursive fan-out
                        up = recs[sole].record_for_pub_index(s)
                        if up is not None:
                            ups.append(
                                self._resolve_record(sole, up, depth - 1, recs)
                            )
                    if ups:
                        entry["via"] = ups
                else:
                    # multi-producer upstream: resolve each contributing
                    # seq to ITS producer via the arena's publish log
                    ups = []
                    for s in seqs[:8]:
                        hit = (
                            arena.producer_for_seq(s)
                            if arena is not None
                            else None
                        )
                        if hit is None or hit[0] not in recs:
                            continue
                        up = recs[hit[0]].record_for_pub_index(hit[1])
                        if up is not None:
                            ups.append(
                                self._resolve_record(
                                    hit[0], up, depth - 1, recs
                                )
                            )
                    if ups:
                        entry["via"] = ups
            node["inputs"].append(entry)
        return node

    # -- reporting ---------------------------------------------------------

    def report(self, resolve_recent: int = 1) -> dict:
        streams = {}
        for sid, j in list(self.runtime.junctions.items()):
            ar = getattr(j, "lineage", None)
            if ar is not None:
                streams[sid] = ar.describe_state()
        queries = {}
        recent = {}
        recs = self.recorders()
        for qid, lin in recs.items():
            queries[qid] = lin.describe()
            if resolve_recent:
                chains = []
                with lin._lock:
                    tail = list(lin.records)[-resolve_recent:]
                for rec in tail:
                    try:
                        chains.append(
                            self._resolve_record(qid, rec, 4, recs)
                        )
                    except Exception:  # resolution must never break a scrape
                        pass
                if chains:
                    recent[qid] = chains
        rep = {
            "config": {
                "capacity": self.cfg.capacity,
                "mode": self.cfg.mode,
            },
            "streams": streams,
            "queries": queries,
            "aggregations": {
                aid: lin.describe()
                for aid, lin in self.agg_recorders().items()
            },
        }
        if recent:
            rep["recent"] = recent
        return rep


def render_lineage_text(reports: dict) -> str:
    """Human-readable /lineage (reports: app name -> ledger.report())."""
    lines: list[str] = []
    for app, rep in reports.items():
        lines.append(f"== app: {app} ==")
        cfg = rep.get("config", {})
        lines.append(
            f"  lineage capacity={cfg.get('capacity')} mode={cfg.get('mode')}"
        )
        for sid, st in sorted(rep.get("streams", {}).items()):
            lines.append(
                f"  stream {sid}: next_seq={st.get('next_seq')} "
                f"ring={st.get('recorded')}/{st.get('size')}"
            )
        for qid, q in sorted(rep.get("queries", {}).items()):
            lines.append(
                f"  query {qid} [{q.get('kind')}]: outputs={q.get('outputs')}"
                f" fan-in avg={q.get('avg_inputs_per_output')}"
                f" max={q.get('max_inputs_per_output')}"
                f" recorded={q.get('recorded')}"
                + (" DESYNC" if q.get("desync") else "")
            )
        for aid, a in sorted(rep.get("aggregations", {}).items()):
            lines.append(
                f"  aggregation {aid}: events={a.get('events')} "
                f"buckets={len(a.get('buckets') or {})}"
            )
        for qid, chains in sorted(rep.get("recent", {}).items()):
            for ch in chains:
                lines.append(f"  last {qid}: {_chain_line(ch)}")
    return "\n".join(lines) + "\n"


def _chain_line(node: dict) -> str:
    parts = [
        f"out#{node.get('out_index')} ts={node.get('ts')} "
        f"{node.get('kind')}"
    ]
    for inp in node.get("inputs", ()):
        rng = ",".join(
            f"{lo}..{hi}" if lo != hi else str(lo)
            for lo, hi in inp.get("ranges", ())
        )
        parts.append(f"<- {inp['stream']}[{rng}] (n={inp['n']})")
    return " ".join(parts)
