"""Plan-vs-actual calibration: join static predictions to live meters.

The analyzer prices every app before it runs — per-query selectivity and
state bytes (analysis/cost.py), compile-cause counts, group dispatch
reductions and encoded wire B/ev (analysis/fusion.py) — and the runtime
meters what actually happened (registry throughput/memory, the compile
ledger, group_report, the roofline split). The join key is the component
name, which both sides share *by design* (`query.{qid}`,
`stream.{sid}.fused`, `stream.{sid}.fusedgroup.{g}`). This module closes
the loop: a CalibrationLedger pairs each prediction with its live
counterpart, tracks the live/predicted error ratio with EWMA drift, and
flags mispricings with stable reason codes:

    selectivity_off_4x             metered selectivity >4x off the estimate
    wire_full_width_fallback       a hinted wire lane fell back full-width
    unpredicted_recompile_cause    the compile ledger recorded a cause the
                                   plan did not price (full_width_rebuild
                                   with no hazard, deliver_set_change,
                                   donation_mismatch)
    shared_state_refcount_collapsed  a priced shared-state ring is refcounted
                                   by <2 queries ("To Share, or not to
                                   Share", PAPERS.md: sharing gone stale)

Pairing happens at `start()` and re-pairs on every churn splice / fused
rebuild (the `rearm_routers` precedent) — predictions are rebuilt from the
*current* AST, while cumulative mispriced counters survive re-pairing.
With `@app:statistics` absent no ledger exists at all: the zero-overhead
contract is one `is None` check.
"""

from __future__ import annotations

import math

# stable mispricing reason codes (the flag vocabulary is API: tests, CI
# and dashboards match on these strings)
REASON_SELECTIVITY = "selectivity_off_4x"
REASON_WIRE_FALLBACK = "wire_full_width_fallback"
REASON_RECOMPILE = "unpredicted_recompile_cause"
REASON_SHARED_STATE = "shared_state_refcount_collapsed"

# the six prediction kinds the ledger pairs (acceptance surface: CI
# asserts all six show up with live values on the sentinel app)
KIND_SELECTIVITY = "selectivity"
KIND_STATE_BYTES = "state_bytes"
KIND_COMPILES = "compiles"
KIND_DISPATCH = "dispatch_reduction"
KIND_WIRE_DECLARED = "wire_declared_B_per_ev"
KIND_WIRE_INFERRED = "wire_inferred_B_per_ev"

_SELECTIVITY_FACTOR = 4.0
_MIN_EVENTS = 64  # selectivity flags need this much evidence to arm
_EWMA_ALPHA = 0.3
# causes that fire in normal operation even when the plan priced none of
# them precisely (first compile of a variant, organic shape changes):
# only causes outside BOTH the prediction and this set flag a mispricing
_BASELINE_CAUSES = frozenset(
    ("first_compile", "shape_change", "tail_variant_k")
)


def _safe_ratio(live, pred):
    """live/predicted kept finite: both-zero pairs are perfectly priced
    (1.0); a zero prediction with live signal saturates at the live value
    (rather than inf, which JSON and Prometheus both reject)."""
    try:
        live = float(live)
        pred = float(pred)
    except (TypeError, ValueError):
        return None
    if not (math.isfinite(live) and math.isfinite(pred)):
        return None
    if pred == 0.0:
        return 1.0 if live == 0.0 else round(1.0 + live, 4)
    return round(live / pred, 4)


class CalibrationLedger:
    """Pairs one app's static predictions with its live meters (owned by
    SiddhiAppRuntime; exists only when `@app:statistics` is armed)."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.generation = 0  # pair() count: 1 at start, +1 per re-pair
        self._pred: dict = {}  # (kind, component) -> prediction entry
        self._ewma: dict = {}  # (kind, component) -> smoothed error ratio
        # cumulative mispriced counters: (reason, component) -> count.
        # `_active` dedups while a flag persists (one increment per
        # raise, re-raised after it clears); both SURVIVE pair().
        self.mispriced: dict = {}
        self._active: set = set()

    # ---- pairing ---------------------------------------------------------

    def pair(self) -> None:
        """(Re)build the prediction table from the app's *current* AST —
        called at start() and from every fused rebuild (churn splices and
        re-formed groups re-price automatically). Never raises: the plan
        pass is advisory and must not take start() or a splice down."""
        try:
            self._pred = self._build_predictions()
            self.generation += 1
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "calibration pairing for app '%s' raised", self.runtime.name
            )

    def _build_predictions(self) -> dict:
        from siddhi_tpu.analysis.cost import iter_query_entries
        from siddhi_tpu.analysis.fusion import build_fusion_plan

        app = self.runtime.app
        plan = build_fusion_plan(app)
        model = plan.costs
        pred: dict = {}
        # qid -> produced stream (the selectivity denominator/numerator
        # pair needs both junction meters)
        produces = {}
        for qid, q, _in_part in iter_query_entries(app):
            out = getattr(q, "output_stream", None)
            if out is not None and not getattr(out, "is_inner", False):
                produces[qid] = getattr(out, "target", None)
        group_of = {g["stream"]: g for g in plan.groups}
        for qid, qc in model.queries.items():
            comp = f"query.{qid}"
            pred[(KIND_SELECTIVITY, comp)] = {
                "predicted": qc.est_selectivity,
                "consumes": list(qc.consumed_streams),
                "produces": produces.get(qid),
            }
            pred[(KIND_STATE_BYTES, comp)] = {"predicted": qc.state_bytes}
            for p in qc.programs:
                pred[(KIND_COMPILES, p.component)] = {
                    "predicted": p.predicted_compiles,
                    "causes": dict(p.predicted_causes),
                }
        for sid, sc in model.streams.items():
            # fused-group members compile under the GROUP component
            g = group_of.get(sid)
            comp = g["component"] if g is not None else f"stream.{sid}.fused"
            causes = sc.predicted_causes()
            pred[(KIND_COMPILES, comp)] = {
                "predicted": sum(causes.values()),
                "causes": causes,
                "stream": sid,
            }
        shared_of: dict = {}
        for s in plan.shared_state:
            shared_of.setdefault(s["stream"], []).append(s)
        for g in plan.groups:
            pred[(KIND_DISPATCH, g["component"])] = {
                "predicted": g["est_dispatch_reduction"],
                "stream": g["stream"],
                "shared": [
                    {"queries": list(s["queries"]),
                     "refcount": len(s["queries"])}
                    for s in shared_of.get(g["stream"], [])
                ],
            }
        for sid, w in plan.wire.items():
            if w.get("disabled"):
                continue
            comp = f"stream.{sid}"
            inferred = set(w.get("inferred_lanes", ()))
            declared = set(w.get("encodings", ())) - inferred
            entry = {
                "predicted": w.get("encoded_B_per_ev_est"),
                "logical": w.get("logical_B_per_ev"),
                "stream": sid,
                "narrow": bool(w.get("encodings")),
            }
            # a stream with no encodings at all is still a static
            # full-width price — keep it under the declared kind
            if declared or not inferred:
                pred[(KIND_WIRE_DECLARED, comp)] = dict(entry)
            if inferred:
                pred[(KIND_WIRE_INFERRED, comp)] = {
                    **entry, "inferred_lanes": sorted(inferred),
                }
        return pred

    # ---- live observation ------------------------------------------------

    def _live_value(self, kind, component, p):
        """The live counterpart of one prediction, or None when the meter
        has no signal yet. Also returns per-pair flags."""
        rt = self.runtime
        sm = rt.statistics_manager
        flags: list = []
        if sm is None:
            return None, flags
        if kind == KIND_SELECTIVITY:
            ins = 0
            seen = False
            for sid in p["consumes"]:
                tt = sm.throughput.get(f"stream.{sid}")
                if tt is not None:
                    ins += tt.count
                    seen = True
            out = sm.throughput.get(f"stream.{p['produces']}") \
                if p.get("produces") else None
            if not seen or ins <= 0 or out is None:
                return None, flags
            live = out.count / ins
            if ins >= _MIN_EVENTS and p["predicted"]:
                r = live / p["predicted"]
                if r > _SELECTIVITY_FACTOR or r < 1.0 / _SELECTIVITY_FACTOR:
                    flags.append(REASON_SELECTIVITY)
            return round(live, 4), flags
        if kind == KIND_STATE_BYTES:
            fn = sm.memory.get(component)
            if fn is None:
                return None, flags
            try:
                return int(fn()), flags
            except Exception:
                return None, flags
        if kind == KIND_COMPILES:
            ent = sm.compile_telemetry.component(component)
            if ent is None:
                return None, flags
            predicted_causes = set(p.get("causes", ()))
            for cause, n in ent.get("causes", {}).items():
                if (
                    n > 0
                    and cause not in predicted_causes
                    and cause not in _BASELINE_CAUSES
                ):
                    flags.append(REASON_RECOMPILE)
                    break
            return ent.get("compiles", 0), flags
        if kind == KIND_DISPATCH:
            j = rt.junctions.get(p["stream"])
            fi = getattr(j, "fused_ingest", None) if j is not None else None
            gr = fi.group_report() if fi is not None else None
            if gr is None:
                return None, flags
            live = gr.get("achieved_dispatch_reduction")
            # shared-state collapse: the plan priced a >=2-query ring but
            # the live group refcounts no ring above 1 (only meaningful
            # once the group has actually fused batches)
            if (
                live is not None
                and any(s["refcount"] >= 2 for s in p.get("shared", ()))
            ):
                live_rc = [
                    s.get("refcount", 0)
                    for s in gr.get("shared_state", ())
                ]
                if not live_rc or max(live_rc) < 2:
                    flags.append(REASON_SHARED_STATE)
            return live, flags
        if kind in (KIND_WIRE_DECLARED, KIND_WIRE_INFERRED):
            sid = p["stream"]
            ent = sm.roofline().get(f"stream.{sid}")
            j = rt.junctions.get(sid)
            fi = getattr(j, "fused_ingest", None) if j is not None else None
            if p.get("narrow") and fi is not None:
                # {} is the permanent full-width fallback; None just means
                # no batch has chosen encodings yet
                narrow = getattr(fi, "_narrow", None)
                if narrow == {}:
                    flags.append(REASON_WIRE_FALLBACK)
            if ent is None:
                return None, flags
            return ent.get("wire_bytes_per_event"), flags
        return None, flags

    def observe(self) -> list[dict]:
        """One entry per prediction with its live counterpart, error ratio
        (raw + EWMA) and any active flags; updates the cumulative mispriced
        counters on flag transitions."""
        pairs: list[dict] = []
        now_active: set = set()
        for (kind, component), p in sorted(self._pred.items()):
            try:
                live, flags = self._live_value(kind, component, p)
            except Exception:
                live, flags = None, []
            ratio = _safe_ratio(live, p.get("predicted"))
            key = (kind, component)
            if ratio is not None:
                prev = self._ewma.get(key)
                self._ewma[key] = round(
                    ratio if prev is None
                    else _EWMA_ALPHA * ratio + (1 - _EWMA_ALPHA) * prev,
                    4,
                )
            for reason in flags:
                fkey = (reason, component)
                now_active.add(fkey)
                if fkey not in self._active:
                    self.mispriced[fkey] = self.mispriced.get(fkey, 0) + 1
                    bb = getattr(self.runtime, "_blackbox", None)
                    if bb is not None:  # mispricing transition = incident
                        bb.fire("calibration", f"{reason} at {component}")
            entry = {
                "kind": kind,
                "component": component,
                "predicted": p.get("predicted"),
                "live": live,
                "ratio": ratio,
                "ratio_ewma": self._ewma.get(key),
            }
            if flags:
                entry["flags"] = flags
            pairs.append(entry)
        self._active = now_active
        return pairs

    # ---- surfaces --------------------------------------------------------

    def report(self) -> dict:
        """The `/calibration(.json)` payload for one app."""
        pairs = self.observe()
        return {
            "app": self.runtime.name,
            "generation": self.generation,
            "pairs": pairs,
            "kinds_paired": sorted(
                {p["kind"] for p in pairs if p["live"] is not None}
            ),
            "flags": sorted(
                {f for p in pairs for f in p.get("flags", ())}
            ),
            "mispriced": [
                {"reason": reason, "component": component, "count": n}
                for (reason, component), n in sorted(self.mispriced.items())
            ],
            "mispriced_total": sum(self.mispriced.values()),
        }

    def prometheus_section(self) -> dict:
        """The `calibration` section of StatisticsManager.report(), feeding
        `siddhi_calibration_error_ratio{kind=,component=}` and
        `siddhi_calibration_mispriced_total` (reporters.py)."""
        pairs = self.observe()
        return {
            "pairs": [
                {
                    "kind": p["kind"],
                    "component": p["component"],
                    "ratio": p["ratio_ewma"],
                }
                for p in pairs
                if p.get("ratio_ewma") is not None
            ],
            "mispriced": [
                {"reason": reason, "component": component, "count": n}
                for (reason, component), n in sorted(self.mispriced.items())
            ],
        }

    def pairs_for_component(self, component: str) -> dict:
        """{kind: pair entry} for one component — explain()'s `calib:`
        lines (observability/explain.py) read this per query/stream node."""
        out = {}
        for p in self.observe():
            if p["component"] == component:
                out[p["kind"]] = p
        return out

    def describe_state(self) -> dict:
        return {
            "generation": self.generation,
            "pairs": len(self._pred),
            "mispriced_total": sum(self.mispriced.values()),
        }


def render_calibration_text(reports: dict) -> str:
    """Plain-text `/calibration` rendering over
    manager.calibration_reports()."""
    lines = []
    for app, rep in sorted(reports.items()):
        lines.append(
            f"app '{app}'  generation={rep['generation']}  "
            f"kinds={','.join(rep['kinds_paired']) or '-'}  "
            f"mispriced={rep['mispriced_total']}"
        )
        for p in rep["pairs"]:
            flag = (
                "  !! " + ",".join(p["flags"]) if p.get("flags") else ""
            )
            lines.append(
                f"  {p['kind']} {p['component']}: "
                f"pred={p['predicted']} live={p['live']} "
                f"x{p['ratio']} ewma={p['ratio_ewma']}{flag}"
            )
        for m in rep["mispriced"]:
            lines.append(
                f"  mispriced {m['reason']} {m['component']}: {m['count']}"
            )
    return "\n".join(lines) + "\n"
