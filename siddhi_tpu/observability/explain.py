"""EXPLAIN ANALYZE for SiddhiQL apps: the analyzer's dataflow graph
annotated with live runtime counters.

The plan is the analyzer's query-level dataflow (`analysis/analyzer.py
collect_flows`: consumed stream ids -> produced stream id per query),
rendered as nodes + edges. With a running app and `@app:statistics`
configured, every node carries live counters:

* stream nodes — events published, 1m EWMA rate, queue depth, fused/
  pipelined engagement, and the fused chunk program's compile ledger;
* query nodes — dispatch count, latency p50/p99, device-time share (this
  query's jitted-step time over the app's total device time), the step
  program's compile ledger (count + causes, observability/profiler.py),
  and selectivity (output-stream events over input-stream events) when
  both ends are metered;
* table / window / aggregation nodes — row counts and fills from
  `describe_state()`.

Surfaces: `runtime.explain()` (text) / `runtime.explain_plan()` (dict),
`/explain` + `/explain.json` on the MetricsServer, and the analysis CLI's
`--explain` mode (static plan: same graph, no live counters). This plan —
which queries share an input stream, how selective each is, where the
device time actually goes — is exactly what a cross-query fusion planner
needs to decide what to compile together (TiLT's plan-level view argument,
PAPERS.md; ROADMAP whole-graph fusion direction).

Best-effort by construction: every annotation source is independently
guarded, so a half-started app, a stats-off app, or a plan the analyzer
would reject (e.g. invalid partition keys) still renders its topology
instead of raising.
"""

from __future__ import annotations

from typing import Optional

from siddhi_tpu.query_api.execution import (
    DeleteStream,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    Query,
    ReturnStream,
    SingleInputStream,
    StateInputStream,
    StreamFunctionHandler,
    UpdateOrInsertStream,
    UpdateStream,
    WindowHandler,
    iter_state_streams,
)


# ---------------------------------------------------------------------------
# labels
# ---------------------------------------------------------------------------


def _handler_labels(s: SingleInputStream) -> list[str]:
    out = []
    for h in s.handlers:
        if isinstance(h, Filter):
            out.append("[filter]")
        elif isinstance(h, WindowHandler):
            w = h.window
            ns = f"{w.namespace}:" if w.namespace else ""
            out.append(f"#window.{ns}{w.name}")
        elif isinstance(h, StreamFunctionHandler):
            ns = f"{h.namespace}:" if h.namespace else ""
            out.append(f"#{ns}{h.name}")
    return out


def _source_label(query: Query) -> str:
    s = query.input_stream
    if isinstance(s, SingleInputStream):
        return " ".join([s.stream_id] + _handler_labels(s))
    if isinstance(s, JoinInputStream):
        return (
            " ".join([s.left.stream_id] + _handler_labels(s.left))
            + f" {s.join_type.value} "
            + " ".join([s.right.stream_id] + _handler_labels(s.right))
        )
    if isinstance(s, StateInputStream):
        ids = [a.stream_id for a in iter_state_streams(s.state)]
        return f"{s.type.value} over " + ", ".join(dict.fromkeys(ids))
    return type(s).__name__


def _sink_label(query: Query) -> str:
    out = query.output_stream
    if isinstance(out, InsertIntoStream):
        return (
            f"insert into {'#' if out.is_inner else ''}{out.target}"
        )
    if isinstance(out, UpdateOrInsertStream):
        return f"update or insert into {out.target}"
    if isinstance(out, UpdateStream):
        return f"update {out.target}"
    if isinstance(out, DeleteStream):
        return f"delete {out.target}"
    if isinstance(out, ReturnStream):
        return "return"
    return type(out).__name__


def _selector_label(query: Query) -> str:
    sel = query.selector
    parts = []
    if sel.select_all:
        parts.append("select *")
    else:
        n_agg = len(sel.selection_list)
        parts.append(f"select {n_agg} attr{'s' if n_agg != 1 else ''}")
    if sel.group_by:
        parts.append(
            "group by " + ",".join(v.attribute for v in sel.group_by)
        )
    if sel.having is not None:
        parts.append("having")
    return " ".join(parts)


# ---------------------------------------------------------------------------
# plan construction
# ---------------------------------------------------------------------------


def _query_index(app) -> dict[str, Query]:
    """qid -> Query AST node via the ONE shared id assignment the runtime
    and the analyzer use (query_api/execution.py assign_execution_ids)."""
    from siddhi_tpu.query_api.execution import assign_execution_ids

    idx: dict[str, Query] = {}
    for ent in assign_execution_ids(app):
        if ent[0] == "query":
            idx[ent[1]] = ent[2]
        else:
            for qid, q in ent[3]:
                idx[qid] = q
    return idx


def build_plan(app, runtime=None) -> dict:
    """The dataflow plan of `app` as {"app", "nodes": [...], "edges":
    [...]}. With `runtime` (a SiddhiAppRuntime), nodes carry live
    counters; without, the plan is purely static (CLI --explain)."""
    from siddhi_tpu.analysis.analyzer import collect_flows

    flows = collect_flows(app)
    qindex = _query_index(app)

    # static cost model + fusion plan (analysis/cost.py, analysis/fusion.py):
    # predicted state bytes / compile counts / selectivity per query, and the
    # per-stream fusable groups — rendered NEXT TO the live counters so the
    # predicted and measured numbers sit on the same line of the same plan.
    # Independently guarded: a cost-model defect must not take down EXPLAIN.
    static_costs: dict = {}
    fusion_summary = None
    try:
        from siddhi_tpu.analysis.cost import compute_costs
        from siddhi_tpu.analysis.fusion import build_fusion_plan
        from siddhi_tpu.analysis.symbols import build_symbols

        _sym = build_symbols(app, [])
        _values = None
        try:
            from siddhi_tpu.analysis.values import analyze_values

            _values = analyze_values(app, _sym)
        except Exception:
            _values = None
        _model = compute_costs(app, _sym, values=_values)
        static_costs = _model.queries
        fusion_summary = build_fusion_plan(
            app, _sym, model=_model, values=_values
        ).summary()
    except Exception:
        pass

    sm = getattr(runtime, "statistics_manager", None) if runtime else None
    ct = sm.compile_telemetry if sm is not None else None

    # plan-vs-actual calibration pairs (observability/calibration.py),
    # indexed by component so each query node renders its `calib:` line
    # beside the `static:` line. Guarded like the cost model above.
    calib_by_comp: dict = {}
    calib_summary = None
    ledger = getattr(runtime, "_calibration", None) if runtime else None
    if ledger is not None:
        try:
            for p in ledger.observe():
                calib_by_comp.setdefault(p["component"], {})[p["kind"]] = p
            calib_summary = {
                "generation": ledger.generation,
                "flags": sorted({
                    f
                    for kinds in calib_by_comp.values()
                    for p in kinds.values()
                    for f in p.get("flags", ())
                }),
                "mispriced": [
                    {"reason": r, "component": c, "count": n}
                    for (r, c), n in sorted(ledger.mispriced.items())
                ],
            }
        except Exception:
            calib_by_comp = {}
            calib_summary = None

    # total device step time across the app: the device-share denominator
    total_dev_ns = 0
    if sm is not None:
        for t in list(sm.device_time.values()):
            if getattr(t, "op", None) in ("step", "fused_step"):
                total_dev_ns += t.total_ns

    nodes: list[dict] = []
    edges: list[dict] = []
    seen_streams: set[str] = set()

    def stream_events(sid: str) -> Optional[int]:
        if sm is None:
            return None
        t = sm.throughput.get(f"stream.{sid}")
        return t.count if t is not None else None

    def add_stream(sid: str) -> str:
        nid = f"stream:{sid}"
        if sid in seen_streams:
            return nid
        seen_streams.add(sid)
        kind = "stream"
        label = sid
        if "#" in sid:  # partition-namespaced inner stream ('partition0#x')
            pid, inner = sid.split("#", 1)
            kind = "inner_stream"
            label = f"#{inner} ({pid})"
        elif sid.startswith("!"):
            kind = "fault_stream"
        node: dict = {"id": nid, "kind": kind, "label": label}
        counters: dict = {}
        ev = stream_events(sid)
        if ev is not None:
            counters["events"] = ev
            counters["rate_1m"] = round(
                sm.throughput[f"stream.{sid}"].rate_1m, 3
            )
        fused_component = f"stream.{sid}.fused"
        if runtime is not None:
            j = runtime.junctions.get(sid)
            if j is not None:
                try:
                    counters["queue_depth"] = j.queued()
                    fi = j.fused_ingest
                    if fi is not None:
                        counters["fused"] = (
                            "pipelined" if fi.pipeline_enabled else "serial"
                        )
                        counters["chunk_batches"] = fi.K
                        # plan-driven group engine: the achieved-vs-predicted
                        # dispatch-reduction ledger (core/fusion_exec.py),
                        # under the cost model's component taxonomy
                        # (stream.<S>.fusedgroup.<g>)
                        fused_component = fi.component
                        gr = fi.group_report()
                        if gr is not None:
                            counters["fusedgroup"] = gr
                        # batch-axis sharded execution (parallel/shard.py):
                        # per-device dispatch/event counts on the stream node
                        sr = getattr(fi, "shard_router", None)
                        if sr is not None:
                            counters["shard"] = sr.describe_state()
                        # compact wire encodings (core/wire.py): per-column
                        # encoder choices + encoded-vs-logical bytes/event,
                        # once the first engaged send chose them
                        if fi._narrow is not None:
                            from siddhi_tpu.core.wire import wire_report

                            counters["wire"] = wire_report(
                                j.schema, getattr(fi, "_keep", None),
                                fi._narrow, fi.wire_spec,
                                capacity=j.batch_size,
                            )
                except Exception:
                    pass
            # event-time watermark (core/watermark.py): the reorder stage's
            # frontier + buffer pressure + late-row tally for this source
            wm = getattr(runtime, "_watermark", None)
            if wm is not None:
                tr = wm.trackers.get(sid)
                if tr is not None:
                    d = tr.describe()
                    counters["watermark"] = {
                        "wm_ms": d["watermark_ms"],
                        "lag_ms": d["lag_ms"],
                        "buffered": d["buffered"],
                        "late": d["late_total"],
                    }
            # black-box recorder (observability/blackbox.py): ring totals
            # + app-wide incident count on every armed stream node
            bb = getattr(runtime, "_blackbox", None)
            if bb is not None:
                bbc = bb.stream_counters(sid)
                if bbc is not None:
                    counters["blackbox"] = bbc
        if ct is not None:
            comp = ct.component(fused_component)
            if comp is not None:
                counters["compile"] = comp
        # wire-kind calibration pairs live under `stream.<sid>`; the
        # fused group's dispatch pair under its plan component
        cp = dict(calib_by_comp.get(f"stream.{sid}", ()))
        cp.update(calib_by_comp.get(fused_component, ()))
        if cp:
            node["calib"] = cp
        if counters:
            node["counters"] = counters
        nodes.append(node)
        return nid

    # aggregation flows carry qids like "aggregation 'A'": render those as
    # aggregation nodes, everything else as query nodes
    for f in flows:
        is_agg = f.qid.startswith("aggregation ")
        if is_agg:
            aid = f.qid.split("'")[1] if "'" in f.qid else f.qid
            nid = f"aggregation:{aid}"
            node = {"id": nid, "kind": "aggregation", "label": aid}
            if runtime is not None:
                ar = runtime.aggregations.get(aid)
                if ar is not None:
                    try:
                        node["counters"] = {"state": ar.describe_state()}
                    except Exception:
                        pass
            nodes.append(node)
        else:
            nid = f"query:{f.qid}"
            q = qindex.get(f.qid)
            node = {
                "id": nid,
                "kind": "query",
                "label": f.qid,
            }
            if q is not None:
                node["source"] = _source_label(q)
                node["selector"] = _selector_label(q)
                node["sink"] = _sink_label(q)
            counters = _query_counters(
                f, runtime, sm, ct, total_dev_ns, stream_events
            )
            if counters:
                node["counters"] = counters
            qc = static_costs.get(f.qid)
            if qc is not None:
                node["static"] = {
                    "state_bytes": qc.state_bytes,
                    "est_selectivity": qc.est_selectivity,
                    "predicted_compiles": qc.predicted_compiles,
                    "programs": [p.to_dict() for p in qc.programs],
                }
            cp = calib_by_comp.get(f"query.{f.qid}")
            if cp:
                node["calib"] = cp
            nodes.append(node)
        for sid in sorted(f.consumes):
            edges.append({"from": add_stream(sid), "to": nid})
        if f.produces is not None:
            edges.append({"from": nid, "to": add_stream(f.produces)})

    # stand-alone definition nodes: tables, named windows, plus streams no
    # flow touched (sources/sinks-only apps still render their topology)
    for sid in app.stream_definitions:
        add_stream(sid)
    for tid in app.table_definitions:
        node = {"id": f"table:{tid}", "kind": "table", "label": tid}
        if runtime is not None:
            t = runtime.tables.get(tid)
            if t is not None:
                try:
                    node["counters"] = {"state": t.describe_state()}
                except Exception:
                    pass
        nodes.append(node)
    for wid in app.window_definitions:
        node = {"id": f"window:{wid}", "kind": "window", "label": wid}
        if runtime is not None:
            nw = runtime.named_windows.get(wid)
            if nw is not None:
                try:
                    node["counters"] = {"state": nw.describe_state()}
                except Exception:
                    pass
        nodes.append(node)

    plan = {
        "app": app.name,
        "analyzed": bool(flows),
        "live": sm is not None,
        "nodes": nodes,
        "edges": edges,
        "fusion": fusion_summary,
    }
    if calib_summary is not None:
        plan["calibration"] = calib_summary
    # churn ledger (core/churn.py): deploy/undeploy/redeploy counters, last
    # splice wall time, and the last state-seed outcome per component —
    # manager-owned, so it survives the runtime this plan annotates
    if runtime is not None:
        try:
            churn = runtime.manager.churn_stats(runtime.name, create=False)
            if churn is not None:
                plan["churn"] = churn.describe_state()
        except Exception:
            pass
    return plan


def _query_counters(
    flow, runtime, sm, ct, total_dev_ns, stream_events
) -> dict:
    counters: dict = {}
    qid = flow.qid
    # partition-axis mesh placement (parallel/shard.py): rendered even with
    # statistics off — placement is topology, not a counter
    shard_rt = getattr(runtime, "_shard", None) if runtime is not None else None
    if shard_rt is not None:
        pl = shard_rt.partitioned.get(qid)
        if pl is not None:
            counters["shard"] = pl
        # key-sharded group-by / join state (parallel/keyshard.py): static
        # placement plus the live per-device key-occupancy gauges
        ks = shard_rt.keyshard.get(qid) or shard_rt.joins.get(qid)
        if ks is not None:
            entry = dict(ks)
            qr = runtime.queries.get(qid)
            ex = getattr(qr, "_keyshard", None) if qr is not None else None
            if ex is not None:
                entry.update(ex.describe_state())
            counters["keyshard"] = entry
    # live lineage fan-in (observability/lineage.py): rendered even with
    # statistics off — @app:lineage has its own gate
    if runtime is not None:
        qr = runtime.queries.get(qid)
        lin = getattr(qr, "lineage", None) if qr is not None else None
        if lin is not None:
            counters["lineage"] = lin.fan_in()
    if sm is None:
        return counters
    lt = sm.latency.get(f"query.{qid}")
    if lt is not None and lt.samples:
        counters["dispatches"] = lt.samples
        p50, p99 = lt.hist.quantiles([0.5, 0.99])
        counters["latency_ms"] = {
            "p50": round(p50 / 1e6, 3),
            "p99": round(p99 / 1e6, 3),
        }
    dt = sm.device_time.get(f"query.{qid}.step")
    if dt is not None and dt.samples:
        counters["device_ms"] = round(dt.total_ns / 1e6, 3)
        if total_dev_ns > 0:
            counters["device_share"] = round(dt.total_ns / total_dev_ns, 3)
    if ct is not None:
        comp = ct.component(f"query.{qid}")
        if comp is not None:
            counters["compile"] = comp
    # selectivity: output events over input events, when both junctions are
    # metered (fused-ingest insert targets with no consumers publish
    # nothing, so absence of the out meter means "unknown", not 0)
    ins = [stream_events(sid) for sid in flow.consumes]
    ins = [v for v in ins if v is not None]
    out_ev = (
        stream_events(flow.produces) if flow.produces is not None else None
    )
    if ins:
        counters["events_in"] = int(sum(ins))
    if out_ev is not None:
        counters["events_out"] = int(out_ev)
        if ins and sum(ins) > 0:
            counters["selectivity"] = round(out_ev / sum(ins), 4)
    return counters


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_counters(c: Optional[dict]) -> str:
    if not c:
        return ""
    parts = []
    for k in (
        "events", "rate_1m", "queue_depth", "fused", "chunk_batches",
        "dispatches", "events_in", "events_out", "selectivity",
        "device_ms", "device_share",
    ):
        if k in c:
            parts.append(f"{k}={c[k]}")
    if "latency_ms" in c:
        lm = c["latency_ms"]
        parts.append(f"p50={lm['p50']}ms p99={lm['p99']}ms")
    if "fusedgroup" in c:
        g = c["fusedgroup"]
        pred = g.get("predicted_dispatch_reduction")
        ach = g.get("achieved_dispatch_reduction")
        parts.append(
            f"fusedgroup[{','.join(g.get('queries', ()))}] "
            f"chunks={g.get('chunks')} "
            f"dispatch {g.get('dispatches_per_chunk_before')}->"
            f"{g.get('dispatches_per_chunk_after')}/chunk"
            + (f" pred=-{pred * 100:.1f}%" if pred is not None else "")
            + (f" meas=-{ach * 100:.1f}%" if ach is not None else "")
            + (
                f" shared={len(g['shared_state'])}"
                if g.get("shared_state") else ""
            )
            + (
                f" residual={len(g['residual'])}"
                if g.get("residual") else ""
            )
        )
    if "shard" in c:
        s = c["shard"]
        if "per_device_dispatches" in s:  # stream node: batch router counts
            parts.append(
                f"shard[devices={s.get('devices')}] "
                f"dispatches={s.get('per_device_dispatches')} "
                f"events={s.get('per_device_events')}"
            )
        elif s.get("sharded"):  # query node: partition-axis mesh placement
            parts.append(
                f"shard[devices={s.get('devices')} axis={s.get('axis')} "
                f"local_slots={s.get('local_slots')}]"
            )
        else:
            parts.append(f"shard[off: {s.get('reason')}]")
    if "keyshard" in c:
        k = c["keyshard"]
        if k.get("sharded", True):
            extra = ""
            if "per_device_keys" in k:
                extra = (
                    f" keys={k['per_device_keys']}"
                    f" skew={k.get('skew')}"
                )
            parts.append(
                f"keyshard[devices={k.get('devices')}"
                f" axis={k.get('axis')}{extra}]"
            )
        else:
            parts.append(f"keyshard[off: {k.get('reason')}]")
    if "wire" in c:
        w = c["wire"]
        encs = " ".join(
            f"{lane}:{label}" for lane, label in w.get("lanes", {}).items()
        )
        parts.append(
            f"wire[{w.get('source')}] {encs} "
            f"{w.get('encoded_B_per_ev')}B/ev (logical "
            f"{w.get('logical_B_per_ev')}B/ev)"
        )
    if "watermark" in c:
        w = c["watermark"]
        parts.append(
            f"watermark[wm={w.get('wm_ms')} lag={w.get('lag_ms')}ms "
            f"buffered={w.get('buffered')} late={w.get('late')}]"
        )
    if "lineage" in c:
        li = c["lineage"]
        parts.append(
            f"lineage[fan-in avg={li.get('avg_inputs_per_output')} "
            f"max={li.get('max_inputs_per_output')} "
            f"outputs={li.get('outputs')}]"
        )
    if "blackbox" in c:
        bb = c["blackbox"]
        w_ms = bb.get("window_ms") or 0
        parts.append(
            f"blackbox[window={w_ms / 1000:g}s rings={bb.get('rings')} "
            f"incidents={bb.get('incidents')}]"
        )
    if "compile" in c:
        comp = c["compile"]
        causes = ",".join(
            f"{k}:{v}" for k, v in sorted(comp.get("causes", {}).items())
        )
        parts.append(
            f"compiles={comp['compiles']}"
            + (f"[{causes}]" if causes else "")
            + f" wall={comp['wall_ms_total']}ms"
        )
    if "state" in c:
        st = c["state"]
        for k in ("rows", "fill", "capacity"):
            if isinstance(st, dict) and k in st:
                parts.append(f"{k}={st[k]}")
    return "  (" + " ".join(parts) + ")" if parts else ""


def _fmt_calib(cp: dict) -> str:
    """One `calib:` line per node: live-over-predicted ratio per paired
    kind (observability/calibration.py), rendered beside the `static:`
    prediction it calibrates."""
    parts = []
    flags: list[str] = []
    for kind, p in sorted(cp.items()):
        if p.get("live") is None:
            continue
        parts.append(
            f"{kind} {p['predicted']}->{p['live']} x{p['ratio']}"
        )
        for f in p.get("flags", ()):
            if f not in flags:
                flags.append(f)
    if flags:
        parts.append("!! " + ",".join(flags))
    return " | ".join(parts)


def render_text(plan: dict) -> str:
    """Human-readable plan: one block per query with its inputs/outputs,
    then the remaining definition nodes."""
    nodes = {n["id"]: n for n in plan["nodes"]}
    in_edges: dict[str, list[str]] = {}
    out_edges: dict[str, list[str]] = {}
    for e in plan["edges"]:
        out_edges.setdefault(e["from"], []).append(e["to"])
        in_edges.setdefault(e["to"], []).append(e["from"])

    lines = [
        f"EXPLAIN{' ANALYZE' if plan.get('live') else ''} — app "
        f"'{plan['app']}'"
        + ("" if plan.get("analyzed") else "  [analyzer unavailable]")
    ]
    linked: set[str] = set()
    for n in plan["nodes"]:
        if n["kind"] not in ("query", "aggregation"):
            continue
        linked.add(n["id"])
        head = f"{n['kind']} {n['label']}"
        if n.get("source"):
            head += f"  <- {n['source']}"
        lines.append(head + _fmt_counters(n.get("counters")))
        if n.get("selector"):
            lines.append(f"    {n['selector']}  |  {n['sink']}")
        st = n.get("static")
        if st is not None:
            progs = ",".join(
                f"{p['component']}~{p['predicted_compiles']}"
                for p in st.get("programs", [])
            )
            lines.append(
                f"    static: state={st['state_bytes']}B "
                f"sel~{st['est_selectivity']} "
                f"compiles~{st['predicted_compiles']}"
                + (f" [{progs}]" if progs else "")
            )
        cp = n.get("calib")
        if cp:
            rendered = _fmt_calib(cp)
            if rendered:
                lines.append(f"    calib: {rendered}")
        for src in sorted(in_edges.get(n["id"], [])):
            sn = nodes.get(src)
            if sn is None:
                continue
            linked.add(src)
            lines.append(
                f"    in  <- {sn['label']}" + _fmt_counters(sn.get("counters"))
            )
            scp = sn.get("calib")
            if scp:
                rendered = _fmt_calib(scp)
                if rendered:
                    lines.append(f"      calib: {rendered}")
        for dst in sorted(out_edges.get(n["id"], [])):
            dn = nodes.get(dst)
            if dn is None:
                continue
            linked.add(dst)
            lines.append(
                f"    out -> {dn['label']}" + _fmt_counters(dn.get("counters"))
            )
    rest = [
        n for n in plan["nodes"]
        if n["id"] not in linked and n["kind"] != "query"
    ]
    if rest:
        lines.append("definitions:")
        for n in sorted(rest, key=lambda n: n["id"]):
            lines.append(
                f"  {n['kind']} {n['label']}" + _fmt_counters(n.get("counters"))
            )
    fusion = plan.get("fusion")
    if fusion:
        if fusion.get("groups"):
            lines.append("fusion plan:")
            for g in fusion["groups"]:
                lines.append(
                    f"  stream {g['stream']}: fuse "
                    f"{', '.join(g['queries'])}  "
                    f"(-{g['est_dispatch_reduction'] * 100:.1f}% dispatch)"
                )
        if fusion.get("shared_state"):
            for s in fusion["shared_state"]:
                lines.append(
                    f"  shared state on {s['stream']}: "
                    f"{', '.join(s['queries'])} "
                    f"(~{s['est_bytes_saved']}B saved)"
                )
        if fusion.get("blockers"):
            for b in fusion["blockers"]:
                lines.append(
                    f"  blocked: {b['query']} on {b['stream']} "
                    f"({b['hazard']})"
                )
        if fusion.get("rewrites"):
            lines.append("rewrites (value analysis):")
            for r in fusion["rewrites"]:
                detail = ", ".join(
                    f"{k}={v}" for k, v in sorted(r.items()) if k != "kind"
                )
                lines.append(f"  {r['kind']}: {detail}")
    calib = plan.get("calibration")
    if calib:
        line = f"calibration: generation={calib.get('generation')}"
        if calib.get("flags"):
            line += f"  flags={','.join(calib['flags'])}"
        lines.append(line)
        for m in calib.get("mispriced", ()):
            lines.append(
                f"  mispriced {m['reason']} {m['component']}: {m['count']}"
            )
    churn = plan.get("churn")
    if churn:
        line = (
            f"churn: deploys={churn.get('deploys', 0)} "
            f"undeploys={churn.get('undeploys', 0)} "
            f"redeploys={churn.get('redeploys', 0)} "
            f"rollbacks={churn.get('rollbacks', 0)}"
        )
        if churn.get("last_splice_ms") is not None:
            line += f" last_splice={churn['last_splice_ms']}ms"
        lines.append(line)
        seed = churn.get("last_seed")
        if seed:
            outcomes = ", ".join(
                f"{k}={v}" for k, v in sorted(seed.items())
            )
            lines.append(f"  last seed: {outcomes}")
    return "\n".join(lines)


def explain(runtime, fmt: str = "text"):
    """`runtime.explain()` entry: the live-annotated plan as rendered text
    (fmt='text') or the raw plan dict (fmt='dict'/'json')."""
    plan = build_plan(runtime.app, runtime=runtime)
    if fmt in ("dict", "json"):
        return plan
    return render_text(plan)


def explain_static(app, fmt: str = "text"):
    """CLI `--explain`: the plan with no runtime (topology only)."""
    plan = build_plan(app, runtime=None)
    if fmt in ("dict", "json"):
        return plan
    return render_text(plan)
