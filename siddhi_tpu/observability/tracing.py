"""Sampled event tracing: spans across junction -> query -> sink dispatch.

A trace is rooted at an ingress dispatch (the first junction publish on a
thread with no active trace) and carries through every synchronous hop the
event chunk makes — downstream junction publishes, query steps, and sink
callbacks each record a child span. The sampling decision is made ONCE at
the root with a seeded RNG (`trace.sample` probability, `trace.seed` for
deterministic runs); an unsampled root parks a sentinel on the thread so
every nested span call is a single attribute check. Completed traces land
in a bounded ring readable at runtime (`runtime.traces()`).

Async ingress severs the sender's thread context by design; traces for
`@async` streams root at the drain worker's junction dispatch instead —
the device-side path is identical.
"""

from __future__ import annotations

import itertools
import json
import random
import threading
import time
from collections import deque

# span layout: [component, depth, n_events, t0_ns, t1_ns]
_SKIP = object()  # token for spans inside an unsampled trace


class _Trace:
    __slots__ = ("trace_id", "wall_ms", "t0_ns", "spans", "open")

    def __init__(self, trace_id: int) -> None:
        self.trace_id = trace_id
        self.wall_ms = int(time.time() * 1000)
        self.t0_ns = time.perf_counter_ns()
        self.spans: list[list] = []
        self.open: list[list] = []

    def to_dict(self) -> dict:
        spans = []
        for s in self.spans:
            d = {
                "component": s[0],
                "depth": s[1],
                "events": s[2],
                "start_us": round((s[3] - self.t0_ns) / 1e3, 1),
                "duration_us": round((s[4] - s[3]) / 1e3, 1),
            }
            if len(s) > 5 and s[5]:
                d.update(s[5])  # annotations (e.g. lineage_seq)
            spans.append(d)
        return {
            "trace_id": self.trace_id,
            "wall_ms": self.wall_ms,
            "spans": spans,
        }


class Tracer:
    """Per-app tracer: sampling decision + span stack + bounded trace ring."""

    def __init__(
        self,
        sample: float,
        capacity: int = 256,
        seed: int | None = None,
    ) -> None:
        if not 0.0 <= sample <= 1.0:
            raise ValueError("trace.sample must be in [0, 1]")
        self.sample = float(sample)
        self.capacity = int(capacity)
        self._rng = random.Random(seed)
        self._ring: deque[_Trace] = deque(maxlen=self.capacity)
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._lock = threading.Lock()
        self.sampled_count = 0
        self.enabled = True

    # ---- span recording (hot path) ---------------------------------------

    def start_span(self, component: str, n_events: int = -1):
        """Open a span; returns a token for `end_span`. On a thread with no
        active trace this IS the root: the sampling decision happens here."""
        tls = self._tls
        cur = getattr(tls, "cur", None)
        if cur is None:
            if not self.enabled or (
                self.sample < 1.0 and self._rng.random() >= self.sample
            ):
                tls.cur = _SKIP
                tls.skip_depth = 1
                return _SKIP
            cur = tls.cur = _Trace(next(self._ids))
            with self._lock:
                self.sampled_count += 1
        elif cur is _SKIP:
            tls.skip_depth += 1
            return _SKIP
        span = [component, len(cur.open), n_events, time.perf_counter_ns(), 0]
        cur.spans.append(span)
        cur.open.append(span)
        return span

    def annotate(self, token, key: str, value) -> None:
        """Attach a key/value annotation to an open span (no-op on a
        skipped trace) — e.g. the publish span's lineage seq range."""
        if token is _SKIP or not isinstance(token, list):
            return
        if len(token) == 5:
            token.append({})
        token[5][key] = value

    def end_span(self, token) -> None:
        tls = self._tls
        if token is _SKIP:
            tls.skip_depth -= 1
            if tls.skip_depth <= 0:
                tls.cur = None
            return
        token[4] = time.perf_counter_ns()
        cur = getattr(tls, "cur", None)
        if cur is None or cur is _SKIP:
            return  # unbalanced end (shutdown race): drop silently
        if cur.open and cur.open[-1] is token:
            cur.open.pop()
        if not cur.open:  # root closed: commit the trace
            tls.cur = None
            with self._lock:
                self._ring.append(cur)

    # ---- reading ----------------------------------------------------------

    def traces(self) -> list[dict]:
        """Completed traces, oldest first (bounded by `trace.capacity`)."""
        with self._lock:
            return [t.to_dict() for t in self._ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.traces(), indent=indent)
