"""Black-box incident recorder & deterministic time-travel replay.

`@app:blackbox(window='30 sec', triggers='slo,crash,dispatch_error,
calibration,admission', keep='8')` arms a continuous flight-data recorder
over the whole app: every junction gets a preallocated columnar ring (the
FlightRecorder arena, plus a parallel seq lane stamped from one app-wide
arrival counter so multi-stream interleave is recoverable), and a base
checkpoint is re-pinned through the snapshot SPI every `window` so ring +
checkpoint always cover a coherent interval. When an armed trigger fires —
an SLO burn alert, an unguarded crash, a junction dispatch failure, a
calibration mispricing transition, an admission shed — the recorder
freezes a versioned **incident bundle** to disk: the trigger and its
wall/event-time marks, the pinned checkpoint bytes, every ring's contents
since the pin in global arrival order, and the app's live observability
surfaces (`/status.json`, `/profile`, `/calibration.json`, `explain()`).

`replay_incident(bundle)` is the other half: rebuild the app from the
bundle's retained AST under `@app:playback`, restore the checkpoint,
re-feed the source-stream rings in recorded seq order on the event-time
clock, and reproduce the live run's emissions byte-identical (the
order-preservation guarantees of the fused/sharded paths make this
CI-diffable under FUSE/SHARD/WIRE). `debug=True` attaches the
`core/debugger.py` step debugger to the rebuilt runtime so the exact
query terminal that misbehaved can be breakpointed mid-replay.

Zero-overhead contract: without the annotation every hook site pays one
`is None` check (the flight/lineage/faults precedent). Retention: `keep`
bundles per app, evicted oldest-first, so disk use is bounded.

Replay scope: streams fed by queries (insert-into targets), engine-fed
streams (selfmon/slo alerts), and fault streams are recorded for
diagnosis but NOT re-fed — the replayed queries regenerate them; only
external source streams are replayed. Apps whose emissions depend on
wall-clock timers past the freeze point, or on live meter values
(SelfMonitorStream/SloAlertStream consumers), fall outside the
byte-identical contract.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import re
import threading
import time
from typing import Optional

import numpy as np

from siddhi_tpu.observability.flight import FlightRecorder, _MAX_FLIGHT_SIZE

logger = logging.getLogger(__name__)

TRIGGERS = ("slo", "crash", "dispatch_error", "calibration", "admission")

DEFAULT_WINDOW_MS = 30_000
DEFAULT_KEEP = 8
DEFAULT_RING = 4096
DEFAULT_DEBOUNCE_MS = 1_000

BUNDLE_VERSION = 1
BLACKBOX_DIR_ENV = "SIDDHI_TPU_BLACKBOX_DIR"

# annotations that must not survive into a replay runtime: the recorder
# itself (no recursive incidents), admission (must not shed replayed
# rows), statistics (no second metrics port), persist/restart (no store,
# no supervisor), and any pre-existing playback config (replaced by ours)
_STRIP_FOR_REPLAY = (
    "app:blackbox",
    "app:statistics",
    "app:admission",
    "app:persist",
    "app:restart",
    "app:playback",
)


# ---------------------------------------------------------------------------
# annotation: one shared rule set (analyzer SA140 + runtime resolver)
# ---------------------------------------------------------------------------


def _time_ms(v) -> Optional[int]:
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    try:
        return SiddhiCompiler.parse_time_constant(str(v))
    except Exception:
        return None


def iter_blackbox_annotation_problems(ann):
    """Yield one message per malformed `@app:blackbox` element — THE
    validation rules, shared by the runtime resolver (raises on the first)
    and the analyzer's SA140 diagnostics (reports them all)."""
    for k, v in ann.elements:
        if k == "window" or k == "checkpoint.interval":
            ms = _time_ms(v)
            if ms is None or ms < 1000:
                yield (
                    f"@app:blackbox {k} '{v}' must be a time constant of "
                    "at least 1 sec"
                )
        elif k == "debounce":
            ms = _time_ms(v)
            if ms is None:
                yield (
                    f"@app:blackbox debounce '{v}' must be a time constant"
                )
        elif k == "triggers":
            names = [t.strip() for t in str(v).split(",") if t.strip()]
            if not names:
                yield "@app:blackbox triggers must name at least one trigger"
            for t in names:
                if t not in TRIGGERS:
                    yield (
                        f"unknown @app:blackbox trigger '{t}' (expected a "
                        f"subset of {', '.join(TRIGGERS)})"
                    )
        elif k == "keep":
            try:
                ok = 1 <= int(v) <= 4096
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@app:blackbox keep '{v}' must be an integer in 1..4096"
                )
        elif k == "ring":
            try:
                ok = 1 <= int(v) <= _MAX_FLIGHT_SIZE
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@app:blackbox ring '{v}' must be an integer in "
                    f"1..{_MAX_FLIGHT_SIZE}"
                )
        elif k == "dir":
            if not str(v).strip():
                yield "@app:blackbox dir must be a non-empty path"
        else:
            yield (
                f"unknown @app:blackbox option '{k if k is not None else v}'"
                " (expected window, triggers, keep, ring, dir, "
                "checkpoint.interval, debounce)"
            )


@dataclasses.dataclass(frozen=True)
class BlackboxConfig:
    window_ms: int = DEFAULT_WINDOW_MS
    triggers: tuple = TRIGGERS
    keep: int = DEFAULT_KEEP
    ring: int = DEFAULT_RING
    dir: Optional[str] = None
    checkpoint_interval_ms: Optional[int] = None  # None -> window_ms
    debounce_ms: int = DEFAULT_DEBOUNCE_MS

    @property
    def interval_ms(self) -> int:
        return self.checkpoint_interval_ms or self.window_ms


def resolve_blackbox_annotation(ann) -> Optional[BlackboxConfig]:
    """BlackboxConfig from `@app:blackbox` (None when absent). Raises
    SiddhiAppCreationError on the first malformed option — the runtime
    analog of the analyzer's SA140 diagnostic."""
    if ann is None:
        return None
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for problem in iter_blackbox_annotation_problems(ann):
        raise SiddhiAppCreationError(problem)
    kw: dict = {}
    for k, v in ann.elements:
        if k == "window":
            kw["window_ms"] = _time_ms(v)
        elif k == "checkpoint.interval":
            kw["checkpoint_interval_ms"] = _time_ms(v)
        elif k == "debounce":
            kw["debounce_ms"] = _time_ms(v)
        elif k == "triggers":
            kw["triggers"] = tuple(
                t.strip() for t in str(v).split(",") if t.strip()
            )
        elif k == "keep":
            kw["keep"] = int(v)
        elif k == "ring":
            kw["ring"] = int(v)
        elif k == "dir":
            kw["dir"] = str(v)
    return BlackboxConfig(**kw)


# ---------------------------------------------------------------------------
# rings
# ---------------------------------------------------------------------------


class SeqCounter:
    """App-wide arrival counter: each recorded row takes one monotone seq
    id, so multi-stream ring contents interleave deterministically at
    replay."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0

    def take(self, n: int) -> int:
        with self._lock:
            base = self.value
            self.value += n
            return base


class BlackboxRing(FlightRecorder):
    """FlightRecorder arena plus a parallel int64 seq lane. The seq block
    for a batch is taken from the shared counter inside `_write` (under
    the ring lock), so seq order equals recorded order per stream and the
    global counter totally orders rows across streams."""

    def __init__(self, schema, interner, size: int, counter: SeqCounter):
        super().__init__(schema, interner, size)
        self._seq = np.zeros((self.size,), np.int64)
        self._counter = counter

    def _write(self, ts, kind, cols, n: int) -> None:
        if n <= 0:
            return
        base = self._counter.take(n)
        seqs = np.arange(base, base + n, dtype=np.int64)
        if n > self.size:  # only the tail survives; match the parent trim
            seqs = seqs[n - self.size:]
        h = self._head
        super()._write(ts, kind, cols, n)
        m = seqs.shape[0]
        first = min(m, self.size - h)
        self._seq[h:h + first] = seqs[:first]
        if first < m:
            self._seq[:m - first] = seqs[first:]

    def sequenced_events(self, min_seq: int = 0) -> list[tuple]:
        """Decode rows with seq >= min_seq, oldest first, as
        (seq, timestamp, data_tuple) triples."""
        from siddhi_tpu.core.event import rows_from_arrays

        with self._lock:
            n = min(self._count, self.size)
            if n == 0:
                return []
            order = (np.arange(n) + (self._head - n)) % self.size
            ts = self._ts[order].copy()
            kind = self._kind[order].copy()
            seq = self._seq[order].copy()
            cols = {name: a[order].copy() for name, a in self._cols.items()}
        keep = np.nonzero(seq >= min_seq)[0]
        if keep.size == 0:
            return []
        ts, kind, seq = ts[keep], kind[keep], seq[keep]
        cols = {k: v[keep] for k, v in cols.items()}
        triples = rows_from_arrays(
            self.schema, ts, kind, cols, int(keep.size), self.interner
        )
        return [
            (int(s), int(t), tuple(d))
            for s, (t, _k, d) in zip(seq, triples)
        ]


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


def _sanitize(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


class BlackboxRecorder:
    """Continuous recorder for one runtime: arms a BlackboxRing on every
    junction as it is created, re-pins a base checkpoint every
    `checkpoint.interval` (default: `window`) on the app scheduler, and
    freezes incident bundles when armed triggers fire."""

    def __init__(self, runtime, config: BlackboxConfig):
        self.runtime = runtime
        self.config = config
        self.seq = SeqCounter()
        self.incidents_total = {t: 0 for t in config.triggers}
        self.bundles: list[dict] = []  # newest last, JSON-safe records
        self.last_incident_id: Optional[str] = None
        self.pins = 0
        self.suppressed = 0  # fires swallowed by the debounce
        self._ordinal = 0
        self._fired_at: dict = {}  # trigger -> last fire wall ms (debounce)
        self._pin: Optional[dict] = None
        self._lock = threading.Lock()
        self._target = self._tick  # stable identity for the scheduler

    # ---- arming ---------------------------------------------------------

    def arm(self, junction) -> None:
        junction.enable_blackbox(self.config.ring, self.seq)
        junction.on_incident = self.fire

    def start(self) -> None:
        """Pin the first checkpoint and schedule the re-pinner (the
        AutoPersist/SelfMonitor recurring-target idiom)."""
        rt = self.runtime
        try:
            self.pin_checkpoint()
        except Exception:
            logger.warning(
                "blackbox: initial checkpoint pin failed", exc_info=True
            )
        rt._scheduler.start()
        rt._scheduler.notify_at(
            rt.clock() + self.config.interval_ms, self._target
        )

    def _tick(self, t_ms: int) -> None:
        rt = self.runtime
        if not rt._running:
            return
        try:
            self.pin_checkpoint()
        except Exception:
            logger.warning("blackbox: checkpoint pin failed", exc_info=True)
        finally:
            if rt._running:
                rt._scheduler.notify_at(
                    rt.clock() + self.config.interval_ms, self._target
                )

    def pin_checkpoint(self) -> None:
        """Snapshot the full app state and mark the arrival counter under
        the process lock, so the checkpoint and the seq watermark agree:
        every row with seq >= the mark arrived after this state."""
        rt = self.runtime
        with rt._process_lock:
            data = rt.snapshot_service.full_snapshot()
            mark = self.seq.value
        pin = {
            "wall_ms": int(time.time() * 1000),
            "event_ms": int(rt.clock()),
            "seq_mark": mark,
            "data": data,
        }
        with self._lock:
            self._pin = pin
            self.pins += 1

    # ---- triggers -------------------------------------------------------

    def fire(self, trigger: str, detail: str = "") -> Optional[str]:
        """One-line trigger hook: freeze an incident bundle unless the
        trigger is unarmed or inside the debounce interval. Never raises
        (the callers are hot/error paths); returns the bundle id or None."""
        if trigger not in self.incidents_total:
            return None
        now = int(time.time() * 1000)
        with self._lock:
            last = self._fired_at.get(trigger)
            if last is not None and now - last < self.config.debounce_ms:
                self.suppressed += 1
                return None
            self._fired_at[trigger] = now
        try:
            return self._freeze(trigger, str(detail), now)
        except Exception:
            logger.warning(
                "blackbox: failed to freeze %s incident", trigger,
                exc_info=True,
            )
            return None

    # ---- freezing -------------------------------------------------------

    def _dir(self) -> str:
        d = (
            self.config.dir
            or os.environ.get(BLACKBOX_DIR_ENV)
            or "incidents"
        )
        return os.path.abspath(d)

    def _freeze(self, trigger: str, detail: str, wall_ms: int) -> str:
        rt = self.runtime
        with self._lock:
            pin = self._pin
            self._ordinal += 1
            ordinal = self._ordinal
        min_seq = pin["seq_mark"] if pin is not None else 0
        rings = {}
        for sid, j in list(rt.junctions.items()):
            bb = j.blackbox
            if bb is None:
                continue
            rings[sid] = {
                "schema": [(n, str(t)) for n, t in j.schema.attrs],
                "events": bb.sequenced_events(min_seq=min_seq),
                "state": bb.describe_state(),
            }

        def _safe(f):
            try:
                return f()
            except Exception as e:  # a broken surface must not block the dump
                return {"error": f"{type(e).__name__}: {e}"}

        event_ms = int(rt.clock())
        iid = f"{wall_ms}_{ordinal:03d}_{trigger}"
        bundle = {
            "version": BUNDLE_VERSION,
            "id": iid,
            "app": rt.name,
            "trigger": trigger,
            "detail": detail,
            "wall_ms": wall_ms,
            "event_ms": event_ms,
            "checkpoint": {
                "wall_ms": pin["wall_ms"] if pin else None,
                "event_ms": pin["event_ms"] if pin else None,
                "seq_mark": min_seq,
                "data": pin["data"] if pin else None,
            },
            "rings": rings,
            "app_ast": pickle.dumps(rt.app),
            "surfaces": {
                "status": _safe(rt.snapshot_status),
                "profile": _safe(rt.profile_report),
                "calibration": _safe(rt.calibration_report),
                "explain": _safe(rt.explain_plan),
            },
            "config": {
                "window_ms": self.config.window_ms,
                "triggers": list(self.config.triggers),
                "keep": self.config.keep,
                "ring": self.config.ring,
            },
        }
        path = self._write_bundle(bundle)
        record = {
            "id": iid,
            "app": rt.name,
            "trigger": trigger,
            "detail": detail,
            "wall_ms": wall_ms,
            "event_ms": event_ms,
            "path": path,
            "events": sum(len(r["events"]) for r in rings.values()),
        }
        with self._lock:
            self.incidents_total[trigger] += 1
            self.bundles.append(record)
            del self.bundles[: -self.config.keep]
            self.last_incident_id = iid
        logger.warning(
            "blackbox: incident %s frozen (trigger=%s detail=%s) -> %s",
            iid, trigger, detail, path,
        )
        return iid

    def _write_bundle(self, bundle: dict) -> str:
        d = self._dir()
        os.makedirs(d, exist_ok=True)
        prefix = f"incident_{_sanitize(self.runtime.name)}_"
        path = os.path.join(d, f"{prefix}{bundle['id']}.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(bundle, f)
        os.replace(tmp, path)
        # oldest-first eviction over this app's bundles: disk use stays
        # bounded at `keep` bundles even across restarts
        mine = sorted(
            fn for fn in os.listdir(d)
            if fn.startswith(prefix) and fn.endswith(".pkl")
        )
        for fn in mine[: max(0, len(mine) - self.config.keep)]:
            try:
                os.remove(os.path.join(d, fn))
            except OSError:
                pass
        return path

    # ---- surfaces -------------------------------------------------------

    def incident_index(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self.bundles]

    def describe_state(self) -> dict:
        with self._lock:
            return {
                "window_ms": self.config.window_ms,
                "triggers": list(self.config.triggers),
                "keep": self.config.keep,
                "ring": self.config.ring,
                "dir": self._dir(),
                "pins": self.pins,
                "suppressed": self.suppressed,
                "incidents": dict(self.incidents_total),
                "bundles": [
                    {k: r[k] for k in ("id", "trigger", "wall_ms", "path")}
                    for r in self.bundles
                ],
            }

    def stream_counters(self, stream_id: str) -> Optional[dict]:
        """The explain() stream-node payload:
        blackbox[window=30s rings=N incidents=K]."""
        rt = self.runtime
        j = rt.junctions.get(stream_id)
        bb = j.blackbox if j is not None else None
        if bb is None:
            return None
        rings = sum(
            1 for jj in rt.junctions.values() if jj.blackbox is not None
        )
        return {
            "window_ms": self.config.window_ms,
            "rings": rings,
            "incidents": sum(self.incidents_total.values()),
            "events": bb.describe_state()["total"],
        }


# ---------------------------------------------------------------------------
# bundles on disk
# ---------------------------------------------------------------------------


def load_bundle(path: str) -> dict:
    with open(path, "rb") as f:
        bundle = pickle.load(f)
    v = bundle.get("version")
    if v != BUNDLE_VERSION:
        raise ValueError(
            f"incident bundle version {v!r} is not supported "
            f"(expected {BUNDLE_VERSION})"
        )
    return bundle


def bundle_summary(bundle: dict) -> dict:
    """JSON-safe view of a bundle (checkpoint bytes and pickled AST
    elided) — what `/incidents/<id>.json` serves."""
    cp = bundle.get("checkpoint") or {}
    return {
        "version": bundle.get("version"),
        "id": bundle.get("id"),
        "app": bundle.get("app"),
        "trigger": bundle.get("trigger"),
        "detail": bundle.get("detail"),
        "wall_ms": bundle.get("wall_ms"),
        "event_ms": bundle.get("event_ms"),
        "checkpoint": {
            "wall_ms": cp.get("wall_ms"),
            "event_ms": cp.get("event_ms"),
            "seq_mark": cp.get("seq_mark"),
            "bytes": len(cp.get("data") or b""),
        },
        "rings": {
            sid: {
                "schema": r.get("schema"),
                "events": len(r.get("events") or []),
                "state": r.get("state"),
            }
            for sid, r in (bundle.get("rings") or {}).items()
        },
        "surfaces": bundle.get("surfaces"),
        "config": bundle.get("config"),
    }


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------


def _source_streams(app) -> set:
    """Stream ids a replay must re-feed: externally-fed streams only.
    Query outputs are regenerated by the replayed queries, fault streams
    (`!S`) and engine-fed monitor streams are produced by the engine."""
    fed = set()
    for elem in app.execution_elements:
        qs = getattr(elem, "queries", None)
        queries = qs if qs is not None else [elem]
        for q in queries:
            out = getattr(q, "output_stream", None)
            target = getattr(out, "target", "")
            if target:
                fed.add(target)
    sources = set()
    for sid in app.stream_definitions:
        if sid in fed or sid.startswith("!") or sid.startswith("#"):
            continue
        sources.add(sid)
    return sources


def _replay_app(bundle: dict):
    """The bundle's retained AST, re-annotated for deterministic replay:
    strip recorder/admission/statistics/supervision, add @app:playback."""
    from siddhi_tpu.query_api.annotation import Annotation

    app = pickle.loads(bundle["app_ast"])
    strip = set(_STRIP_FOR_REPLAY)
    app.annotations = [
        a for a in app.annotations if a.name.lower() not in strip
    ]
    app.annotations.append(Annotation("app:playback"))
    return app


def attach_emission_collector(rt, streams=None) -> dict:
    """Register stream callbacks that append `(timestamp, data_tuple)`
    rows per stream — one canonical shape for both the live run and the
    replay, so emissions diff byte-identical. Engine-fed monitor streams
    (live meter values, never deterministic) are excluded by default."""
    from siddhi_tpu.observability.selfmon import SELFMON_STREAM_ID
    from siddhi_tpu.observability.slo import SLO_STREAM_ID

    skip = {SELFMON_STREAM_ID, SLO_STREAM_ID}
    if streams is None:
        streams = [
            sid for sid in rt.stream_schemas
            if sid not in skip and not sid.startswith("#")
        ]
    out: dict = {sid: [] for sid in streams}

    def _mk(sid):
        rows = out[sid]

        def _cb(events):
            rows.extend((int(e[0]), tuple(e[1])) for e in events)

        return _cb

    for sid in streams:
        rt.add_callback(sid, _mk(sid))
    return out


def emissions_checksum(emissions: dict) -> str:
    """sha256 over the canonical repr of per-stream emission rows — the
    CI diff key for the byte-identical replay contract."""
    import hashlib

    h = hashlib.sha256()
    for sid in sorted(emissions):
        h.update(sid.encode())
        for ts, data in emissions[sid]:
            h.update(repr((ts, data)).encode())
    return h.hexdigest()


class IncidentReplay:
    """A rebuilt, checkpoint-restored runtime ready to re-feed the
    bundle's rings. `feed()` drives the replay; `emissions` collects
    per-stream rows; `debugger` is a SiddhiDebugger when requested."""

    def __init__(self, bundle: dict, debug: bool = False, streams=None):
        from siddhi_tpu.core.manager import SiddhiManager

        self.bundle = bundle
        self.manager = SiddhiManager()
        self.runtime = self.manager.create_siddhi_app_runtime(
            _replay_app(bundle)
        )
        self.debugger = self.runtime.debug() if debug else None
        self.emissions = attach_emission_collector(self.runtime, streams)
        data = (bundle.get("checkpoint") or {}).get("data")
        if data:
            self.runtime.restore(data)
        self.runtime.start()
        self.events_fed = 0
        self._fed = False

    def feed(self) -> dict:
        """Re-feed source-stream ring rows in global seq order on the
        playback clock, then advance event time to the freeze mark so
        event-time timers up to the incident fire. Returns emissions."""
        if self._fed:
            return self.emissions
        self._fed = True
        rt = self.runtime
        sources = _source_streams(rt.app)
        rows = []
        for sid, ring in (self.bundle.get("rings") or {}).items():
            if sid not in sources:
                continue
            for seq, ts, data in ring["events"]:
                rows.append((seq, sid, ts, data))
        rows.sort(key=lambda r: r[0])
        self.events_fed = len(rows)
        handlers: dict = {}
        i = 0
        while i < len(rows):  # contiguous same-stream runs keep seq order
            j = i
            sid = rows[i][1]
            while j < len(rows) and rows[j][1] == sid:
                j += 1
            h = handlers.get(sid)
            if h is None:
                h = handlers[sid] = rt.get_input_handler(sid)
            h.send_many(
                [r[3] for r in rows[i:j]],
                timestamps=[r[2] for r in rows[i:j]],
            )
            i = j
        event_ms = self.bundle.get("event_ms")
        clock = getattr(rt, "_playback_clock", None)
        if event_ms is not None and clock is not None:
            clock.advance(int(event_ms))
        return self.emissions

    def checksum(self) -> str:
        return emissions_checksum(self.emissions)

    def close(self) -> None:
        try:
            self.manager.shutdown()
        except Exception:
            pass


def replay_incident(bundle, debug: bool = False, streams=None):
    """Deterministically replay an incident bundle (a dict, or a path to
    one on disk). Default: feed everything, shut the replay runtime down,
    return the IncidentReplay (emissions/checksum populated). With
    `debug=True` the runtime is left live with a SiddhiDebugger attached
    and NOT yet fed — set breakpoints, then call `.feed()` (from a worker
    thread if you intend to step) and `.close()` yourself."""
    if isinstance(bundle, str):
        bundle = load_bundle(bundle)
    replay = IncidentReplay(bundle, debug=debug, streams=streams)
    if not debug:
        try:
            replay.feed()
        finally:
            replay.close()
    return replay
