"""Metrics + introspection endpoint: a tiny stdlib `http.server` serving
every app registered on a SiddhiManager.

Routes:
  /metrics        Prometheus text format (version 0.0.4) — scrape this
  /metrics.json   the raw report() dicts, one per app
  /traces         sampled trace spans per app (JSON)
  /status         live engine state, human-readable text
  /status.json    live engine state (junction queue depths, window fills,
                  NFA instance counts, pipeline occupancy, error store)
  /flight         flight-recorder rings per app/stream (JSON)
  /lineage        event lineage & provenance summary, human-readable text
  /lineage.json   per-stream seq arenas + per-query fan-in + recent
                  resolved provenance chains (observability/lineage.py)
  /profile        continuous profiler: compile telemetry (count/cause/wall
                  per program), slowest-chunk waterfalls, p99.99s (JSON)
  /explain        EXPLAIN ANALYZE: the dataflow plan annotated with live
                  counters, human-readable text
  /explain.json   the raw plan dicts (nodes + edges) per app
  /calibration    plan-vs-actual calibration ledger, human-readable text
  /calibration.json  every static prediction paired with its live meter:
                  error ratios + EWMA drift, mispricing reason codes
                  (observability/calibration.py)
  /slo            SLO burn rates per objective, human-readable text
  /slo.json       multi-window burn rates + budget left per @app:slo
                  objective (observability/slo.py)
  /incidents(.json)  black-box incident index per app: frozen bundle ids,
                  triggers and on-disk paths (observability/blackbox.py)
  /incidents/<id>.json  one bundle's JSON-safe summary: trigger, marks,
                  checkpoint coverage, ring contents sizes, captured
                  status/profile/calibration/explain surfaces

Started by `manager.serve_metrics(port)` (idempotent; port 0 picks an
ephemeral port and returns it). No dependency beyond the stdlib — the
environment bakes no prometheus_client, and the text format is stable.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    def __init__(self, manager, host: str = "127.0.0.1", port: int = 9464):
        self.manager = manager
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # keep scrapes out of stderr
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        body = outer._prometheus().encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif path == "/metrics.json":
                        body = json.dumps(
                            outer._reports(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/traces":
                        body = json.dumps(
                            outer._traces(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/status":
                        body = outer.manager.status_text().encode()
                        ctype = "text/plain; charset=utf-8"
                    elif path == "/status.json":
                        body = json.dumps(
                            outer.manager.snapshot_status(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/flight":
                        body = json.dumps(
                            outer.manager.flight_records(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/lineage":
                        body = outer.manager.lineage_text().encode()
                        ctype = "text/plain; charset=utf-8"
                    elif path == "/lineage.json":
                        body = json.dumps(
                            outer.manager.lineage_reports(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/profile":
                        body = json.dumps(
                            outer.manager.profile_reports(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/explain":
                        body = outer.manager.explain_text().encode()
                        ctype = "text/plain; charset=utf-8"
                    elif path == "/explain.json":
                        body = json.dumps(
                            outer.manager.explain_reports(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/calibration":
                        body = outer.manager.calibration_text().encode()
                        ctype = "text/plain; charset=utf-8"
                    elif path == "/calibration.json":
                        body = json.dumps(
                            outer.manager.calibration_reports(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path == "/slo":
                        body = outer.manager.slo_text().encode()
                        ctype = "text/plain; charset=utf-8"
                    elif path == "/slo.json":
                        body = json.dumps(
                            outer.manager.slo_reports(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path in ("/incidents", "/incidents.json"):
                        body = json.dumps(
                            outer.manager.incidents(), default=str
                        ).encode()
                        ctype = "application/json"
                    elif path.startswith("/incidents/"):
                        iid = path[len("/incidents/"):]
                        if iid.endswith(".json"):
                            iid = iid[: -len(".json")]
                        detail = outer.manager.incident_detail(iid)
                        if detail is None:
                            self.send_error(404)
                            return
                        body = json.dumps(detail, default=str).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # a bad metric must not 500 forever
                    # send_error writes a complete, Content-Length-framed
                    # response; the previous raw write after end_headers()
                    # left keep-alive scrapers waiting on an unframed body
                    self.send_error(500, explain=str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"siddhi-metrics:{self.port}",
        )
        self._thread.start()

    def _reports(self) -> list[dict]:
        return self.manager.observability_reports()

    def _prometheus(self) -> str:
        # the manager's renderer, not render_prometheus(reports) directly:
        # the supervisor / admission / churn families live OUTSIDE the
        # per-app statistics registries (they meter apps with statistics
        # off too) and were invisible to scrapes otherwise
        return self.manager.prometheus_text()

    def _traces(self) -> dict:
        return {
            rt.name: rt.traces()
            for rt in list(self.manager._runtimes.values())
            if getattr(rt, "tracer", None) is not None
        }

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)
