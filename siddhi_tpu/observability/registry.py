"""StatisticsManager: per-app metric registry + periodic reporter thread.

Reference: util/statistics/metrics/SiddhiStatisticsManager.java:35-80
(Dropwizard MetricRegistry + reporters), enabled by
`@app:statistics(reporter=..., interval=..., trace.sample=...)`
(SiddhiAppParser.java:106-142) and toggled at runtime
(SiddhiAppRuntime.enableStats :682). Metric naming follows
util/SiddhiConstants.java METRIC_* conventions (`stream.S`, `query.q`,
`table.T`, `sink.S`, ...).

The registry IS the enable gate: every tracker it hands out checks
`registry.enabled` on the hot path, so `enable_stats(False)` stops
collection (not just reporting) with one attribute read per event batch.
"""

from __future__ import annotations

import threading
from typing import Optional

from siddhi_tpu.observability.metrics import (
    BufferedEventsTracker,
    LatencyTracker,
    ThroughputTracker,
)


class JunctionDeviceStats:
    """Device-budget trackers for one junction's dispatch path: fused-step
    dispatch time, h2d wire traffic, and d2h truth-sync stalls (the engine's
    live version of what bench.py's `timebudget` leg reconstructs offline)."""

    __slots__ = (
        "step", "h2d_bytes", "h2d_chunks", "h2d_events", "h2d_logical",
        "sync_stall",
    )

    def __init__(self, registry: "StatisticsManager", component: str) -> None:
        self.step = registry.device_time_tracker(component, "fused_step")
        self.h2d_bytes = registry.device_counter(component, "h2d_bytes")
        self.h2d_chunks = registry.device_counter(component, "h2d_chunks")
        # events shipped over the wire alongside h2d_bytes: the live
        # roofline attribution (bytes/event) the compact-wire-encoding
        # work targets (BENCH r04 `*_wire_B_per_ev`, but always-on)
        self.h2d_events = registry.device_counter(component, "h2d_events")
        # what the FULL-WIDTH wire would have carried for the same events
        # (core/wire.py logical_row_bytes): the logical side of the
        # logical-vs-encoded bytes/event split
        self.h2d_logical = registry.device_counter(
            component, "h2d_logical_bytes"
        )
        self.sync_stall = registry.device_time_tracker(component, "sync_stall")


class PipelineStats:
    """Per-stage budget of one junction's pipelined fused ingest
    (core/pipeline.py): encode / h2d / dispatch / drain histograms plus the
    measured overlap ratio `pipeline.occupancy` — summed stage busy time
    over send wall time, so 1.0 means fully serial stages and values above
    1.0 mean the pipeline genuinely overlapped them (upper bound: the
    number of concurrently busy stages)."""

    __slots__ = (
        "encode", "h2d", "dispatch", "drain", "depth", "_wall_ns", "_lock",
        "_gate",
    )

    def __init__(self, registry: "StatisticsManager", component: str) -> None:
        self.encode = registry.device_time_tracker(component, "pipeline.encode")
        self.h2d = registry.device_time_tracker(component, "pipeline.h2d")
        self.dispatch = registry.device_time_tracker(
            component, "pipeline.dispatch"
        )
        self.drain = registry.device_time_tracker(component, "pipeline.drain")
        self.depth = 0  # configured max in-flight chunks (0 = pipeline off)
        self._wall_ns = 0
        self._lock = threading.Lock()
        self._gate = registry

    def add_wall(self, ns: int) -> None:
        """Accumulate one pipelined send's wall-clock (the occupancy
        denominator)."""
        if not self._gate.enabled:
            return
        with self._lock:
            self._wall_ns += int(ns)

    def occupancy(self) -> float:
        wall = self._wall_ns
        if wall <= 0:
            return 0.0
        busy = (
            self.encode.total_ns
            + self.h2d.total_ns
            + self.dispatch.total_ns
            + self.drain.total_ns
        )
        return busy / wall


class StatisticsManager:
    """Registry of trackers + reporter thread (one per app runtime)."""

    def __init__(
        self,
        app_name: str,
        reporter: str = "console",
        interval_s: float = 60.0,
        options: Optional[dict] = None,
        tracer=None,
    ):
        self.app_name = app_name
        self.reporter = reporter
        self.interval_s = float(interval_s)
        self.options = dict(options or {})
        self.tracer = tracer
        self.throughput: dict[str, ThroughputTracker] = {}
        self.latency: dict[str, LatencyTracker] = {}
        self.buffered: dict[str, BufferedEventsTracker] = {}
        # failed dispatches / sink publishes per component; per-subscriber
        # attribution keys are `<component>.subscriber.<name>` with the
        # structured (component, subscriber) pair kept on the tracker
        self.errors: dict[str, ThroughputTracker] = {}
        # name -> () -> bytes; the TPU-native analog of the reference's
        # ObjectSizeCalculator memory metric (util/statistics/memory/):
        # device-buffer bytes held by each component's carried state
        self.memory: dict[str, callable] = {}
        # device-time budget: `<component>.<op>` -> histogram / counter
        self.device_time: dict[str, LatencyTracker] = {}
        self.device_counters: dict[str, ThroughputTracker] = {}
        # pipelined fused ingest: component -> PipelineStats (stage
        # histograms ride device_time; occupancy/depth are gauges here)
        self.pipeline: dict[str, PipelineStats] = {}
        # sharded execution (parallel/shard.py): component -> router-like
        # object with describe_state() -> per-device dispatch/event counts
        # + occupancy; rendered as the siddhi_shard_* Prometheus families
        self.shard: dict[str, object] = {}
        # event-time robustness (core/watermark.py): () -> the watermark
        # runtime's describe_state() — per-stream watermarks/lag, late-event
        # meters, lateness histograms; rendered as the siddhi_watermark_* /
        # siddhi_late_* / siddhi_lateness_ms Prometheus families
        self.watermark_fn = None
        # plan-vs-actual calibration (observability/calibration.py): () ->
        # the ledger's prometheus section — error-ratio pairs + cumulative
        # mispriced counters; rendered as siddhi_calibration_* families
        self.calibration_fn = None
        # SLO burn rates (observability/slo.py): () -> the engine's
        # prometheus section; rendered as siddhi_slo_burn_rate
        self.slo_fn = None
        # continuous profiler: compile telemetry + per-chunk stage
        # waterfalls (observability/profiler.py), gated by this registry
        from siddhi_tpu.observability.profiler import (
            CompileTelemetry,
            Profiler,
        )

        self.compile_telemetry = CompileTelemetry(gate=self)
        self.profiler = Profiler(gate=self)
        self.enabled = True
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reporter_obj = None

    # ---- tracker factories -------------------------------------------------

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        t = self.throughput.get(name)
        if t is None:
            t = self.throughput[name] = ThroughputTracker(name, gate=self)
        return t

    def latency_tracker(self, name: str) -> LatencyTracker:
        t = self.latency.get(name)
        if t is None:
            t = self.latency[name] = LatencyTracker(name, gate=self)
        return t

    def buffered_tracker(self, name: str) -> BufferedEventsTracker:
        return self.buffered.setdefault(name, BufferedEventsTracker(name))

    def error_tracker(
        self, name: str, subscriber: Optional[str] = None
    ) -> ThroughputTracker:
        key = f"{name}.subscriber.{subscriber}" if subscriber else name
        t = self.errors.get(key)
        if t is None:
            t = self.errors[key] = ThroughputTracker(key, gate=self)
            t.component = name
            t.subscriber = subscriber
        return t

    def register_memory(self, name: str, fn) -> None:
        """fn() -> device bytes held by the named component's state."""
        self.memory[name] = fn

    def device_time_tracker(self, component: str, op: str) -> LatencyTracker:
        key = f"{component}.{op}"
        t = self.device_time.get(key)
        if t is None:
            t = self.device_time[key] = LatencyTracker(key, gate=self)
            t.component = component
            t.op = op
        return t

    def device_counter(self, component: str, op: str) -> ThroughputTracker:
        key = f"{component}.{op}"
        t = self.device_counters.get(key)
        if t is None:
            t = self.device_counters[key] = ThroughputTracker(key, gate=self)
            t.component = component
            t.subscriber = None
            t.op = op
        return t

    def junction_device_stats(self, component: str) -> JunctionDeviceStats:
        return JunctionDeviceStats(self, component)

    def pipeline_stats(self, component: str) -> PipelineStats:
        p = self.pipeline.get(component)
        if p is None:
            p = self.pipeline[component] = PipelineStats(self, component)
        return p

    def register_shard(self, component: str, router) -> None:
        """Attach a shard router (parallel/shard.py BatchShardRouter) whose
        describe_state() feeds the report's `shard` section and the
        siddhi_shard_* Prometheus families."""
        self.shard[component] = router

    def register_watermark(self, fn) -> None:
        """Attach the @app:watermark runtime's describe_state supplier; it
        feeds the report's `watermark` section and the watermark/lateness
        Prometheus families."""
        self.watermark_fn = fn

    def register_calibration(self, fn) -> None:
        """Attach the CalibrationLedger's prometheus-section supplier; it
        feeds the report's `calibration` section and the
        siddhi_calibration_* Prometheus families."""
        self.calibration_fn = fn

    def register_slo(self, fn) -> None:
        """Attach the SloEngine's prometheus-section supplier; it feeds the
        report's `slo` section and siddhi_slo_burn_rate."""
        self.slo_fn = fn

    def roofline(self) -> dict:
        """Live per-stream wire roofline: bytes/event over the fused h2d
        path plus the 1-minute h2d throughput in MB/s — the always-on
        version of bench r04's roofline attribution, the signal the
        compact-wire-encoding work targets. Keyed by component
        (`stream.<id>`); empty until a fused send ships bytes."""
        out: dict = {}
        for key, t in list(self.device_counters.items()):
            if getattr(t, "op", None) != "h2d_bytes" or t.count <= 0:
                continue
            comp = t.component
            ev = self.device_counters.get(f"{comp}.h2d_events")
            n_ev = ev.count if ev is not None else 0
            lg = self.device_counters.get(f"{comp}.h2d_logical_bytes")
            n_lg = lg.count if lg is not None else 0
            entry = {
                "h2d_bytes": t.count,
                "h2d_events": n_ev,
                "h2d_logical_bytes": n_lg,
                "h2d_mb_s_1m": round(t.rate_1m / 1e6, 3),
            }
            if n_ev > 0:
                # the encoded-vs-logical split (core/wire.py): encoded is
                # what actually crossed the link, logical is the full-width
                # equivalent; their ratio is the live wire reduction
                entry["wire_bytes_per_event"] = round(t.count / n_ev, 3)
                if n_lg > 0:
                    entry["wire_logical_bytes_per_event"] = round(
                        n_lg / n_ev, 3
                    )
                    entry["wire_reduction"] = round(n_lg / t.count, 3)
            out[comp] = entry
        return out

    # ---- reporting ---------------------------------------------------------

    def report(self) -> dict:
        # snapshot each registry dict with one atomic list() first: trackers
        # are created lazily from dispatch threads (first subscriber failure,
        # first store query, ...) while scrape/reporter threads read, and a
        # Python-level comprehension over a mutating dict raises
        mem = {}
        for n, fn in list(self.memory.items()):
            try:
                mem[n] = int(fn())
            except Exception:
                mem[n] = -1
        throughput = list(self.throughput.items())
        latency = list(self.latency.items())
        buffered = list(self.buffered.items())
        errors = list(self.errors.items())
        device_time = list(self.device_time.items())
        device_counters = list(self.device_counters.items())
        pipeline = list(self.pipeline.items())
        rep = {
            "app": self.app_name,
            "throughput": {n: t.count for n, t in throughput},
            "rates": {
                n: {"m1": round(t.rate_1m, 3), "m5": round(t.rate_5m, 3)}
                for n, t in throughput
            },
            # back-compat key (pre-histogram shape) beside the summaries
            "latency_avg_ms": {
                n: round(t.avg_ms, 3) for n, t in latency
            },
            "latency_ms": {
                n: t.summary_ms() for n, t in latency
            },
            "buffered": {n: t.get_size() for n, t in buffered},
            "errors": {n: t.count for n, t in errors},
            "errors_detail": {
                n: {
                    "component": t.component or n,
                    "subscriber": t.subscriber,
                    "count": t.count,
                }
                for n, t in errors
            },
            "memory_bytes": mem,
            "device": {
                "time_ms": {
                    n: {
                        "component": t.component,
                        "op": t.op,
                        "summary": t.summary_ms(),
                    }
                    for n, t in device_time
                },
                "counters": {
                    n: {"component": t.component, "op": t.op, "count": t.count}
                    for n, t in device_counters
                },
            },
            "pipeline": {
                n: {"occupancy": round(p.occupancy(), 3), "depth": p.depth}
                for n, p in pipeline
            },
            "shard": {
                n: r.describe_state() for n, r in list(self.shard.items())
            },
            "watermark": (
                self.watermark_fn() if self.watermark_fn is not None else {}
            ),
            "roofline": self.roofline(),
            # compile-cause taxonomy totals (observability/profiler.py):
            # promoted out of /profile so a recompile storm is alertable as
            # siddhi_compiles_total{cause=,component=}
            "compiles": {
                n: {"compiles": e["compiles"], "causes": dict(e["causes"])}
                for n, e in self.compile_telemetry.report().items()
            },
            "traces_sampled": (
                self.tracer.sampled_count if self.tracer is not None else 0
            ),
        }
        # advisory sections must never take a scrape down with them
        if self.calibration_fn is not None:
            try:
                rep["calibration"] = self.calibration_fn()
            except Exception:
                rep["calibration"] = {}
        if self.slo_fn is not None:
            try:
                rep["slo"] = self.slo_fn()
            except Exception:
                rep["slo"] = {}
        return rep

    def prometheus_text(self) -> str:
        from siddhi_tpu.observability.reporters import render_prometheus

        return render_prometheus([self.report()])

    def profile_report(self) -> dict:
        """The app's `/profile` payload: compile ledger per program, the
        top-K slowest chunk waterfalls, and the high quantiles (p99/p999/
        p9999) of every latency + device-time histogram."""

        def highs(trackers) -> dict:
            out = {}
            for n, t in trackers:
                h = t.hist
                if h.count == 0:
                    continue
                p99, p999, p9999 = h.quantiles([0.99, 0.999, 0.9999])
                out[n] = {
                    "count": h.count,
                    "p99": round(p99 / 1e6, 4),
                    "p999": round(p999 / 1e6, 4),
                    "p9999": round(p9999 / 1e6, 4),
                }
            return out

        return {
            "app": self.app_name,
            "compile": self.compile_telemetry.report(),
            "waterfalls": self.profiler.report(),
            "latency_high_ms": highs(list(self.latency.items())),
            "device_time_high_ms": highs(list(self.device_time.items())),
            "roofline": self.roofline(),
        }

    def start_reporting(self) -> None:
        if self._thread is not None:
            return
        from siddhi_tpu.observability.reporters import make_reporter

        self._reporter_obj = make_reporter(
            self.reporter, self.app_name, self.options
        )
        if self._reporter_obj is None:
            return  # pull-based (prometheus) or disabled (none)
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                if self.enabled:
                    try:
                        self._reporter_obj.emit(self.report())
                    except Exception:
                        import logging

                        logging.getLogger(__name__).exception(
                            "stats reporter for app '%s' raised", self.app_name
                        )

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop_reporting(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None
        if self._reporter_obj is not None:
            self._reporter_obj.close()
            self._reporter_obj = None
