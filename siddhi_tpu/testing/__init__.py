"""Testing utilities: the deterministic fault-injection harness used by the
supervised-runtime recovery tests and the CI chaos leg (see `faults`)."""

from siddhi_tpu.testing.faults import (  # noqa: F401
    FaultPlan,
    FaultRule,
    InjectedFault,
    install,
    parse_plan,
    uninstall,
)

__all__ = [
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "install",
    "parse_plan",
    "uninstall",
]
