"""Deterministic fault injection: the chaos half of the supervised runtime.

A `FaultPlan` is a seeded set of rules over NAMED INJECTION SITES — fixed
points in the engine where real production failures originate. Each site
calls `faults.hit(site, key)` on its hot path; with no plan installed that
is one module-attribute check (`ACTIVE is None`), so the engine pays
nothing in normal operation. With a plan installed, matching rules count
the hit and deterministically decide whether to raise.

Sites (the key passed at each):

    sink_publish        "<app>:<stream>"  Sink.publish_guarded, raises
                        ConnectionUnavailableError by default so the sink's
                        on.error policy engages exactly like a real outage
    junction_dispatch   "<stream>:<subscriber>"  StreamJunction._fan_out,
                        inside the guarded region so @OnError policies own
                        the failure when configured
    device_dispatch     "<component>"  the fused chunk program dispatch
                        (core/ingest.py _dispatch_chunk)
    drain_worker        "<stream>"  @async drain workers and the pipelined
                        ingest drain (poison-batch path)
    persist_save        "<app>"  persistence-store save
    persist_load        "<app>"  persistence-store load
    churn_splice        "<app>:+<qid>" / "<app>:-<qid>"  the hot deploy/
                        undeploy splice critical section (core/churn.py);
                        an injected fault proves the rollback-to-pre-churn
                        contract
    churn_restore       "<app>"(redeploy) / "<app>:<qid>"(add_query seed)
                        state restore through the snapshot SPI during churn
    ingest_disorder     "<app>:<stream>"  the input-handler feed
                        (app_runtime.get_input_handler); rules carrying a
                        `jitter=<ms>` budget TRANSFORM instead of raise:
                        each row's timestamp is perturbed by uniform(0,
                        jitter) and the batch re-sorted by the perturbed
                        keys — a seeded within-bound shuffle, the
                        adversary the @app:watermark reorder stage must
                        exactly undo (core/watermark.py parity gate)

Determinism: rules fire by hit count (`after` skips the first N matching
hits, `times` bounds how often the rule fires), optionally thinned by a
probability `p` drawn from a `random.Random(seed:site:index)` — the same
plan over the same call sequence always fails at the same points. Counting
is lock-protected; multi-threaded call ORDER is the caller's to pin down
(single-threaded feeds in tests).

Activation: `install(plan)` / `uninstall()` from code, or the
`SIDDHI_TPU_FAULTS` environment variable (parsed once at import, so
subprocess chaos runs need no API access):

    SIDDHI_TPU_FAULTS="seed=42;junction_dispatch:after=10,times=2;sink_publish@Out:p=0.2,times=-1"

Rule grammar: `site[@key_substring]:opt=val[,opt=val...]` joined by `;`,
with opts `after`, `times` (-1 = forever), `p`, `error` (`fault` raises
InjectedFault, `conn` raises ConnectionUnavailableError), `jitter` (ms;
makes the rule a timestamp-shuffle transform for the `ingest_disorder`
site instead of an error). A bare `seed=N` entry seeds the plan.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
from typing import Optional


class InjectedFault(RuntimeError):
    """An error raised by the fault-injection harness (never by real code)."""


@dataclasses.dataclass
class FaultRule:
    site: str
    match: str = ""          # substring filter over the site key ("" = any)
    after: int = 0           # skip the first `after` matching hits
    times: Optional[int] = 1  # fire at most this many times (None = forever)
    p: float = 1.0           # thinning probability once past `after`
    error: Optional[str] = None  # 'fault' | 'conn' (None = site default)
    jitter: Optional[int] = None  # ms; transform rule (shuffle), not a raise
    hits: int = 0
    fired: int = 0


# sites whose real-world failure mode is a transport outage default to
# ConnectionUnavailableError so the engine's retry/on.error machinery engages
_CONN_SITES = frozenset({"sink_publish"})


class FaultPlan:
    """Seeded, deterministic failure schedule over the named sites."""

    def __init__(self, rules: list[FaultRule], seed: int = 0) -> None:
        self.rules = list(rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rngs = [
            random.Random(f"{self.seed}:{r.site}:{i}")
            for i, r in enumerate(self.rules)
        ]
        self.log: list[tuple[str, str]] = []  # (site, key) per fired fault

    def check(self, site: str, key: str = "") -> None:
        """Count one hit at `site`; raise when a matching rule fires."""
        for i, r in enumerate(self.rules):
            if r.site != site or r.jitter is not None or (
                r.match and r.match not in key
            ):
                continue
            with self._lock:
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.p < 1.0 and self._rngs[i].random() >= r.p:
                    continue
                r.fired += 1
                self.log.append((site, key))
            kind = r.error or ("conn" if site in _CONN_SITES else "fault")
            if kind == "conn":
                from siddhi_tpu.core.errors import ConnectionUnavailableError

                raise ConnectionUnavailableError(
                    f"injected fault at {site} ({key})"
                )
            raise InjectedFault(f"injected fault at {site} ({key})")

    def permute(self, site: str, key: str, timestamps) -> Optional[list]:
        """Count one hit at `site` against the TRANSFORM rules (those with a
        `jitter` budget); return a permutation of range(len(timestamps))
        that re-sorts the batch by jitter-perturbed timestamps, or None
        when no rule fires. Each row's sort key is its timestamp plus
        uniform(0, jitter) from the rule's seeded RNG, so a row is never
        displaced behind rows more than `jitter` ms newer — the shuffle
        stays within the disorder bound a watermark of `bound >= jitter`
        must fully absorb. Stacked rules compose left to right."""
        perm: Optional[list] = None
        for i, r in enumerate(self.rules):
            if r.site != site or r.jitter is None or (
                r.match and r.match not in key
            ):
                continue
            with self._lock:
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.p < 1.0 and self._rngs[i].random() >= r.p:
                    continue
                r.fired += 1
                self.log.append((site, key))
                ts = (
                    timestamps if perm is None
                    else [timestamps[j] for j in perm]
                )
                keys = [
                    int(t) + self._rngs[i].random() * r.jitter for t in ts
                ]
            step = sorted(range(len(keys)), key=keys.__getitem__)
            perm = step if perm is None else [perm[j] for j in step]
        return perm

    def report(self) -> dict:
        """Fired/hit counts per rule (test assertions + chaos-run logs)."""
        return {
            "seed": self.seed,
            "rules": [
                {
                    "site": r.site, "match": r.match, "after": r.after,
                    "times": r.times, "p": r.p,
                    "hits": r.hits, "fired": r.fired,
                }
                for r in self.rules
            ],
            "fired_total": sum(r.fired for r in self.rules),
        }


def parse_plan(spec: str) -> FaultPlan:
    """Parse the SIDDHI_TPU_FAULTS grammar into a FaultPlan (see module
    docstring). Raises ValueError on malformed specs — a chaos run with a
    typo'd plan must fail loudly, not run fault-free."""
    seed = 0
    rules: list[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[5:])
            continue
        head, sep, opts_s = part.partition(":")
        if not sep:
            raise ValueError(f"fault rule '{part}' needs ':opt=val' options")
        site, _, match = head.partition("@")
        kw: dict = {"site": site.strip(), "match": match.strip()}
        for opt in opts_s.split(","):
            k, sep, v = opt.partition("=")
            if not sep:
                raise ValueError(f"fault option '{opt}' is not k=v")
            k = k.strip()
            v = v.strip()
            if k == "after":
                kw["after"] = int(v)
            elif k == "times":
                kw["times"] = None if int(v) < 0 else int(v)
            elif k == "p":
                kw["p"] = float(v)
                if not 0.0 < kw["p"] <= 1.0:
                    raise ValueError(f"fault p={v} must be in (0, 1]")
            elif k == "error":
                if v not in ("fault", "conn"):
                    raise ValueError(f"fault error='{v}' (fault|conn)")
                kw["error"] = v
            elif k == "jitter":
                kw["jitter"] = int(v)
                if kw["jitter"] <= 0:
                    raise ValueError(f"fault jitter={v} must be a positive ms")
            else:
                raise ValueError(f"unknown fault option '{k}'")
        rules.append(FaultRule(**kw))
    return FaultPlan(rules, seed=seed)


# the active plan; hot paths check `ACTIVE is not None` before calling hit()
ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    global ACTIVE
    ACTIVE = plan
    return plan


def uninstall() -> Optional[FaultPlan]:
    global ACTIVE
    plan, ACTIVE = ACTIVE, None
    return plan


def hit(site: str, key: str = "") -> None:
    """Injection-site hook: no-op without a plan; otherwise may raise."""
    plan = ACTIVE
    if plan is not None:
        plan.check(site, key)


def permutation(site: str, key: str, timestamps) -> Optional[list]:
    """Transform-site hook: a shuffle permutation over the batch, or None
    (no plan / no matching jitter rule / nothing to shuffle)."""
    plan = ACTIVE
    if plan is None or len(timestamps) < 2:
        return None
    return plan.permute(site, key, timestamps)


# env activation: parsed once at import so subprocess chaos legs need no API
_env = os.environ.get("SIDDHI_TPU_FAULTS")
if _env:
    ACTIVE = parse_plan(_env)
