"""Compact wire encodings: analyzer-chosen per-column codecs for the h2d link.

The r04 roofline attribution showed the headline ingest legs are
TRANSFER-bound, not compute-bound: `filter_window_avg` shipped 12 B/event
over a ~54 MB/s h2d link while the device could sustain 3x the delivered
rate. This module attacks the bytes, not the kernel (TiLT's
compile-to-compact-representation, PAPERS.md): the analysis package selects
per-column wire encodings STATICALLY from the declared types and value
ranges, the host encodes into the compact form, and the matching decode is
fused into the already-jitted chunk program (core/ingest.py) — bytes stay
compressed across the link and the host never materializes wide columns.

Encoders (per lane of the fused wire):

* ``narrow``  — integer downcast (int64 -> int32/int16/int8). Chosen
  statically from a declared `@app:wire(range.S.col='lo..hi')` contract, or
  sampled from the first engaged send (the pre-existing
  `StreamSchema.propose_narrow` behavior, kept as the fallback).
* ``dict``    — per-chunk dictionary encoding for low-cardinality
  string/interned columns (`@app:wire(dict.S.col='N')`): each micro-batch
  ships uint8/uint16 codes plus an N-slot dictionary of the original int32
  ids; decode is a device-side gather.
* ``delta``   — per-batch base + consecutive diffs for declared-monotone
  int/long columns (`@app:wire(delta.S.col='int16')`), reconstructed with a
  device cumsum — the same trick the built-in timestamp lane (`__tsd__`)
  already plays, extended to payload columns (event-time seqs, counters).
* ``bitpack`` — BOOL columns ride 1 bit/value (np.packbits on the host,
  shift-and-mask unpack on device). Always safe, applied whenever wire
  encoding is enabled; no hint needed.

Every encoder is guarded per chunk: a batch that violates the static
assumption (value out of the declared range, dictionary cardinality
overflow, delta outside the narrow dtype) raises `WireNarrowMisfit` and the
sender rebuilds the chunk program FULL-WIDTH (once, permanent) — the same
fallback path the sampled narrow wire has always used — so emissions are
byte-identical encode-on vs encode-off.

Toggle: `@app:wire(disable='true')` on the app, overridden process-wide by
SIDDHI_TPU_WIRE=1 (force on) / SIDDHI_TPU_WIRE=0 (force off: the wire ships
FULL-WIDTH lanes — no narrowing, no sampling — which is what the CI parity
step diffs against). The annotation is validated here (the runtime analog
of the analyzer's SA132, one shared rule set like SA125-SA131).

The per-stream `WireSpec` (versioned) is also emitted into the FusionPlan
(analysis/fusion.py `plan.wire`) so the static contract — which encoder
serves which column, and the predicted logical-vs-encoded bytes/event — is
inspectable before any runtime exists.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

from siddhi_tpu.core.types import AttrType, PHYSICAL_DTYPE

WIRE_ENV = "SIDDHI_TPU_WIRE"

# value-analysis inferred encoders (analysis/values.py): default ON; set
# SIDDHI_TPU_WIRE_INFER=0 to fall back to declared @app:wire hints only.
# Independent of WIRE_ENV: inference chooses encoders, WIRE_ENV gates
# whether any encoder runs at all.
WIRE_INFER_ENV = "SIDDHI_TPU_WIRE_INFER"

WIRE_SPEC_VERSION = 1

_TRUE = ("1", "on", "true", "force")
_FALSE = ("0", "off", "false")

# hint kinds accepted as `@app:wire(<kind>.<Stream>.<col>='...')`
_HINT_KINDS = ("range", "dict", "delta")

_DELTA_DTYPES = {
    "true": np.dtype(np.int16),  # delta.S.col='true' -> default int16 diffs
    "int8": np.dtype(np.int8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
}

_INTEGRAL = (AttrType.INT, AttrType.LONG)
_INTERNED = (AttrType.STRING, AttrType.OBJECT)


def wire_env_override() -> Optional[bool]:
    """Process-wide wire-encoding toggle: True (forced on), False (forced
    off), or None (defer to the app's @app:wire annotation)."""
    v = os.environ.get(WIRE_ENV, "").strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    return None


def wire_inference_enabled() -> bool:
    """Whether inferred wire hints (analysis/values.py) overlay the
    declared ones. On by default; SIDDHI_TPU_WIRE_INFER=0 disables."""
    return os.environ.get(WIRE_INFER_ENV, "").strip().lower() not in _FALSE


def _parse_range(v) -> Optional[tuple[int, int]]:
    try:
        lo_s, hi_s = str(v).split("..", 1)
        lo, hi = int(lo_s), int(hi_s)
    except (TypeError, ValueError):
        return None
    return (lo, hi) if lo <= hi else None


def iter_wire_annotation_problems(ann, streams: Optional[dict] = None):
    """Yield one message per malformed `@app:wire` element — THE validation
    rules, shared by the runtime resolver (raises on the first) and the
    analyzer's SA132 diagnostics (reports them all), so the two can never
    drift. With `streams` (the analyzer's symbol table: sid -> {attr ->
    AttrType}), hint targets are also checked for existence and encoder/type
    compatibility."""
    for k, v in ann.elements:
        if k == "disable":
            if str(v).strip().lower() not in ("true", "false"):
                yield f"@app:wire disable '{v}' must be true or false"
            continue
        if k is None:
            yield (
                f"unknown @app:wire option '{v}' (expected disable, "
                "range.<stream>.<col>, dict.<stream>.<col>, "
                "delta.<stream>.<col>)"
            )
            continue
        parts = str(k).split(".")
        if len(parts) != 3 or parts[0] not in _HINT_KINDS:
            yield (
                f"unknown @app:wire option '{k}' (expected disable, "
                "range.<stream>.<col>, dict.<stream>.<col>, "
                "delta.<stream>.<col>)"
            )
            continue
        kind, sid, col = parts
        if kind == "range":
            if _parse_range(v) is None:
                yield (
                    f"@app:wire {k} '{v}' must be 'lo..hi' with integer "
                    "lo <= hi"
                )
        elif kind == "dict":
            try:
                ok = 2 <= int(v) <= 65536
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@app:wire {k} '{v}' must be an integer dictionary "
                    "capacity in 2..65536"
                )
        elif kind == "delta":
            if str(v).strip().lower() not in _DELTA_DTYPES:
                yield (
                    f"@app:wire {k} '{v}' must be true, int8, int16, or "
                    "int32"
                )
        if streams is None:
            continue
        schema = streams.get(sid)
        if sid not in streams:
            yield f"@app:wire {k}: unknown stream '{sid}'"
            continue
        if schema is None:
            continue  # open schema: attribute checks are skipped
        if col not in schema:
            yield f"@app:wire {k}: stream '{sid}' has no attribute '{col}'"
            continue
        t = schema[col]
        if t is None:
            continue
        if kind in ("range", "delta") and t not in _INTEGRAL:
            yield (
                f"@app:wire {k}: '{col}' is {t.name}; {kind} encoding "
                "needs an INT or LONG column"
            )
        elif kind == "dict" and t not in _INTEGRAL + _INTERNED:
            yield (
                f"@app:wire {k}: '{col}' is {t.name}; dict encoding needs "
                "a STRING/OBJECT (interned) or INT/LONG column"
            )


def parse_wire_hints(ann) -> dict:
    """(stream_id, col) -> hint tuple from a (validated) `@app:wire`
    annotation: ("range", lo, hi) | ("dict", card) | ("delta", np.dtype).
    Malformed elements are skipped (the validator reports them)."""
    hints: dict = {}
    if ann is None:
        return hints
    for k, v in ann.elements:
        if k is None or k == "disable":
            continue
        parts = str(k).split(".")
        if len(parts) != 3 or parts[0] not in _HINT_KINDS:
            continue
        kind, sid, col = parts
        if kind == "range":
            r = _parse_range(v)
            if r is not None:
                hints[(sid, col)] = ("range",) + r
        elif kind == "dict":
            try:
                card = int(v)
            except (TypeError, ValueError):
                continue
            if 2 <= card <= 65536:
                hints[(sid, col)] = ("dict", card)
        elif kind == "delta":
            dt = _DELTA_DTYPES.get(str(v).strip().lower())
            if dt is not None:
                hints[(sid, col)] = ("delta", dt)
    return hints


def resolve_wire_annotation(ann) -> tuple[bool, dict]:
    """(enabled, hints) for one app from its `@app:wire` annotation (or
    None) plus the SIDDHI_TPU_WIRE env override. Raises
    SiddhiAppCreationError on malformed options — the runtime analog of the
    analyzer's SA132 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    enabled = True
    hints: dict = {}
    if ann is not None:
        for problem in iter_wire_annotation_problems(ann):
            raise SiddhiAppCreationError(problem)
        enabled = (
            str(ann.element("disable", "false")).strip().lower() != "true"
        )
        hints = parse_wire_hints(ann)
    env = wire_env_override()
    if env is not None:
        enabled = env
    return enabled, hints


# ---------------------------------------------------------------------------
# WireSpec: the static per-stream encoding choice
# ---------------------------------------------------------------------------


def _narrow_for_range(lo: int, hi: int, wide: np.dtype) -> Optional[np.dtype]:
    """Smallest integer dtype covering the DECLARED [lo, hi] contract (no
    sampling margin — out-of-range values hit the runtime guard)."""
    for nd in (np.int8, np.int16, np.int32):
        dt = np.dtype(nd)
        if dt.itemsize >= wide.itemsize:
            return None
        info = np.iinfo(dt)
        if lo >= info.min and hi <= info.max:
            return dt
    return None


@dataclasses.dataclass
class WireSpec:
    """Versioned static wire-encoding choice for one stream.

    `encodings` maps lane names (attribute names; "__tsd__" for the
    timestamp-delta lane) to normalized entries:
    ("narrow", np.dtype) | ("dict", code np.dtype, card) |
    ("delta", np.dtype) | ("bitpack",). Lanes absent from the map ride
    full-width."""

    stream_id: str
    encodings: dict = dataclasses.field(default_factory=dict)
    source: str = "static"
    version: int = WIRE_SPEC_VERSION
    # lanes whose encoding was PROVEN by value analysis rather than
    # declared via @app:wire (provenance for the plan + explain())
    inferred_lanes: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        out = {
            "version": self.version,
            "stream": self.stream_id,
            "source": self.source,
            "encodings": {
                lane: encoding_label(e)
                for lane, e in sorted(self.encodings.items())
            },
        }
        if self.inferred_lanes:
            out["inferred_lanes"] = sorted(self.inferred_lanes)
        return out


def encoding_label(entry) -> str:
    """Human/JSON-stable label for one encoding entry (also used by
    explain() and the FusionPlan wire section)."""
    if isinstance(entry, np.dtype) or not isinstance(entry, tuple):
        return f"narrow:{np.dtype(entry).name}"
    kind = entry[0]
    if kind == "narrow":
        return f"narrow:{np.dtype(entry[1]).name}"
    if kind == "dict":
        return f"dict:{np.dtype(entry[1]).name}[{entry[2]}]"
    if kind == "delta":
        return f"delta:{np.dtype(entry[1]).name}"
    if kind == "bitpack":
        return "bitpack:1bit"
    return str(entry)


def _hint_entry(hint, t: AttrType, wide: np.dtype) -> Optional[tuple]:
    """Encoding entry for one hint tuple against one declared type, or
    None when the hint does not apply / does not shrink the lane."""
    if hint is None:
        return None
    if hint[0] == "range" and t in _INTEGRAL:
        dt = _narrow_for_range(int(hint[1]), int(hint[2]), wide)
        if dt is not None:
            return ("narrow", dt)
    elif hint[0] == "dict" and t in _INTEGRAL + _INTERNED:
        card = int(hint[1])
        code = np.dtype(np.uint8 if card <= 256 else np.uint16)
        if code.itemsize < wide.itemsize:
            return ("dict", code, card)
    elif hint[0] == "delta" and t in _INTEGRAL:
        dt = np.dtype(hint[1])
        if dt.itemsize < wide.itemsize:
            return ("delta", dt)
    return None


def build_wire_spec(
    stream_id: str,
    attrs,
    hints: dict,
    capacity: Optional[int] = None,
    inferred: Optional[dict] = None,
) -> Optional[WireSpec]:
    """Static per-stream spec from declared attribute types + `@app:wire`
    hints, optionally overlaid with value-analysis `inferred` hints (same
    (sid, col) -> hint-tuple shape; a DECLARED hint wins its lane — the
    user's contract beats a proof, and both ride the same per-chunk misfit
    guard, so a wrong proof can only cost a full-width rebuild, never
    wrong bytes). `attrs` is [(name, AttrType)] (StreamSchema.attrs or the
    analyzer's schema items). With `capacity` (the micro-batch row count
    each chunk amortizes a dictionary/delta header over) an encoding is
    kept only when its amortized bytes/row actually undercut the wide
    lane — e.g. dict.col='64' on an int32 column at batch 64 would SHIP
    64 codes + a 256-byte dictionary per chunk (320 B vs 256 B full
    width), so it is dropped. Returns None when nothing is statically
    encodable (the sampled narrow wire then stands alone)."""
    enc: dict = {}
    inferred_lanes: list = []
    for name, t in attrs:
        if t is None:
            continue
        wide = np.dtype(PHYSICAL_DTYPE[t])
        entry = None
        from_inference = False
        if t is AttrType.BOOL:
            # 1 bit/value, lossless, guard-free: on whenever wire
            # encoding is enabled
            entry = ("bitpack",)
        else:
            entry = _hint_entry(hints.get((stream_id, name)), t, wide)
            if entry is None and inferred is not None:
                entry = _hint_entry(
                    inferred.get((stream_id, name)), t, wide
                )
                from_inference = entry is not None
        if entry is None:
            continue
        if capacity is not None and lane_bytes_per_row(
            name, wide, entry, capacity
        ) >= wide.itemsize:
            continue  # net loss at this chunk shape: stay wide
        enc[name] = entry
        if from_inference:
            inferred_lanes.append(name)
    if not enc:
        return None
    declared = [
        lane for lane in enc
        if lane not in inferred_lanes and enc[lane][0] != "bitpack"
    ]
    source = "static"
    if inferred_lanes:
        source = "static+inferred" if declared else "inferred"
    return WireSpec(
        stream_id, enc, source=source, inferred_lanes=inferred_lanes
    )


def app_wire_specs(
    app, sym_streams: dict, stream_ids, capacity: int,
    inferred: Optional[dict] = None,
):
    """(disabled, {sid: (attrs, spec)}) for the given consumed streams —
    ONE preamble (annotation fetch, disable parse, hint parsing, spec
    building with the optional inferred overlay) shared by the analyzer's
    SA133/SA138 lint (analysis/cost.py) and the FusionPlan wire section
    (analysis/fusion.py), so hint resolution can never drift between
    them. Streams with open/unknown schemas are skipped."""
    from siddhi_tpu.query_api.annotation import find_annotation

    ann = find_annotation(app.annotations, "app:wire")
    disabled = ann is not None and str(
        ann.element("disable", "false")
    ).strip().lower() == "true"
    hints = parse_wire_hints(ann)
    if not wire_inference_enabled():
        inferred = None
    out: dict = {}
    for sid in stream_ids:
        schema = sym_streams.get(sid)
        if not schema or any(t is None for t in schema.values()):
            continue
        attrs = list(schema.items())
        out[sid] = (
            attrs, build_wire_spec(sid, attrs, hints, capacity, inferred)
        )
    return disabled, out


def choose_encodings(
    schema,
    keep,
    spec: Optional[WireSpec],
    enabled: bool,
    ts_sample,
    cols_sample,
) -> dict:
    """The one place the wire-encoding decision is made for an engaging
    fused ingest: disabled -> {} (FULL-WIDTH wire, no sampling, no
    narrowing — the parity baseline); enabled -> the sampled narrow map
    (`propose_narrow`, the pre-existing behavior) overlaid with the static
    spec's entries (static wins per lane: a declared contract beats a
    sample)."""
    if not enabled:
        return {}
    enc = schema.propose_narrow(ts_sample, cols_sample, keep)
    if spec is not None:
        for lane, entry in spec.encodings.items():
            if keep is not None and lane not in keep and lane != "__tsd__":
                continue
            enc[lane] = entry
    return enc


def encodings_source(enc: dict, spec: Optional[WireSpec]) -> str:
    """'full-width' | 'sampled' | 'static' | 'static+sampled' — for
    describe_state()/explain()."""
    if not enc:
        return "full-width"
    has_static = any(isinstance(e, tuple) for e in enc.values())
    has_sampled = any(not isinstance(e, tuple) for e in enc.values())
    if has_static and has_sampled:
        return "static+sampled"
    return "static" if has_static else "sampled"


def logical_row_bytes(attrs) -> int:
    """Full-width bytes/event the h2d link would carry with NO wire
    encoding (the packed per-batch codec: int64 ts + every column at its
    physical width) — the roofline's logical numerator."""
    total = 8  # int64 timestamp
    for _name, t in attrs:
        total += np.dtype(PHYSICAL_DTYPE[t or AttrType.LONG]).itemsize
    return total


def estimate_wire_bytes(
    attrs, spec: Optional[WireSpec], capacity: int = 8192
) -> int:
    """Static per-event estimate of the encoded wire (tsd int32 default —
    sampling may shrink it further at runtime), for the FusionPlan wire
    section and SA133."""
    enc = dict(spec.encodings) if spec is not None else {}
    total = 4.0  # __tsd__ int32 default
    for name, t in attrs:
        wide = np.dtype(PHYSICAL_DTYPE[t or AttrType.LONG])
        total += lane_bytes_per_row(name, wide, enc.get(name), capacity)
    return int(round(total))


def lane_bytes_per_row(
    name: str, wide: np.dtype, entry, capacity: int
) -> float:
    """Amortized wire bytes/row of one lane under an encoding entry."""
    if entry is None:
        return wide.itemsize
    if not isinstance(entry, tuple):
        return np.dtype(entry).itemsize
    kind = entry[0]
    if kind == "narrow":
        return np.dtype(entry[1]).itemsize
    if kind == "dict":
        return np.dtype(entry[1]).itemsize + entry[2] * wide.itemsize / max(
            capacity, 1
        )
    if kind == "delta":
        return np.dtype(entry[1]).itemsize + 8.0 / max(capacity, 1)
    if kind == "bitpack":
        return 0.125
    return wide.itemsize


# ---------------------------------------------------------------------------
# the generalized codec builder (hosts encode, device decode)
# ---------------------------------------------------------------------------


def _normalize(entry) -> tuple:
    """Plain dtypes (the sampled-narrow legacy form) normalize to
    ("narrow", dtype); tuples pass through."""
    if isinstance(entry, tuple):
        return entry
    return ("narrow", np.dtype(entry))


def _lane_nbytes(kind: str, cap: int, wire_dt, wide_dt, card: int) -> int:
    if kind == "dict":
        return cap * wire_dt.itemsize + card * wide_dt.itemsize
    if kind == "delta":
        return 8 + cap * wire_dt.itemsize
    if kind == "bitpack":
        return -(-cap // 8)
    return cap * wire_dt.itemsize  # narrow / wide


def build_codec(schema, capacity: int, keep, narrow: dict):
    """The fused-ingest wire codec: encode(ts, cols, n) -> (buf u8[total],
    base int64); decode(buf, n, base) -> EventBatch. Generalizes the
    original narrow-downcast codec with the dict/delta/bitpack encoders;
    `narrow` maps lane names to encoding entries (plain np.dtype = legacy
    narrow downcast). Invoked through `StreamSchema.wire_codec` (which owns
    the cache); see that docstring for the wire-shrinking contract."""
    import jax
    import jax.numpy as jnp

    from siddhi_tpu.core.event import (
        EventBatch,
        WireNarrowMisfit,
        _bitcast_split,
    )
    from siddhi_tpu.core.types import null_value

    narrow = {k: _normalize(v) for k, v in (narrow or {}).items()}
    cap = int(capacity)
    kept = [
        (name, t) for name, t in schema.attrs
        if keep is None or name in keep
    ]
    dropped = [
        (name, t) for name, t in schema.attrs
        if not (keep is None or name in keep)
    ]

    # (lane, kind, wire dtype, decoded dtype, dict card)
    tsd_entry = narrow.get("__tsd__", ("narrow", np.dtype(np.int32)))
    sections: list[tuple] = [(
        "__tsd__", "narrow", np.dtype(tsd_entry[1]), np.dtype(np.int32), 0
    )]
    for name, t in kept:
        wide = np.dtype(PHYSICAL_DTYPE[t])
        entry = narrow.get(name)
        if entry is None:
            sections.append((name, "wide", wide, wide, 0))
            continue
        kind = entry[0]
        if kind == "narrow":
            sections.append((name, "narrow", np.dtype(entry[1]), wide, 0))
        elif kind == "dict":
            sections.append(
                (name, "dict", np.dtype(entry[1]), wide, int(entry[2]))
            )
        elif kind == "delta":
            sections.append((name, "delta", np.dtype(entry[1]), wide, 0))
        elif kind == "bitpack":
            sections.append((name, "bitpack", np.dtype(np.uint8), wide, 0))
        else:
            sections.append((name, "wide", wide, wide, 0))
    offsets = []
    off = 0
    for _name, kind, wire_dt, wide_dt, card in sections:
        offsets.append(off)
        off += _lane_nbytes(kind, cap, wire_dt, wide_dt, card)
    total = off

    tsd_diff = sections[0][2].itemsize < 4  # narrow tsd = diff-coded

    def _check_fits(src, dt: np.dtype, name: str) -> None:
        if src.size == 0:
            return
        info = np.iinfo(dt)
        if int(src.min()) < info.min or int(src.max()) > info.max:
            raise WireNarrowMisfit(name)

    def encode(timestamps: np.ndarray, cols: dict, n: int):
        base = np.int64(timestamps[0]) if n > 0 else np.int64(0)
        buf = np.zeros((total,), dtype=np.uint8)
        for (name, kind, dt, wide, card), o in zip(sections, offsets):
            if name == "__tsd__":
                ts64 = timestamps[:n].astype(np.int64, copy=False)
                if n > 0 and (
                    int(ts64.max()) - int(base) >= (1 << 31)
                    or int(ts64.min()) - int(base) < -(1 << 31)
                ):
                    raise ValueError(
                        "wire_codec: timestamp span exceeds int32 deltas "
                        "(>~24.8 days per batch); use packed_codec"
                    )
                src = (
                    np.diff(ts64, prepend=base) if tsd_diff
                    else ts64 - base
                )
                if dt.itemsize < 4:
                    _check_fits(src, dt, name)
                buf[o : o + cap * dt.itemsize].view(dt)[:n] = src.astype(
                    dt, copy=False
                )
                continue
            src = np.asarray(cols[name])[:n]
            if kind == "wide":
                buf[o : o + cap * dt.itemsize].view(dt)[:n] = src.astype(
                    dt, copy=False
                )
            elif kind == "narrow":
                if dt.itemsize < wide.itemsize:
                    _check_fits(src, dt, name)
                buf[o : o + cap * dt.itemsize].view(dt)[:n] = src.astype(
                    dt, copy=False
                )
            elif kind == "dict":
                # per-chunk dictionary: codes + the batch's unique values;
                # cardinality overflow = the runtime guard (full-width
                # fallback), so a mis-declared stream stays correct
                uniq, inv = np.unique(src, return_inverse=True)
                if uniq.size > card:
                    raise WireNarrowMisfit(name)
                codes = buf[o : o + cap * dt.itemsize].view(dt)
                if n > 0:
                    codes[:n] = inv.astype(dt, copy=False)
                vals = buf[
                    o + cap * dt.itemsize
                    : o + cap * dt.itemsize + card * wide.itemsize
                ].view(wide)
                vals[: uniq.size] = uniq.astype(wide, copy=False)
            elif kind == "delta":
                d_base = np.int64(src[0]) if n > 0 else np.int64(0)
                d = np.diff(
                    src.astype(np.int64, copy=False), prepend=d_base
                )
                _check_fits(d, dt, name)
                buf[o : o + 8].view(np.int64)[0] = d_base
                buf[o + 8 : o + 8 + cap * dt.itemsize].view(dt)[:n] = (
                    d.astype(dt, copy=False)
                )
            elif kind == "bitpack":
                if n > 0:
                    packed = np.packbits(src.astype(bool), bitorder="big")
                    buf[o : o + packed.size] = packed
        return buf, base

    def decode(buf, n, base):
        cols_out = {}
        ts = None
        for (name, kind, dt, wide, card), o in zip(sections, offsets):
            if name == "__tsd__":
                arr = _bitcast_split(buf, o, cap, dt)
                if tsd_diff:
                    arr = jnp.cumsum(arr.astype(jnp.int32))
                ts = base + arr.astype(jnp.int64)
            elif kind == "dict":
                codes = _bitcast_split(buf, o, cap, dt)
                vals = _bitcast_split(
                    buf, o + cap * dt.itemsize, card, wide
                )
                cols_out[name] = vals[codes.astype(jnp.int32)]
            elif kind == "delta":
                d_base = _bitcast_split(buf, o, 1, np.dtype(np.int64))[0]
                d = _bitcast_split(buf, o + 8, cap, dt)
                vals = d_base + jnp.cumsum(d.astype(jnp.int64))
                cols_out[name] = vals.astype(jnp.dtype(wide))
            elif kind == "bitpack":
                nb = -(-cap // 8)
                seg = jax.lax.slice(buf, (o,), (o + nb,))
                idx = jnp.arange(cap, dtype=jnp.int32)
                byte = seg[idx >> 3]
                bit = (byte >> (7 - (idx & 7))) & 1
                cols_out[name] = bit.astype(jnp.bool_)
            else:
                arr = _bitcast_split(buf, o, cap, dt)
                cols_out[name] = arr.astype(jnp.dtype(wide))
        for name, t in dropped:
            nv = null_value(t)
            cols_out[name] = jnp.full(
                (cap,),
                np.asarray(0 if nv is None else nv, PHYSICAL_DTYPE[t]),
                dtype=PHYSICAL_DTYPE[t],
            )
        cols_out = {n2: cols_out[n2] for n2, _ in schema.attrs}
        valid = jnp.arange(cap, dtype=jnp.int32) < n
        return EventBatch(
            ts=ts,
            kind=jnp.zeros((cap,), jnp.int8),
            valid=valid,
            cols=cols_out,
        )

    return encode, decode, total


def wire_report(
    schema, keep, narrow: dict, spec: Optional[WireSpec],
    capacity: int = 8192,
) -> dict:
    """describe_state()/explain() wire summary for one engaged fused
    ingest: per-lane encoding labels + encoded vs logical bytes/event,
    amortizing dict/delta headers over `capacity` (the junction's real
    micro-batch rows — a hard-coded large capacity would overstate the
    reduction on small batches)."""
    enc = {k: _normalize(v) for k, v in (narrow or {}).items()}
    kept = [
        (name, t) for name, t in schema.attrs
        if keep is None or name in keep
    ]
    lanes = {
        "__tsd__": encoding_label(
            enc.get("__tsd__", ("narrow", np.dtype(np.int32)))
        )
    }
    encoded = np.dtype(
        enc.get("__tsd__", ("narrow", np.dtype(np.int32)))[1]
    ).itemsize * 1.0
    for name, t in kept:
        wide = np.dtype(PHYSICAL_DTYPE[t])
        e = enc.get(name)
        lanes[name] = encoding_label(e) if e is not None else (
            f"wide:{wide.name}"
        )
        encoded += lane_bytes_per_row(name, wide, e, capacity)
    return {
        "source": encodings_source(narrow or {}, spec),
        "spec_version": spec.version if spec is not None else None,
        "lanes": lanes,
        "encoded_B_per_ev": round(encoded, 2),
        "logical_B_per_ev": logical_row_bytes(schema.attrs),
        "projected_out": [name for name, _t in schema.attrs
                          if keep is not None and name not in keep],
    }
