"""Step-mode debugger: breakpoints at query IN/OUT terminals.

Reference: debugger/SiddhiDebugger.java:36-260 — acquireBreakPoint(query,
IN|OUT) blocks the processing thread on a semaphore when events cross the
terminal; next() steps to the following breakpoint, play() releases until the
same breakpoint recurs; getQueryState inspects the snapshot map. Wired through
SiddhiAppRuntime.debug() (SiddhiAppRuntime.java:509-528).
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Optional


class QueryTerminal(enum.Enum):
    IN = "IN"
    OUT = "OUT"


class SiddhiDebugger:
    def __init__(self, app_runtime):
        self.rt = app_runtime
        self._breakpoints: set[tuple[str, QueryTerminal]] = set()
        self._lock = threading.Lock()
        self._gate = threading.Semaphore(0)
        self._blocked = threading.Event()
        self._current_bp: Optional[tuple[str, QueryTerminal]] = None
        self._free_until: Optional[tuple[str, QueryTerminal]] = None
        self.callback: Optional[Callable] = None  # (events, qid, terminal, dbg)

    def set_debugger_callback(self, fn: Callable) -> None:
        self.callback = fn

    def acquire_break_point(self, query_name: str, terminal: QueryTerminal) -> None:
        with self._lock:
            self._breakpoints.add((query_name, terminal))

    def release_break_point(self, query_name: str, terminal: QueryTerminal) -> None:
        with self._lock:
            self._breakpoints.discard((query_name, terminal))

    def release_all_break_points(self) -> None:
        with self._lock:
            self._breakpoints.clear()

    def next(self) -> None:
        """Release the blocked thread to run to the NEXT breakpoint hit."""
        self._gate.release()

    def play(self) -> None:
        """Release the blocked thread and run freely until the SAME breakpoint
        is hit again (reference: SiddhiDebugger.play semantics)."""
        with self._lock:
            self._free_until = self._current_bp
        self._gate.release()

    def get_query_state(self, query_name: str):
        qr = self.rt.queries.get(query_name)
        if qr is None or qr.state is None:
            return None
        import numpy as np
        import jax

        return jax.tree_util.tree_map(lambda x: np.asarray(x), qr.state)

    # ---- engine hook (called from query receive paths) --------------------

    def check(self, query_name: str, terminal: QueryTerminal, events_thunk) -> None:
        """`events_thunk() -> list` is only evaluated when the breakpoint is
        armed (decoding is not free on the hot path)."""
        bp = (query_name, terminal)
        with self._lock:
            hit = bp in self._breakpoints
            if hit and self._free_until is not None:
                if bp == self._free_until:
                    self._free_until = None  # play() ran back to this point
                else:
                    return  # free-running past other breakpoints
        if not hit:
            return
        events = events_thunk()
        if not events:
            return
        with self._lock:
            self._current_bp = bp  # before the callback: it may call play()
        if self.callback is not None:
            self.callback(events, query_name, terminal, self)
        self._blocked.set()
        self._gate.acquire()  # block the processing thread until next()/play()
        self._blocked.clear()
