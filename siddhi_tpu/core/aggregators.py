"""Attribute aggregators: streaming sum/count/avg/min/max/stdDev/... over batches.

Reference: query/selector/attribute/aggregator/*.java — per-event add on CURRENT,
remove on EXPIRED, zero on RESET, type-specialized inner classes. Batched here:
per-event running outputs become reset-aware prefix reductions (ops/prefix.py);
min/max/distinct under an upstream window use the window's membership matrix
(exact expiry accounting) instead of incremental remove, which is the TPU-shaped
equivalent of the reference's value-deque bookkeeping.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from siddhi_tpu.core.executor import CompiledExpr, Env
from siddhi_tpu.core.types import AttrType, PHYSICAL_DTYPE, null_value
from siddhi_tpu.ops.prefix import extreme_identity, running_extreme, running_sum


@dataclasses.dataclass
class FlowInfo:
    """Per-batch signals handed to aggregators by the selector.

    sign:   [B] +1 valid CURRENT, -1 valid EXPIRED, 0 otherwise
    active: [B] valid CURRENT rows
    reset:  [B] valid RESET rows
    member / member_env: optional [B, K] window membership matrix (row i = the
        window contents as seen just after event i) and an Env over the K-long
        window columns — provided by window stages for exact min/max/distinct.
    """

    sign: jnp.ndarray
    active: jnp.ndarray
    reset: jnp.ndarray
    member: Optional[jnp.ndarray] = None
    member_env: Optional[Env] = None


class CompiledAggregator:
    """One aggregator instance in a selector; owns a slice of query state."""

    type: AttrType

    def init(self):  # -> pytree of device arrays
        raise NotImplementedError

    def apply(self, state, flow: FlowInfo, env: Env):  # -> (state', [B] col)
        raise NotImplementedError


def _null_arr(t: AttrType):
    return jnp.asarray(null_value(t), dtype=PHYSICAL_DTYPE[t])


class SumAggregator(CompiledAggregator):
    """sum(): LONG for int/long input, DOUBLE for float/double
    (reference: SumAttributeAggregator.java type matrix)."""

    def __init__(self, arg: CompiledExpr):
        self.arg = arg
        self.type = (
            AttrType.LONG if arg.type in (AttrType.INT, AttrType.LONG) else AttrType.DOUBLE
        )
        self.dtype = PHYSICAL_DTYPE[self.type]

    def init(self):
        return jnp.zeros((), dtype=self.dtype)

    def apply(self, state, flow: FlowInfo, env: Env):
        x = self.arg(env).astype(self.dtype)
        contrib = jnp.where(flow.sign != 0, x * flow.sign.astype(self.dtype), 0)
        run, carry = running_sum(contrib, flow.reset, state)
        return carry, run


class CountAggregator(CompiledAggregator):
    type = AttrType.LONG

    def init(self):
        return jnp.zeros((), dtype=jnp.int64)

    def apply(self, state, flow: FlowInfo, env: Env):
        run, carry = running_sum(flow.sign.astype(jnp.int64), flow.reset, state)
        return carry, run


class AvgAggregator(CompiledAggregator):
    """DOUBLE average; null (NaN) when count == 0, matching the reference
    (reference: AvgAttributeAggregator.java:164-166 returns null on count 0)."""

    type = AttrType.DOUBLE

    def __init__(self, arg: CompiledExpr):
        self.arg = arg

    def init(self):
        z = jnp.zeros((), dtype=jnp.float32)
        return {"sum": z, "count": z}

    def apply(self, state, flow: FlowInfo, env: Env):
        x = self.arg(env).astype(jnp.float32)
        sgn = flow.sign.astype(jnp.float32)
        s_run, s_carry = running_sum(jnp.where(flow.sign != 0, x * sgn, 0.0), flow.reset, state["sum"])
        c_run, c_carry = running_sum(sgn, flow.reset, state["count"])
        out = jnp.where(c_run != 0, s_run / jnp.where(c_run != 0, c_run, 1.0), jnp.nan)
        return {"sum": s_carry, "count": c_carry}, out


class StdDevAggregator(CompiledAggregator):
    """Population std-dev from running sum/sumsq/count
    (reference: StdDevAttributeAggregator.java)."""

    type = AttrType.DOUBLE

    def __init__(self, arg: CompiledExpr):
        self.arg = arg

    def init(self):
        z = jnp.zeros((), dtype=jnp.float32)
        return {"sum": z, "sumsq": z, "count": z}

    def apply(self, state, flow: FlowInfo, env: Env):
        x = self.arg(env).astype(jnp.float32)
        sgn = flow.sign.astype(jnp.float32)
        s_run, s_c = running_sum(jnp.where(flow.sign != 0, x * sgn, 0.0), flow.reset, state["sum"])
        q_run, q_c = running_sum(jnp.where(flow.sign != 0, x * x * sgn, 0.0), flow.reset, state["sumsq"])
        c_run, c_c = running_sum(sgn, flow.reset, state["count"])
        safe_n = jnp.where(c_run != 0, c_run, 1.0)
        mean = s_run / safe_n
        var = jnp.maximum(q_run / safe_n - mean * mean, 0.0)
        out = jnp.where(c_run != 0, jnp.sqrt(var), jnp.nan)
        return {"sum": s_c, "sumsq": q_c, "count": c_c}, out


class ExtremeAggregator(CompiledAggregator):
    """min/max. Exact under windows via the membership matrix; running
    (monotone) otherwise. minForever/maxForever always run monotone
    (reference: MinForeverAttributeAggregator.java ignores expiry)."""

    def __init__(self, arg: CompiledExpr, is_min: bool, forever: bool):
        self.arg = arg
        self.type = arg.type
        self.dtype = PHYSICAL_DTYPE[arg.type]
        self.is_min = is_min
        self.forever = forever

    def init(self):
        return extreme_identity(self.dtype, self.is_min)

    def apply(self, state, flow: FlowInfo, env: Env):
        ident = extreme_identity(self.dtype, self.is_min)
        if not self.forever and flow.member is not None:
            vals = self.arg(flow.member_env).astype(self.dtype)
            masked = jnp.where(flow.member, vals[None, :], ident)
            red = masked.min(axis=-1) if self.is_min else masked.max(axis=-1)
            return state, jnp.where(red == ident, _null_arr(self.type), red)
        reset = jnp.zeros_like(flow.reset) if self.forever else flow.reset
        run, carry = running_extreme(
            self.arg(env).astype(self.dtype), flow.active, reset, state, self.is_min
        )
        return carry, jnp.where(run == ident, _null_arr(self.type), run)


class DistinctCountAggregator(CompiledAggregator):
    """distinctCount under a window: per-event distinct member values via the
    membership matrix (reference: DistinctCountAttributeAggregator.java keeps a
    value->count map; the window columns make this a pure reduction here)."""

    type = AttrType.LONG

    def __init__(self, arg: CompiledExpr):
        self.arg = arg

    def init(self):
        return jnp.zeros((), dtype=jnp.int64)

    def apply(self, state, flow: FlowInfo, env: Env):
        if flow.member is None:
            raise NotImplementedError(
                "distinctCount requires an upstream window (unbounded distinct "
                "state is capacity-unbounded; the reference grows a map forever)"
            )
        vals = self.arg(flow.member_env)
        k = vals.shape[-1]
        eq = vals[None, :] == vals[:, None]  # [K, K]
        earlier = jnp.tril(jnp.ones((k, k), dtype=bool), k=-1)
        # member j is a duplicate within row i if some earlier member j' < j
        # holds an equal value
        dup = ((eq & earlier)[None, :, :] & flow.member[:, None, :]).any(axis=-1)
        firsts = flow.member & ~dup
        return state, firsts.sum(axis=-1).astype(jnp.int64)


def build_aggregator(name: str, args: list[CompiledExpr]) -> CompiledAggregator:
    low = name.lower()
    if low == "count":
        return CountAggregator()
    if not args:
        raise TypeError(f"aggregator '{name}' needs an argument")
    arg = args[0]
    if low == "sum":
        return SumAggregator(arg)
    if low == "avg":
        return AvgAggregator(arg)
    if low == "stddev":
        return StdDevAggregator(arg)
    if low == "min":
        return ExtremeAggregator(arg, is_min=True, forever=False)
    if low == "max":
        return ExtremeAggregator(arg, is_min=False, forever=False)
    if low == "minforever":
        return ExtremeAggregator(arg, is_min=True, forever=True)
    if low == "maxforever":
        return ExtremeAggregator(arg, is_min=False, forever=True)
    if low == "distinctcount":
        return DistinctCountAggregator(arg)
    raise TypeError(f"unknown aggregator '{name}'")
