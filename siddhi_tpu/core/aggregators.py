"""Attribute aggregators: streaming sum/count/avg/min/max/stdDev/... over batches.

Reference: query/selector/attribute/aggregator/*.java — per-event add on CURRENT,
remove on EXPIRED, zero on RESET, type-specialized inner classes; group-by wraps
each in a per-key map (GroupByAggregationAttributeExecutor.java). Batched here:
per-event running outputs become reset-aware prefix reductions (ops/prefix.py),
or keyed segment reductions over a slot table when a group-by is present
(ops/group.py); min/max/distinct under an upstream window use the window's
membership matrix (exact expiry accounting) instead of incremental remove, which
is the TPU-shaped equivalent of the reference's value-deque bookkeeping.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.executor import CompiledExpr, Env
from siddhi_tpu.core.groupby import CompiledGroupBy, GroupCtx
from siddhi_tpu.core.types import AttrType, PHYSICAL_DTYPE, null_value
from siddhi_tpu.ops.group import keyed_running_extreme, keyed_running_sum
from siddhi_tpu.ops.prefix import extreme_identity, running_extreme, running_sum


@dataclasses.dataclass
class FlowInfo:
    """Per-batch signals handed to aggregators by the selector.

    sign:   [B] +1 valid CURRENT, -1 valid EXPIRED, 0 otherwise
    active: [B] valid CURRENT rows
    reset:  [B] valid RESET rows
    member / member_env: optional [B, K] window membership matrix (row i = the
        window contents as seen just after event i) and an Env over the K-long
        window columns — provided by window stages for exact min/max/distinct.
    group:  optional GroupCtx when the selector has a group-by.
    """

    sign: jnp.ndarray
    active: jnp.ndarray
    reset: jnp.ndarray
    member: Optional[jnp.ndarray] = None
    member_env: Optional[Env] = None
    group: Optional[GroupCtx] = None


class CompiledAggregator:
    """One aggregator instance in a selector; owns a slice of query state.

    When `group` is set, state arrays gain a leading [G] axis indexed by the
    GroupCtx slot lane.
    """

    type: AttrType
    group: Optional[CompiledGroupBy] = None

    def _shape(self):
        return (self.group.capacity,) if self.group is not None else ()

    def init(self):  # -> pytree of device arrays
        raise NotImplementedError

    def apply(self, state, flow: FlowInfo, env: Env):  # -> (state', [B] col)
        raise NotImplementedError

    def _run_sum(self, state, contrib, flow: FlowInfo):
        if flow.group is not None:
            return keyed_running_sum(
                contrib, flow.group.sorted, flow.reset, state, flow.group.slot
            )
        run, carry = running_sum(contrib, flow.reset, state)
        return run, carry


def _null_arr(t: AttrType):
    # numpy (NOT jnp): trace-time const — a jax.Array here would degrade
    # every dispatch on tunneled backends (see executor._const_expr).
    return np.asarray(null_value(t), dtype=PHYSICAL_DTYPE[t])


class SumAggregator(CompiledAggregator):
    """sum(): LONG for int/long input, DOUBLE for float/double
    (reference: SumAttributeAggregator.java type matrix)."""

    def __init__(self, arg: CompiledExpr, group=None):
        self.arg = arg
        self.group = group
        self.type = (
            AttrType.LONG if arg.type in (AttrType.INT, AttrType.LONG) else AttrType.DOUBLE
        )
        self.dtype = PHYSICAL_DTYPE[self.type]

    def init(self):
        return jnp.zeros(self._shape(), dtype=self.dtype)

    def apply(self, state, flow: FlowInfo, env: Env):
        x = self.arg(env).astype(self.dtype)
        contrib = jnp.where(flow.sign != 0, x * flow.sign.astype(self.dtype), 0)
        return _swap(self._run_sum(state, contrib, flow))


class CountAggregator(CompiledAggregator):
    type = AttrType.LONG

    def __init__(self, group=None):
        self.group = group

    def init(self):
        return jnp.zeros(self._shape(), dtype=jnp.int64)

    def apply(self, state, flow: FlowInfo, env: Env):
        return _swap(self._run_sum(state, flow.sign.astype(jnp.int64), flow))


def _swap(t):
    run, carry = t
    return carry, run


class AvgAggregator(CompiledAggregator):
    """DOUBLE average; null (NaN) when count == 0, matching the reference
    (reference: AvgAttributeAggregator.java:164-166 returns null on count 0)."""

    type = AttrType.DOUBLE

    def __init__(self, arg: CompiledExpr, group=None):
        self.arg = arg
        self.group = group

    def init(self):
        z = jnp.zeros(self._shape(), dtype=jnp.float32)
        return {"sum": z, "count": z}

    def apply(self, state, flow: FlowInfo, env: Env):
        x = self.arg(env).astype(jnp.float32)
        sgn = flow.sign.astype(jnp.float32)
        s_run, s_carry = self._run_sum(
            state["sum"], jnp.where(flow.sign != 0, x * sgn, 0.0), flow
        )
        c_run, c_carry = self._run_sum(state["count"], sgn, flow)
        out = jnp.where(c_run != 0, s_run / jnp.where(c_run != 0, c_run, 1.0), jnp.nan)
        return {"sum": s_carry, "count": c_carry}, out


class StdDevAggregator(CompiledAggregator):
    """Population std-dev from running sum/sumsq/count
    (reference: StdDevAttributeAggregator.java)."""

    type = AttrType.DOUBLE

    def __init__(self, arg: CompiledExpr, group=None):
        self.arg = arg
        self.group = group

    def init(self):
        z = jnp.zeros(self._shape(), dtype=jnp.float32)
        return {"sum": z, "sumsq": z, "count": z}

    def apply(self, state, flow: FlowInfo, env: Env):
        x = self.arg(env).astype(jnp.float32)
        sgn = flow.sign.astype(jnp.float32)
        s_run, s_c = self._run_sum(state["sum"], jnp.where(flow.sign != 0, x * sgn, 0.0), flow)
        q_run, q_c = self._run_sum(state["sumsq"], jnp.where(flow.sign != 0, x * x * sgn, 0.0), flow)
        c_run, c_c = self._run_sum(state["count"], sgn, flow)
        safe_n = jnp.where(c_run != 0, c_run, 1.0)
        mean = s_run / safe_n
        var = jnp.maximum(q_run / safe_n - mean * mean, 0.0)
        out = jnp.where(c_run != 0, jnp.sqrt(var), jnp.nan)
        return {"sum": s_c, "sumsq": q_c, "count": c_c}, out


class ExtremeAggregator(CompiledAggregator):
    """min/max. Exact under windows via the membership matrix; running
    (monotone) otherwise. minForever/maxForever always run monotone
    (reference: MinForeverAttributeAggregator.java ignores expiry)."""

    def __init__(self, arg: CompiledExpr, is_min: bool, forever: bool, group=None):
        self.arg = arg
        self.group = group
        self.type = arg.type
        self.dtype = PHYSICAL_DTYPE[arg.type]
        self.is_min = is_min
        self.forever = forever

    def init(self):
        ident = extreme_identity(self.dtype, self.is_min)
        return jnp.full(self._shape(), ident, dtype=self.dtype)

    def apply(self, state, flow: FlowInfo, env: Env):
        ident = extreme_identity(self.dtype, self.is_min)
        if not self.forever and flow.member is not None:
            vals = self.arg(flow.member_env).astype(self.dtype)
            member = flow.member
            if flow.group is not None:
                # restrict membership to window elements in the same group
                elem_key = flow.group.key_of(flow.member_env)
                member = member & (elem_key[None, :] == flow.group.key[:, None])
            masked = jnp.where(member, vals[None, :], ident)
            red = masked.min(axis=-1) if self.is_min else masked.max(axis=-1)
            return state, jnp.where(red == ident, _null_arr(self.type), red)
        reset = jnp.zeros_like(flow.reset) if self.forever else flow.reset
        x = self.arg(env).astype(self.dtype)
        if flow.group is not None:
            run, carry = keyed_running_extreme(
                x, flow.active, flow.group.sorted, reset, state,
                flow.group.slot, self.is_min,
            )
        else:
            run, carry = running_extreme(x, flow.active, reset, state, self.is_min)
        return carry, jnp.where(run == ident, _null_arr(self.type), run)


class DistinctCountAggregator(CompiledAggregator):
    """distinctCount under a window: per-event distinct member values via the
    membership matrix (reference: DistinctCountAttributeAggregator.java keeps a
    value->count map; the window columns make this a pure reduction here)."""

    type = AttrType.LONG

    def __init__(self, arg: CompiledExpr, group=None):
        self.arg = arg
        self.group = group

    def init(self):
        return jnp.zeros((), dtype=jnp.int64)

    def apply(self, state, flow: FlowInfo, env: Env):
        if flow.member is None:
            raise NotImplementedError(
                "distinctCount requires an upstream window (unbounded distinct "
                "state is capacity-unbounded; the reference grows a map forever)"
            )
        vals = self.arg(flow.member_env)
        member = flow.member
        if flow.group is not None:
            elem_key = flow.group.key_of(flow.member_env)
            member = member & (elem_key[None, :] == flow.group.key[:, None])
        k = vals.shape[-1]
        eq = vals[None, :] == vals[:, None]  # [K, K]
        earlier = jnp.tril(jnp.ones((k, k), dtype=bool), k=-1)
        # member j is a duplicate within row i if some earlier member j' < j
        # holds an equal value
        dup = ((eq & earlier)[None, :, :] & member[:, None, :]).any(axis=-1)
        firsts = member & ~dup
        return state, firsts.sum(axis=-1).astype(jnp.int64)


def build_aggregator(
    name: str, args: list[CompiledExpr], group: Optional[CompiledGroupBy] = None
) -> CompiledAggregator:
    low = name.lower()
    if low == "count":
        return CountAggregator(group=group)
    if not args:
        raise TypeError(f"aggregator '{name}' needs an argument")
    arg = args[0]
    if low == "sum":
        return SumAggregator(arg, group=group)
    if low == "avg":
        return AvgAggregator(arg, group=group)
    if low == "stddev":
        return StdDevAggregator(arg, group=group)
    if low == "min":
        return ExtremeAggregator(arg, is_min=True, forever=False, group=group)
    if low == "max":
        return ExtremeAggregator(arg, is_min=False, forever=False, group=group)
    if low == "minforever":
        return ExtremeAggregator(arg, is_min=True, forever=True, group=group)
    if low == "maxforever":
        return ExtremeAggregator(arg, is_min=False, forever=True, group=group)
    if low == "distinctcount":
        return DistinctCountAggregator(arg, group=group)
    raise TypeError(f"unknown aggregator '{name}'")
