"""Incremental multi-duration aggregation.

Reference: core/aggregation/ — `define aggregation A from S select ... group by
... aggregate by ts every sec...year` builds a chain of per-duration executors
(IncrementalExecutor.java:49-580): the finest absorbs events into an in-memory
bucket store; when event time crosses a bucket boundary the closed bucket is
spilled to an auto-created table (`<id>_<DURATION>`, AGG_TIMESTAMP first column
— AggregationParser.java:400,695-708) and rolled up into the next coarser
executor. Query path merges table rows with in-flight buckets
(AggregationRuntime.java:176, IncrementalDataAggregator.java).

TPU-native design: the whole duration chain is one carried state pytree; a
`lax.scan` over the batch rows performs close/rollup/absorb per row (each a
masked [G] / [G,G] slot-table op), spilling closed buckets into a bounded
per-batch buffer that is table-inserted vectorized after the scan. Calendar
(month/year) alignment uses integer civil-date math on device.
"""

from __future__ import annotations

import re
from typing import Optional

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.executor import (
    CompiledExpr,
    Env,
    Scope,
    TS_ATTR,
    compile_expression,
    is_aggregator,
)
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.table import InMemoryTable
from siddhi_tpu.core.types import AttrType, PHYSICAL_DTYPE
from siddhi_tpu.query_api.definition import (
    Attribute,
    Duration,
    TableDefinition,
)
from siddhi_tpu.query_api.expression import AttributeFunction, Variable

AGG_TS = "AGG_TIMESTAMP"
DEFAULT_AGG_GROUPS = 64
SPILLS_PER_BATCH = 4

_I64MIN = jnp.iinfo(jnp.int64).min
_I64MAX = jnp.iinfo(jnp.int64).max


# ---------------------------------------------------------------------------
# civil-calendar device math (Howard Hinnant's algorithms, integer-only)
# ---------------------------------------------------------------------------

_DAY_MS = 86_400_000


def _civil_from_days(z):
    z = z + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    return y + (m <= 2), m, d


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def align_bucket(ts_ms, duration: Duration):
    """Bucket start (epoch ms, GMT) containing ts — device-traceable
    (reference: util/IncrementalTimeConverterUtil.getStartTimeOfAggregates)."""
    ts_ms = jnp.asarray(ts_ms, jnp.int64)
    if duration not in (Duration.MONTHS, Duration.YEARS):
        step = np.int64(duration.millis)
        return jnp.floor_divide(ts_ms, step) * step
    days = jnp.floor_divide(ts_ms, _DAY_MS)
    y, m, _d = _civil_from_days(days)
    if duration is Duration.MONTHS:
        start = _days_from_civil(y, m, jnp.ones_like(m))
    else:
        start = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return start * _DAY_MS


# ---------------------------------------------------------------------------
# base decomposition (reference: executor/incremental/*IncrementalAttributeAggregator)
# ---------------------------------------------------------------------------


def _sum_type(t: AttrType) -> AttrType:
    return AttrType.DOUBLE if t in (AttrType.FLOAT, AttrType.DOUBLE) else AttrType.LONG


class _OutSpec:
    """One selected attribute: bases it needs + how to recompose."""

    def __init__(self, name, kind, arg: Optional[CompiledExpr], out_type):
        self.name = name
        self.kind = kind  # sum|count|avg|min|max|last
        self.arg = arg
        self.out_type = out_type


class AggregationRuntime:
    def __init__(
        self,
        definition,
        in_schema: StreamSchema,
        interner,
        group_capacity: int = DEFAULT_AGG_GROUPS,
    ):
        self.definition = definition
        self.agg_id = definition.id
        self.in_schema = in_schema
        self.interner = interner
        self.g = int(group_capacity)

        stream = definition.basic_single_input_stream
        self.stream_id = stream.stream_id
        ref = stream.ref
        self.ref = ref
        scope = Scope(interner)
        scope.add_stream(ref, in_schema.attr_types)
        scope.default_ref = ref
        self.scope = scope

        from siddhi_tpu.query_api.execution import Filter

        self.filters = []
        for h in stream.handlers:
            if isinstance(h, Filter):
                c = compile_expression(h.expression, scope)
                if c.type is not AttrType.BOOL:
                    raise SiddhiAppCreationError("filter must be boolean")
                self.filters.append(c)
            else:
                raise SiddhiAppCreationError(
                    "aggregation inputs support filters only"
                )

        # timestamp source: `aggregate by <attr>` or the event timestamp
        if definition.aggregate_attribute is not None:
            c = compile_expression(definition.aggregate_attribute, scope)
            if c.type not in (AttrType.LONG, AttrType.INT):
                raise SiddhiAppCreationError("aggregate by attribute must be long")
            self.ts_expr = c
        else:
            self.ts_expr = None
        # lineage recorder (observability/lineage.py AggregationLineage):
        # per-bucket contributing seq ranges; None = one check per receive
        self.lineage = None
        from siddhi_tpu.query_api.expression import Variable as _Var

        self._lin_ts_attr = (
            definition.aggregate_attribute.attribute
            if isinstance(definition.aggregate_attribute, _Var)
            else None
        )

        self.durations: list[Duration] = list(definition.time_period.durations)

        # selected attributes -> base columns + recompose
        self.group_by: list[Variable] = list(definition.selector.group_by)
        self.group_keys: list[CompiledExpr] = [
            compile_expression(v, scope) for v in self.group_by
        ]
        self.out_specs: list[_OutSpec] = []
        self.bases: dict[str, tuple[str, Optional[CompiledExpr], AttrType]] = {}
        # base store columns: name -> (kind, arg expr, stored type)
        for oa in definition.selector.selection_list:
            e = oa.expression
            name = oa.name
            if is_aggregator(e):
                assert isinstance(e, AttributeFunction)
                fn = e.name.lower()
                if fn in ("sum", "min", "max", "avg"):
                    arg = compile_expression(e.parameters[0], scope)
                    if arg.type not in (
                        AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE
                    ):
                        raise SiddhiAppCreationError(f"{fn} needs a numeric argument")
                elif fn == "count":
                    arg = None
                else:
                    raise SiddhiAppCreationError(
                        f"'{e.name}' cannot be aggregated incrementally "
                        "(reference supports sum/count/avg/min/max)"
                    )
                if fn in ("sum", "avg"):
                    self._base(f"sum_{name}", "sum", arg, _sum_type(arg.type))
                if fn in ("count", "avg"):
                    self._base("count_", "count", None, AttrType.LONG)
                if fn in ("min", "max"):
                    self._base(f"{fn}_{name}", fn, arg, arg.type)
                out_type = (
                    AttrType.DOUBLE if fn == "avg"
                    else AttrType.LONG if fn == "count"
                    else (_sum_type(arg.type) if fn == "sum" else arg.type)
                )
                self.out_specs.append(_OutSpec(name, fn, arg, out_type))
            else:
                c = compile_expression(e, scope)
                self._base(f"last_{name}", "last", c, c.type)
                self.out_specs.append(_OutSpec(name, "last", c, c.type))

        # group-by attributes must be recoverable for the spill tables: store
        # them as last-value columns too
        self.group_names: list[str] = []
        for v, c in zip(self.group_by, self.group_keys):
            gname = v.attribute
            self.group_names.append(gname)
            self._base(f"last__g_{gname}", "last", c, c.type)

        # per-duration spill tables <id>_<DURATION>
        # (reference: AggregationParser.java:701)
        self.tables: dict[Duration, InMemoryTable] = {}
        table_attrs = [Attribute(AGG_TS, AttrType.LONG)]
        for gname, v in zip(self.group_names, self.group_by):
            t = dict(self.bases)[f"last__g_{gname}"][2]
            table_attrs.append(Attribute(gname, t))
        for bname, (kind, _arg, t) in self.bases.items():
            if bname.startswith("last__g_"):
                continue
            table_attrs.append(Attribute(f"AGG_{bname}", t))
        # @store on the aggregation rides through to every duration table
        # (reference: AggregationParser initDefaultTables passes the
        # aggregation's annotations to each internal table definition)
        from siddhi_tpu.query_api.annotation import find_annotation

        store_ann = find_annotation(
            getattr(definition, "annotations", []) or [], "store"
        )
        for d in self.durations:
            tid = f"{self.agg_id}_{d.name}"
            anns = []
            if store_ann is not None:
                # each duration table needs its OWN store namespace: a shared
                # store.id would make the tables clobber each other's rows
                from siddhi_tpu.query_api.annotation import Annotation

                els = [
                    (k, v) for k, v in store_ann.elements
                    if k != "store.id"
                ]
                base_id = store_ann.element("store.id") or self.agg_id
                els.append(("store.id", f"{base_id}__{d.name}"))
                anns.append(Annotation(store_ann.name, els))
            td = TableDefinition(tid, list(table_attrs), annotations=anns)
            self.tables[d] = InMemoryTable(td, interner)

        # output schema of the find path: AGG_TIMESTAMP + selected attrs
        self.out_schema = StreamSchema(
            self.agg_id,
            [(AGG_TS, AttrType.LONG)] + [(s.name, s.out_type) for s in self.out_specs],
        )

        self._empty = self._empty_store()
        self._store_dtypes = {b: self._empty["vals"][b].dtype for b in self.bases}
        self.state = self.init_state()
        self._step = jax.jit(self._step_impl)
        self._finds = {}
        self.rebuild_from_tables()

    def _base(self, name, kind, arg, t):
        if name not in self.bases:
            self.bases[name] = (kind, arg, t)

    # ---- restart rebuild ---------------------------------------------------

    def rebuild_from_tables(self):
        """Rebuild each coarser duration's OPEN bucket from the next finer
        duration's table rows (reference: aggregation/RecreateInMemoryData.java
        wired at SiddhiAppRuntime.java:380-382). A @store-backed aggregation
        restarting without a snapshot recovers its in-flight coarse buckets
        from the persisted fine spills; the finest duration's open bucket is
        irrecoverable in the reference too (its raw events were never spilled).

        Host-side one-shot: the duration tables were just loaded from the
        record store; rows are small and this runs once at creation."""
        import numpy as np

        for i in range(1, len(self.durations)):
            d = self.durations[i]
            src = self.tables[self.durations[i - 1]].state
            valid = np.asarray(src["valid"])
            if not valid.any():
                continue  # only skip durations whose OWN source is empty
            ts = np.asarray(src["cols"][AGG_TS])[valid]
            # the open bucket is judged by each SOURCE table's latest row —
            # an empty finest table must not suppress coarser rebuilds from
            # the intermediate duration tables
            latest = int(ts.max())
            open_bucket = int(align_bucket(jnp.asarray(latest), d))
            own = self.tables[d].state
            own_valid = np.asarray(own["valid"])
            if own_valid.any() and (
                np.asarray(own["cols"][AGG_TS])[own_valid] == open_bucket
            ).any():
                # this bucket already closed and spilled into d's own table
                # (e.g. the finer table's tail predates the spill); treating
                # it as in-flight again would double-insert it at the next
                # close — spill is a plain insert with no AGG_TS dedupe
                continue
            in_open = np.asarray(
                align_bucket(jnp.asarray(ts), d)
            ) == open_bucket
            if not in_open.any():
                # nothing to rebuild; _merge_into initializes the bucket on
                # the next live merge
                continue
            cols = {
                n: np.asarray(c)[valid][in_open]
                for n, c in src["cols"].items()
            }
            row_ts = ts[in_open]
            order = np.argsort(row_ts, kind="stable")

            # group rows by the stored group attributes
            gvals = [cols[g] for g in self.group_names]
            groups: dict = {}
            for ri in order:
                gk = tuple(v[ri].item() for v in gvals)
                groups.setdefault(gk, []).append(ri)

            store = self._empty_store()
            keys = np.asarray(store["keys"]).copy()
            used = np.asarray(store["used"]).copy()
            vals = {b: np.asarray(v).copy() for b, v in store["vals"].items()}
            for slot_i, (gk, ridx) in enumerate(groups.items()):
                if slot_i >= self.g:
                    break
                # the device key: float group cols bitcast to int32, mixed
                kcols = []
                for gname, gv in zip(self.group_names, gvals):
                    t = dict(self.bases)[f"last__g_{gname}"][2]
                    v = np.asarray([gv[ridx[0]]])
                    if t in (AttrType.FLOAT, AttrType.DOUBLE):
                        v = v.astype(np.float32).view(np.int32).astype(np.int64)
                    kcols.append(jnp.asarray(v, jnp.int64))
                if kcols:
                    from siddhi_tpu.ops.group import mix_keys

                    keys[slot_i] = int(mix_keys(kcols)[0])
                used[slot_i] = True
                for bname, (kind, _arg, _t) in self.bases.items():
                    col = (
                        cols[bname[len("last__g_"):]]
                        if bname.startswith("last__g_")
                        else cols[f"AGG_{bname}"]
                    )
                    sel = col[ridx]
                    if kind in ("sum", "count"):
                        vals[bname][slot_i] = sel.sum()
                    elif kind == "min":
                        vals[bname][slot_i] = sel.min()
                    elif kind == "max":
                        vals[bname][slot_i] = sel.max()
                    elif kind == "first":
                        vals[bname][slot_i] = sel[0]
                    else:  # last
                        vals[bname][slot_i] = sel[-1]
            self.state["stores"][i] = {
                "keys": jnp.asarray(keys),
                "used": jnp.asarray(used),
                "vals": {b: jnp.asarray(v) for b, v in vals.items()},
                "bucket": jnp.asarray(open_bucket, jnp.int64),
            }

    # ---- state -----------------------------------------------------------

    def _empty_store(self):
        g = self.g
        vals = {}
        for bname, (kind, _arg, t) in self.bases.items():
            dt = PHYSICAL_DTYPE[t]
            if kind == "min":
                init = jnp.full((g,), jnp.inf if t in (AttrType.FLOAT, AttrType.DOUBLE) else jnp.iinfo(dt).max, dt)
            elif kind == "max":
                init = jnp.full((g,), -jnp.inf if t in (AttrType.FLOAT, AttrType.DOUBLE) else jnp.iinfo(dt).min, dt)
            else:
                init = jnp.zeros((g,), dt)
            vals[bname] = init
        return {
            "keys": jnp.zeros((g,), jnp.int64),
            "used": jnp.zeros((g,), jnp.bool_),
            "vals": vals,
            "bucket": jnp.full((), -1, jnp.int64),
        }

    def _empty_spill(self):
        g, s = self.g, SPILLS_PER_BATCH
        return {
            "ts": jnp.zeros((s,), jnp.int64),
            "keys": jnp.zeros((s, g), jnp.int64),
            "used": jnp.zeros((s, g), jnp.bool_),
            "vals": {
                bname: jnp.zeros((s, g), self._store_dtypes[bname])
                for bname in self.bases
            },
        }

    def init_state(self):
        return {
            "stores": [self._empty_store() for _ in self.durations],
            # spill buffers are zeroed per step; kept in state for pytree shape
            "spill": [self._empty_spill() for _ in self.durations],
            "spill_n": [jnp.zeros((), jnp.int32) for _ in self.durations],
        }

    # ---- device step ------------------------------------------------------

    def _merge_into(self, store, src_keys, src_used, src_vals, src_bucket_ts, init_bucket):
        """Merge a child store's groups into `store` (masked [G,G] op)."""
        g = self.g
        keys, used = store["keys"], store["used"]
        eq = src_used[:, None] & used[None, :] & (src_keys[:, None] == keys[None, :])
        hit = eq.any(axis=1)
        hit_slot = jnp.argmax(eq, axis=1).astype(jnp.int32)
        # allocate misses in order
        miss = src_used & ~hit
        n_used = used.sum(dtype=jnp.int32)
        rank = (jnp.cumsum(miss.astype(jnp.int32)) - miss).astype(jnp.int32)
        new_slot = n_used + rank
        overflow = (jnp.where(miss, new_slot, 0) >= g).any()
        slot = jnp.where(hit, hit_slot, jnp.where(new_slot < g, new_slot, g))
        slot = jnp.where(src_used, slot, g)
        keys2 = keys.at[slot].set(src_keys, mode="drop")
        used2 = used.at[slot].set(True, mode="drop")
        vals2 = {}
        for bname, (kind, _arg, _t) in self.bases.items():
            dst = store["vals"][bname]
            sv = src_vals[bname]
            if kind in ("sum", "count"):
                vals2[bname] = dst.at[slot].add(jnp.where(src_used, sv, 0), mode="drop")
            elif kind == "min":
                vals2[bname] = dst.at[slot].min(sv, mode="drop")
            elif kind == "max":
                vals2[bname] = dst.at[slot].max(sv, mode="drop")
            else:  # last
                vals2[bname] = dst.at[slot].set(sv, mode="drop")
        bucket = jnp.where(store["bucket"] < 0, init_bucket, store["bucket"])
        return (
            {"keys": keys2, "used": used2, "vals": vals2, "bucket": bucket},
            overflow,
        )

    def _step_impl(self, state, batch: EventBatch, now):
        b = batch.capacity
        env_cols = {(self.ref, None, n): c for n, c in batch.cols.items()}
        env_cols[(self.ref, None, TS_ATTR)] = batch.ts
        env = Env(env_cols, now=now)

        live = batch.valid & (batch.kind == KIND_CURRENT)
        for f in self.filters:
            live = live & f(env)
        is_timer = batch.valid & (batch.kind == KIND_TIMER)
        ev_ts = self.ts_expr(env).astype(jnp.int64) if self.ts_expr else batch.ts
        ev_ts = jnp.where(is_timer, batch.ts, ev_ts)

        # per-row group key + base contributions
        from siddhi_tpu.ops.group import mix_keys

        if self.group_keys:
            kcols = []
            for c in self.group_keys:
                col = c(env)
                if c.type in (AttrType.FLOAT, AttrType.DOUBLE):
                    col = jnp.asarray(col).view(jnp.int32).astype(jnp.int64)
                kcols.append(col.astype(jnp.int64))
            row_key = mix_keys(kcols)
        else:
            row_key = jnp.zeros((b,), jnp.int64)
        contribs = {}
        for bname, (kind, arg, t) in self.bases.items():
            dt = PHYSICAL_DTYPE[t]
            if kind == "count":
                contribs[bname] = jnp.ones((b,), dt)
            else:
                contribs[bname] = jnp.broadcast_to(arg(env).astype(dt), (b,))

        g = self.g
        n_dur = len(self.durations)
        spill0 = [self._empty_spill() for _ in range(n_dur)]
        spill_n0 = [jnp.zeros((), jnp.int32) for _ in range(n_dur)]

        def body(carry, row):
            stores, spills, spill_ns, ovf = carry
            r_live = row["live"]
            r_timer = row["timer"]
            r_ts = row["ts"]
            advance = r_live | r_timer

            # the event itself is the finest "rollup": one pseudo-group
            roll_keys = jnp.where(
                jnp.arange(g) == 0, row["key"], 0
            ).astype(jnp.int64)
            roll_used = (jnp.arange(g) == 0) & r_live
            roll_vals = {
                bname: jnp.zeros((g,), contribs[bname].dtype).at[0].set(row[f"v.{bname}"])
                for bname in self.bases
            }
            roll_ts = r_ts

            def do_close(st, di, close, sp, sn, ovf):
                """Spill the open bucket and reset; returns closed snapshot."""
                pos = jnp.where(close & (sn < SPILLS_PER_BATCH), sn, SPILLS_PER_BATCH)
                sp = {
                    "ts": sp["ts"].at[pos].set(st["bucket"], mode="drop"),
                    "keys": sp["keys"].at[pos].set(st["keys"], mode="drop"),
                    "used": sp["used"].at[pos].set(st["used"], mode="drop"),
                    "vals": {
                        bn: sp["vals"][bn].at[pos].set(st["vals"][bn], mode="drop")
                        for bn in self.bases
                    },
                }
                ovf = ovf | (close & (sn >= SPILLS_PER_BATCH))
                sn = sn + close.astype(jnp.int32)
                closed = (st["keys"], st["used"], st["vals"], st["bucket"])
                empty = self._empty
                nb = align_bucket(r_ts, self.durations[di])
                st = {
                    "keys": jnp.where(close, empty["keys"], st["keys"]),
                    "used": jnp.where(close, empty["used"], st["used"]),
                    "vals": {
                        bn: jnp.where(close, empty["vals"][bn], st["vals"][bn])
                        for bn in self.bases
                    },
                    "bucket": jnp.where(close, nb, st["bucket"]),
                }
                return st, sp, sn, ovf, closed

            new_stores, new_spills, new_spill_ns = [], [], []
            for di, dur in enumerate(self.durations):
                st = stores[di]
                nb = align_bucket(r_ts, dur)
                crossed = advance & (st["bucket"] >= 0) & (nb > st["bucket"])
                sp, sn = spills[di], spill_ns[di]
                if di == 0:
                    # the event belongs to the NEW bucket: close, then absorb
                    st, sp, sn, ovf, closed = do_close(st, di, crossed, sp, sn, ovf)
                    merged, mo = self._merge_into(
                        st, roll_keys, roll_used, roll_vals, roll_ts,
                        align_bucket(roll_ts, dur),
                    )
                    close = crossed
                else:
                    # a child rollup belongs to the OPEN bucket: absorb first,
                    # then close on the row's own time
                    st, mo = self._merge_into(
                        st, roll_keys, roll_used, roll_vals, roll_ts,
                        align_bucket(roll_ts, dur),
                    )
                    close = advance & (st["bucket"] >= 0) & (nb > st["bucket"])
                    st, sp, sn, ovf, closed = do_close(st, di, close, sp, sn, ovf)
                    merged = st
                ovf = ovf | (mo & roll_used.any())
                new_stores.append(merged)
                new_spills.append(sp)
                new_spill_ns.append(sn)
                # the rollup for the NEXT coarser duration is this close
                closed_keys, closed_used, closed_vals, closed_bucket = closed
                roll_keys = jnp.where(close, closed_keys, jnp.zeros_like(closed_keys))
                roll_used = closed_used & close
                roll_vals = {bn: closed_vals[bn] for bn in self.bases}
                roll_ts = jnp.where(close, closed_bucket, r_ts)

            return (new_stores, new_spills, new_spill_ns, ovf), None

        xs = {
            "ts": ev_ts,
            "live": live,
            "timer": is_timer,
            "key": row_key,
            **{f"v.{bn}": contribs[bn] for bn in self.bases},
        }
        (stores, spills, spill_ns, ovf), _ = lax.scan(
            body,
            (state["stores"], spill0, spill_n0, np.bool_(False)),
            xs,
        )

        aux = {"agg_overflow": ovf}
        # schedule the next root-bucket close — only when bucketing by the
        # events' own wall timestamps. With an explicit `aggregate by attr`
        # the event clock is decoupled from the scheduler's wall clock (think
        # replays of historical data), so closes are driven purely by event
        # arrival and find() merging the in-flight buckets.
        d0 = self.durations[0]
        if self.ts_expr is None and d0 not in (Duration.MONTHS, Duration.YEARS):
            aux["next_timer"] = jnp.where(
                stores[0]["bucket"] >= 0,
                stores[0]["bucket"] + d0.millis,
                np.int64(_I64MAX),
            )
        return (
            {"stores": stores, "spill": spills, "spill_n": spill_ns},
            aux,
        )

    def _spill_to_tables(self, new_state, tstates):
        """Vectorized insert of this step's closed buckets into the duration
        tables; returns updated tstates."""
        g = self.g
        for di, dur in enumerate(self.durations):
            sp = new_state["spill"][di]
            table = self.tables[dur]
            rows_used = (
                sp["used"]
                & (jnp.arange(SPILLS_PER_BATCH)[:, None] < new_state["spill_n"][di])
            ).reshape(-1)
            ts_flat = jnp.broadcast_to(
                sp["ts"][:, None], (SPILLS_PER_BATCH, g)
            ).reshape(-1)
            cols = {AGG_TS: ts_flat}
            for gname in self.group_names:
                cols[gname] = sp["vals"][f"last__g_{gname}"].reshape(-1)
            for bname in self.bases:
                if bname.startswith("last__g_"):
                    continue
                cols[f"AGG_{bname}"] = sp["vals"][bname].reshape(-1)
            dtypes = {n: a.dtype for n, a in table.schema.empty_batch(1).cols.items()}
            batch = EventBatch(
                ts=ts_flat,
                kind=jnp.zeros_like(ts_flat, jnp.int8),
                valid=rows_used,
                cols={n: cols[n].astype(dtypes[n]) for n in table.schema.attr_names},
            )
            aux = {}
            tstates[table.table_id] = table.insert(tstates[table.table_id], batch, aux)
        return tstates

    # ---- host -------------------------------------------------------------

    def describe_state(self) -> dict:
        """Introspection: per-granularity bucket state — open (in-flight)
        group count, the open bucket's start (the duration's watermark: all
        coarser output up to it is final), and the duration table's closed
        row count (see observability/introspect.py)."""
        import numpy as np

        from siddhi_tpu.observability.introspect import device_reads_ok

        out: dict = {"group_capacity": self.g, "durations": {}}
        if not device_reads_ok():
            out["durations"] = None  # degraded relay: d2h poisons dispatch
            return out
        try:
            for di, dur in enumerate(self.durations):
                store = self.state["stores"][di]
                bucket = int(np.asarray(store["bucket"]))
                entry = {
                    "open_groups": int(np.asarray(store["used"]).sum()),
                    "watermark_ms": bucket if bucket >= 0 else None,
                }
                tbl = self.tables.get(dur)
                if tbl is not None:
                    entry["closed_rows"] = int(
                        np.asarray(tbl.state["valid"]).sum()
                    )
                out["durations"][dur.name] = entry
        except Exception:
            out["durations"] = None  # mid-dispatch buffer churn: degrade
        return out

    def arm_lineage(self, cfg) -> None:
        """Enable per-bucket provenance (@app:lineage): contributing seq
        ranges + counts per finest-duration time bucket. Host-side only —
        aggregations always ride the per-batch dispatch path."""
        from siddhi_tpu.observability.lineage import AggregationLineage

        self.lineage = AggregationLineage(
            cfg, self.agg_id, self.stream_id, self.durations[0]
        )

    def receive(self, batch: EventBatch, now: int):
        lin = self.lineage
        if lin is not None:
            try:
                import numpy as _np

                ts_col = (
                    _np.asarray(batch.cols[self._lin_ts_attr]).astype("int64")
                    if self._lin_ts_attr is not None
                    else None
                )
                lin.observe_batch(batch, ts_col)
            except Exception:  # provenance must never break dispatch
                import logging

                logging.getLogger(__name__).debug(
                    "aggregation lineage observe failed", exc_info=True
                )
        tstates = {t.table_id: t.state for t in self.tables.values()}
        new_state, aux, tstates = self._step_full(batch, now, tstates)
        self.state = new_state
        for t in self.tables.values():
            t.state = tstates[t.table_id]
            if t.record_store is not None:
                t.notify_change()  # spills write through to the record store
        return aux

    def apply_late(self, ts_ms: int, row: dict) -> bool:
        """Best-effort merge of ONE late event (late.policy='apply',
        core/watermark.py). Each duration whose open bucket still covers the
        event absorbs it through the same masked merge the device step uses;
        an already-closed bucket is corrected IN PLACE in its duration table
        (sum/count add, min/max fold; `last` keeps the newer value already
        there), inserting a fresh row when the group never reached that
        bucket. find() returns table rows verbatim, so in-place update is
        the only shape that keeps store-query results correction-consistent.

        Host-side and rare by construction (each call is one metered late
        row); returns False when the event fails the aggregation's filters."""
        from siddhi_tpu.ops.group import mix_keys

        batch = self.in_schema.to_batch_cols(
            np.asarray([ts_ms], np.int64),
            {n: np.asarray([row[n]]) for n in self.in_schema.attr_names},
            self.interner,
        )
        env_cols = {(self.ref, None, n): c for n, c in batch.cols.items()}
        env_cols[(self.ref, None, TS_ATTR)] = batch.ts
        env = Env(env_cols, now=jnp.asarray(ts_ms, jnp.int64))
        for f in self.filters:
            if not bool(np.asarray(f(env))[0]):
                return False
        ev_ts = (
            int(np.asarray(self.ts_expr(env).astype(jnp.int64))[0])
            if self.ts_expr is not None
            else ts_ms
        )
        if self.group_keys:
            kcols = []
            for c in self.group_keys:
                col = jnp.asarray(c(env))
                if c.type in (AttrType.FLOAT, AttrType.DOUBLE):
                    col = col.view(jnp.int32).astype(jnp.int64)
                kcols.append(col.astype(jnp.int64))
            key = int(np.asarray(mix_keys(kcols))[0])
        else:
            key = 0
        contribs: dict = {}
        for bname, (kind, arg, _t) in self.bases.items():
            dt = self._store_dtypes[bname]
            if kind == "count":
                contribs[bname] = np.ones((), dt)[()]
            else:
                contribs[bname] = np.asarray(arg(env)).astype(dt).reshape(-1)[0]

        g = self.g
        for di, dur in enumerate(self.durations):
            b = int(np.asarray(align_bucket(jnp.asarray(ev_ts, jnp.int64), dur)))
            store = self.state["stores"][di]
            open_bucket = int(np.asarray(store["bucket"]))
            if open_bucket < 0 or b == open_bucket:
                # still in flight here: a one-hot [G] source through the
                # regular merge (opens the bucket at `b` when none is open)
                src_keys = jnp.zeros((g,), jnp.int64).at[0].set(key)
                src_used = jnp.zeros((g,), jnp.bool_).at[0].set(True)
                src_vals = {
                    bn: jnp.zeros((g,), self._store_dtypes[bn]).at[0].set(
                        contribs[bn]
                    )
                    for bn in self.bases
                }
                merged, _ovf = self._merge_into(
                    store, src_keys, src_used, src_vals,
                    jnp.asarray(ev_ts, jnp.int64), jnp.asarray(b, jnp.int64),
                )
                self.state["stores"][di] = merged
                continue
            if b > open_bucket:
                # not actually late for this duration; the live path owns
                # the close/rollup sequencing — never fast-forward it here
                continue
            # closed bucket: correct the spilled row in the duration table
            table = self.tables[dur]
            tstate = table.state
            valid = np.asarray(tstate["valid"])
            tcols = {n: np.asarray(c) for n, c in tstate["cols"].items()}
            match = valid & (tcols[AGG_TS] == b)
            for gname in self.group_names:
                gv = contribs[f"last__g_{gname}"]
                match = match & (tcols[gname] == tcols[gname].dtype.type(gv))
            idx = np.flatnonzero(match)
            if idx.size:
                ri = int(idx[0])
                new_cols = dict(tstate["cols"])
                for bname, (kind, _arg, _t) in self.bases.items():
                    if bname.startswith("last__g_") or kind == "last":
                        # group cols identify the row; a late event is never
                        # the newest by event time, so `last` stays put
                        continue
                    cname = f"AGG_{bname}"
                    col = tcols[cname].copy()
                    if kind in ("sum", "count"):
                        col[ri] += contribs[bname]
                    elif kind == "min":
                        col[ri] = min(col[ri], contribs[bname])
                    else:  # max
                        col[ri] = max(col[ri], contribs[bname])
                    new_cols[cname] = jnp.asarray(col)
                table.state = {**tstate, "cols": new_cols}
            else:
                # the group never reached this bucket: a fresh closed row
                # through the table's own insert (seq/index bookkeeping)
                dtypes = {
                    n: a.dtype
                    for n, a in table.schema.empty_batch(1).cols.items()
                }
                cols = {AGG_TS: np.asarray([b], np.int64)}
                for gname in self.group_names:
                    cols[gname] = np.asarray([contribs[f"last__g_{gname}"]])
                for bname in self.bases:
                    if bname.startswith("last__g_"):
                        continue
                    cols[f"AGG_{bname}"] = np.asarray([contribs[bname]])
                ins = EventBatch(
                    ts=jnp.asarray([b], jnp.int64),
                    kind=jnp.zeros((1,), jnp.int8),
                    valid=jnp.ones((1,), jnp.bool_),
                    cols={
                        n: jnp.asarray(cols[n].astype(dtypes[n]))
                        for n in table.schema.attr_names
                    },
                )
                table.state = table.insert(table.state, ins, {})
            if table.record_store is not None:
                table.notify_change()
        return True

    def _step_full(self, batch, now, tstates):
        if not hasattr(self, "_jit_full"):
            def full(state, batch, now, tstates):
                new_state, aux = self._step_impl(state, batch, now)
                tstates = self._spill_to_tables(new_state, tstates)
                return new_state, aux, tstates

            self._jit_full = jax.jit(full)
        return self._jit_full(self.state, batch, jnp.asarray(now, jnp.int64), tstates)

    # ---- find (store query / join) ---------------------------------------

    def find(self, per: Duration, within: Optional[tuple[int, int]], now: int):
        """Rows for `from A within .. per '<dur>'`: closed buckets from the
        duration table merged with the in-flight buckets of this and all finer
        durations (reference: AggregationRuntime.find:176 +
        IncrementalDataAggregator)."""
        if per not in self.tables:
            raise SiddhiAppCreationError(
                f"aggregation '{self.agg_id}' has no '{per.name}' duration"
            )
        key = per
        if key not in self._finds:
            self._finds[key] = jax.jit(lambda st, ts, now: self._find_impl(per, st, ts, now))
        tstate = self.tables[per].state
        out = self._finds[key](self.state, tstate, jnp.asarray(now, jnp.int64))
        if within is not None:
            lo, hi = within
            valid = out.valid & (out.ts >= lo) & (out.ts < hi)
            out = EventBatch(out.ts, out.kind, valid, out.cols)
        return out

    def _find_impl(self, per: Duration, state, tstate, now):
        g = self.g
        per_idx = self.durations.index(per)
        # merge in-flight stores (finest..per) into one temp store aligned to per
        temp = dict(self._empty)
        temp = {**temp, "bucket": jnp.full((), -1, jnp.int64)}
        ovf = np.bool_(False)
        for di in range(per_idx + 1):
            st = state["stores"][di]
            has = st["bucket"] >= 0
            aligned = jnp.where(has, align_bucket(jnp.maximum(st["bucket"], 0), per), -1)
            temp, mo = self._merge_into(
                temp,
                st["keys"],
                st["used"] & has,
                st["vals"],
                aligned,
                aligned,
            )
            ovf = ovf | mo

        # recompose output columns for a store: (used[G], vals) -> cols
        def recompose(vals):
            cols = {}
            for s in self.out_specs:
                if s.kind == "avg":
                    # logical DOUBLE runs as f32 on TPU (types.PHYSICAL_DTYPE)
                    num = vals[f"sum_{s.name}"].astype(jnp.float32)
                    den = vals["count_"].astype(jnp.float32)
                    cols[s.name] = jnp.where(den != 0, num / den, jnp.nan)
                elif s.kind == "sum":
                    cols[s.name] = vals[f"sum_{s.name}"]
                elif s.kind == "count":
                    cols[s.name] = vals["count_"]
                elif s.kind in ("min", "max"):
                    cols[s.name] = vals[f"{s.kind}_{s.name}"]
                else:
                    cols[s.name] = vals[f"last_{s.name}"]
            return cols

        inflight_cols = recompose(temp["vals"])
        inflight_ts = jnp.full((g,), temp["bucket"], jnp.int64)
        inflight_valid = temp["used"] & (temp["bucket"] >= 0)

        # table rows: recompose from AGG_<base> columns
        tvals = {}
        for bname in self.bases:
            if bname.startswith("last__g_"):
                gname = bname[len("last__g_"):]
                tvals[bname] = tstate["cols"][gname]
            else:
                tvals[bname] = tstate["cols"][f"AGG_{bname}"]
        table_cols = recompose(tvals)
        table_ts = tstate["cols"][AGG_TS]
        table_valid = tstate["valid"]

        out_dtypes = {
            n: a.dtype for n, a in self.out_schema.empty_batch(1).cols.items()
        }
        cols = {AGG_TS: jnp.concatenate([table_ts, inflight_ts]).astype(out_dtypes[AGG_TS])}
        for s in self.out_specs:
            cols[s.name] = jnp.concatenate(
                [
                    table_cols[s.name].astype(out_dtypes[s.name]),
                    inflight_cols[s.name].astype(out_dtypes[s.name]),
                ]
            )
        return EventBatch(
            ts=jnp.concatenate([table_ts, inflight_ts]),
            kind=jnp.zeros((table_ts.shape[0] + g,), jnp.int8),
            valid=jnp.concatenate([table_valid, inflight_valid]),
            cols=cols,
        )


class AggFindable:
    """Findable adapter exposing an aggregation's merged view (closed buckets
    + in-flight) as a passive join side (reference: AggregationRuntime
    participating in joins via compileExpression/find,
    AggregationRuntime.java:176-300)."""

    is_named_window = False  # passive probe target, like a table

    def __init__(self, agg: "AggregationRuntime", per: Duration, within):
        if per not in agg.tables:
            raise SiddhiAppCreationError(
                f"aggregation '{agg.agg_id}' has no '{per.name}' duration"
            )
        self.agg = agg
        self.per = per
        self.within = within  # (start_ms, end_ms) or None (static bounds)
        self.table_id = f"__aggview_{agg.agg_id}_{per.name}"
        self.schema = agg.out_schema

    @property
    def state(self):
        return {
            "agg": self.agg.state,
            "table": self.agg.tables[self.per].state,
        }

    @state.setter
    def state(self, value):  # joins never write through; writeback is a no-op
        pass

    def view(self, packed):
        out = self.agg._find_impl(
            self.per, packed["agg"], packed["table"], np.int64(0)
        )
        valid = out.valid
        if self.within is not None:
            lo, hi = self.within
            valid = valid & (out.ts >= lo) & (out.ts < hi)
        return out.cols, out.ts, valid


# ---------------------------------------------------------------------------
# within / per parsing (host)
# ---------------------------------------------------------------------------

_DUR_NAMES = {
    "sec": Duration.SECONDS, "second": Duration.SECONDS, "seconds": Duration.SECONDS,
    "min": Duration.MINUTES, "minute": Duration.MINUTES, "minutes": Duration.MINUTES,
    "hour": Duration.HOURS, "hours": Duration.HOURS,
    "day": Duration.DAYS, "days": Duration.DAYS,
    "month": Duration.MONTHS, "months": Duration.MONTHS,
    "year": Duration.YEARS, "years": Duration.YEARS,
}


def parse_per(value) -> Duration:
    d = _DUR_NAMES.get(str(value).strip().lower())
    if d is None:
        raise SiddhiAppCreationError(f"unknown aggregation duration {value!r}")
    return d


_TIME_RE = re.compile(
    r"^(\d{4}|\*{1,4})-(\d{2}|\*{1,2})-(\d{2}|\*{1,2})"
    r"(?:[ T](\d{2}|\*{1,2}):(\d{2}|\*{1,2}):(\d{2}|\*{1,2}))?"
    r"(?:\s*(?:Z|([+-])(\d{2}):(\d{2})))?$"
)


def parse_within_value(v) -> tuple[int, int]:
    """One `within` operand -> [start, end) ms. Longs are exact instants;
    strings follow the reference's `yyyy-MM-dd HH:mm:ss` (GMT default) with
    `**` wildcards expanding to the containing range."""
    import calendar
    import datetime as dt

    if isinstance(v, (int, float)):
        return int(v), int(v) + 1
    m = _TIME_RE.match(str(v).strip())
    if not m:
        raise SiddhiAppCreationError(f"cannot parse within time {v!r}")
    y, mo, d, h, mi, s = m.group(1, 2, 3, 4, 5, 6)
    off_sign, off_h, off_m = m.group(7, 8, 9)
    offset_ms = 0
    if off_sign:
        offset_ms = (int(off_h) * 3600 + int(off_m) * 60) * 1000
        if off_sign == "-":
            offset_ms = -offset_ms

    def wild(x):
        return x is None or "*" in x

    parts = [y, mo, d, h, mi, s]
    # find the first wildcarded component; everything after must be wild too
    level = 6
    for i, p in enumerate(parts):
        if wild(p):
            level = i
            break
    for p in parts[level + 1 :] if level < 6 else []:
        if not wild(p):
            raise SiddhiAppCreationError(
                f"within {v!r}: components after a wildcard must be wildcards"
            )
    vals = [int(p) if not wild(p) else 0 for p in parts]
    y_, mo_, d_, h_, mi_, s_ = vals
    if level == 0:
        raise SiddhiAppCreationError(f"within {v!r}: year cannot be a wildcard")
    start = dt.datetime(
        y_, mo_ if level > 1 else 1, d_ if level > 2 else 1,
        h_ if level > 3 else 0, mi_ if level > 4 else 0, s_ if level > 5 else 0,
        tzinfo=dt.timezone.utc,
    )
    if level == 1:
        end = start.replace(year=start.year + 1)
    elif level == 2:
        end = (
            start.replace(year=start.year + 1, month=1)
            if start.month == 12
            else start.replace(month=start.month + 1)
        )
    elif level == 3:
        end = start + dt.timedelta(days=1)
    elif level == 4:
        end = start + dt.timedelta(hours=1)
    elif level == 5:
        end = start + dt.timedelta(minutes=1)
    else:
        end = start + dt.timedelta(seconds=1)
    start_ms = int(start.timestamp() * 1000) - offset_ms
    end_ms = int(end.timestamp() * 1000) - offset_ms
    return start_ms, end_ms
