"""The Flow — the trace-time object threaded through a compiled query chain.

The reference threads `ComplexEventChunk`s through a linked `Processor` chain
(reference: query/processor/Processor.java); here the chain is a compile-time
composition of stages, each a pure function over this Flow during jit tracing.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from siddhi_tpu.core.event import EventBatch, KIND_CURRENT, KIND_EXPIRED, KIND_RESET
from siddhi_tpu.core.executor import Env, TS_ATTR, VALID_ATTR, VarKey


@dataclasses.dataclass
class Flow:
    """batch: events flowing through (padding/filtered rows have valid=False)
    refs: stream refs whose attributes the batch columns carry (cols keyed
          (ref, None, attr) in `extra`; primary single-stream cols live in
          batch.cols under plain attr names for ref `ref`)
    member/member_env: window membership view (see aggregators.FlowInfo)
    """

    batch: EventBatch
    ref: str
    now: jnp.ndarray  # scalar int64 wall/playback clock
    extra_cols: dict[VarKey, jnp.ndarray] = dataclasses.field(default_factory=dict)
    member: Optional[jnp.ndarray] = None
    member_env: Optional[Env] = None
    # device scalars surfaced to the host after the step (e.g. next_timer)
    aux: dict = dataclasses.field(default_factory=dict)
    # live table states keyed by table id (for `in <table>` conditions)
    tables: dict = dataclasses.field(default_factory=dict)

    def env(self) -> Env:
        cols: dict[VarKey, jnp.ndarray] = {
            (self.ref, None, name): arr for name, arr in self.batch.cols.items()
        }
        cols[(self.ref, None, TS_ATTR)] = self.batch.ts
        cols[(self.ref, None, VALID_ATTR)] = self.batch.valid
        cols.update(self.extra_cols)
        return Env(cols, now=self.now, tables=self.tables)

    # ---- kind masks ----
    @property
    def current(self) -> jnp.ndarray:
        return self.batch.valid & (self.batch.kind == KIND_CURRENT)

    @property
    def expired(self) -> jnp.ndarray:
        return self.batch.valid & (self.batch.kind == KIND_EXPIRED)

    @property
    def reset(self) -> jnp.ndarray:
        return self.batch.valid & (self.batch.kind == KIND_RESET)

    @property
    def sign(self) -> jnp.ndarray:
        return self.current.astype(jnp.int8) - self.expired.astype(jnp.int8)
