"""Error store — durable parking lot for events that failed processing.

Reference: core/util/error/handler/ErrorStoreHelper.java +
siddhi-distribution's DBErrorStore: events rejected by `@OnError(action='STORE')`
streams and `on.error='STORE'` sinks are captured as ErroneousEvent records that
can be queried, replayed into the originating stream/sink, and purged. The
built-in implementation is an in-memory bounded ring; persistent backends plug
in through the same three-method surface (`store` / `load` / `purge`).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Optional

# ErroneousEvent.origin values
ORIGIN_STREAM = "stream"
ORIGIN_SINK = "sink"
# ingress-transport map/deliver failures (@source on.error='STORE'); the
# raw wire payload is retained and replay re-delivers it through the source
ORIGIN_SOURCE = "source"
# table mutation failures (@OnError on a table definition): `stream_id` is
# the TABLE id (attribution), `sink_ref` carries the mutating query's input
# stream so replay can re-drive the batch
ORIGIN_TABLE = "table"

# @OnError actions on table / named-window DEFINITIONS. STREAM is
# stream/window-only: a table mutation's failing unit is the mutating
# query's input batch, which does not carry the table's schema — there is
# no well-typed '!T' row to publish.
TABLE_ONERROR_ACTIONS = ("LOG", "STORE")
WINDOW_ONERROR_ACTIONS = ("LOG", "STREAM", "STORE")


def resolve_definition_onerror_action(ann) -> str:
    """Normalized action of a table/window `@OnError` annotation: keyed
    `action=...` or a single positional (`@OnError('STORE')`). A single
    UNRELATED keyed element must not leak in as the action, so this does
    not use `ann.element(None)` (whose single-element fallback ignores
    the key)."""
    v = ann.element("action")
    if v is None and len(ann.elements) == 1 and ann.elements[0][0] is None:
        v = ann.elements[0][1]
    return str(v or "LOG").upper()


def iter_definition_onerror_problems(ann, kind: str, name: str, attr_names=()):
    """Yield (tag, message) per problem with a table/window `@OnError`
    annotation — ONE rule set shared by the analyzer (tag 'action' -> SA110,
    'reserved' -> SA111) and the runtime wiring (SiddhiAppCreationError),
    like the supervised-runtime annotations in core/supervision.py."""
    action = resolve_definition_onerror_action(ann)
    if kind == "table":
        if action not in TABLE_ONERROR_ACTIONS:
            yield "action", (
                f"table '{name}': unknown @OnError action '{action}' "
                "(tables support LOG or STORE)"
            )
        return
    if action not in WINDOW_ONERROR_ACTIONS:
        yield "action", (
            f"window '{name}': unknown @OnError action '{action}' "
            "(expected LOG, STREAM, or STORE)"
        )
        return
    if action == "STREAM" and "_error" in attr_names:
        yield "reserved", (
            f"window '{name}': @OnError(action='STREAM') reserves "
            "the attribute name '_error'"
        )


@dataclasses.dataclass
class ErroneousEvent:
    """One failed unit of work (reference: util/error/handler/ErroneousEvent).

    Stream-origin entries carry the failing batch's decoded host rows in
    `events` as `(timestamp_ms, data_tuple)` pairs; sink-origin entries carry
    the already-mapped wire `payload` instead.
    """

    id: int
    stored_at_ms: int
    app_name: str
    origin: str  # ORIGIN_STREAM | ORIGIN_SINK
    stream_id: str
    error: str
    events: Optional[list[tuple[int, tuple]]] = None
    payload: Any = None
    cause: Optional[BaseException] = None
    # identifies WHICH sink on stream_id failed (a stream can carry several
    # @sink annotations / @distribution destinations); replay targets it
    sink_ref: str = ""
    # flight-recorder dump: the last-N events through the failing junction
    # at capture time, as (timestamp_ms, data_tuple) pairs (None when the
    # junction has no recorder — see observability/flight.py)
    flight: Optional[list[tuple[int, tuple]]] = None
    # lineage provenance: the failing batch's contributing seq-id range on
    # its stream ({stream, seq_lo, seq_hi}; None when @app:lineage is off —
    # see observability/lineage.py)
    lineage: Optional[dict] = None


class ErrorStore:
    """Pluggable SPI; implementations must be thread-safe (dispatch threads,
    sink publish threads, and replay callers all touch the store)."""

    def store(self, entry: ErroneousEvent) -> None:
        raise NotImplementedError

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        raise NotImplementedError

    def purge(self, ids: Optional[list[int]] = None) -> int:
        raise NotImplementedError

    def describe_state(self) -> dict:
        """Introspection: depth + per-app breakdown (generic implementation
        rides `load()`; bounded stores override with O(1) reads)."""
        entries = self.load()
        by_app: dict[str, int] = {}
        for e in entries:
            by_app[e.app_name] = by_app.get(e.app_name, 0) + 1
        return {"depth": len(entries), "by_app": by_app}


class InMemoryErrorStore(ErrorStore):
    """Capacity-bounded FIFO store: when full, the OLDEST entries are evicted
    (the newest failure is the one an operator most wants to see) and counted
    in `dropped`."""

    def __init__(self, capacity: int = 10_000):
        if capacity <= 0:
            raise ValueError("error store capacity must be positive")
        self.capacity = int(capacity)
        self.dropped = 0
        self._entries: dict[int, ErroneousEvent] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def store(self, entry: ErroneousEvent) -> None:
        with self._lock:
            if entry.id == 0:
                entry.id = next(self._ids)
            if entry.stored_at_ms == 0:
                entry.stored_at_ms = int(time.time() * 1000)
            self._entries[entry.id] = entry
            while len(self._entries) > self.capacity:
                self._entries.pop(next(iter(self._entries)))
                self.dropped += 1

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        with self._lock:
            out = [
                e
                for e in self._entries.values()
                if (app_name is None or e.app_name == app_name)
                and (stream_id is None or e.stream_id == stream_id)
                and (origin is None or e.origin == origin)
            ]
        return out[:limit] if limit is not None else out

    def purge(self, ids: Optional[list[int]] = None) -> int:
        with self._lock:
            if ids is None:
                n = len(self._entries)
                self._entries.clear()
                return n
            n = 0
            for i in ids:
                if self._entries.pop(i, None) is not None:
                    n += 1
            return n

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def describe_state(self) -> dict:
        with self._lock:
            by_app: dict[str, int] = {}
            for e in self._entries.values():
                by_app[e.app_name] = by_app.get(e.app_name, 0) + 1
            return {
                "depth": len(self._entries),
                "capacity": self.capacity,
                "dropped": self.dropped,
                "by_app": by_app,
            }


class FileErrorStore(ErrorStore):
    """File-backed persistent store: one JSONL file per app under
    `base_path` (layout mirrors `persistence.FileSystemPersistenceStore`'s
    directory-per-concern shape), so error entries — including their
    flight-recorder dumps — survive restart.

    Serialization is plain JSON: `events`/`flight` row tuples become lists
    on disk and are re-tupled on load (replay re-encodes them through the
    input handler either way); the exception object itself (`cause`) does
    not survive — its rendered `error` string does. Non-JSON payloads are
    stringified rather than lost.
    """

    def __init__(self, base_path: str, capacity: int = 100_000):
        import os

        if capacity <= 0:
            raise ValueError("error store capacity must be positive")
        self.base_path = base_path
        self.capacity = int(capacity)
        self.dropped = 0
        self._lock = threading.Lock()
        os.makedirs(base_path, exist_ok=True)
        # ids must stay unique across restarts: resume after the max on disk
        # (the same scan seeds the entry count so the capacity check is O(1)
        # per store instead of re-reading the directory)
        best = 0
        n = 0
        for e in self._iter_entries():
            best = max(best, e.id)
            n += 1
        self._ids = itertools.count(best + 1)
        self._count = n

    def _files(self) -> list[str]:
        import os

        return sorted(
            os.path.join(self.base_path, f)
            for f in os.listdir(self.base_path)
            if f.endswith(".jsonl")
        )

    def _app_file(self, app_name: str) -> str:
        import os

        # app names come from @app:name — keep the file name filesystem-safe
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in app_name
        )
        return os.path.join(self.base_path, f"{safe}.jsonl")

    @staticmethod
    def _to_json(entry: ErroneousEvent) -> dict:
        # built by hand, NOT dataclasses.asdict: asdict deep-copies every
        # field first, and deep-copying the live exception in `cause` fails
        # for exception classes with non-default __init__ signatures —
        # raising from inside the very store() call that was capturing the
        # failure. `error` already carries the rendered message.
        d = {
            "id": entry.id,
            "stored_at_ms": entry.stored_at_ms,
            "app_name": entry.app_name,
            "origin": entry.origin,
            "stream_id": entry.stream_id,
            "error": entry.error,
            "events": entry.events,
            "payload": entry.payload,
            "sink_ref": entry.sink_ref,
            "flight": entry.flight,
            "lineage": entry.lineage,
        }
        try:
            import json

            json.dumps(d.get("payload"))
        except (TypeError, ValueError):
            d["payload"] = repr(d.get("payload"))
        return d

    @staticmethod
    def _from_json(d: dict) -> ErroneousEvent:
        for key in ("events", "flight"):
            if d.get(key) is not None:
                d[key] = [(int(ts), tuple(row)) for ts, row in d[key]]
        return ErroneousEvent(cause=None, **d)

    def _iter_entries(self):
        import json

        for path in self._files():
            try:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            yield self._from_json(json.loads(line))
                        except Exception:
                            continue  # a torn tail line must not kill load()
            except OSError:
                continue

    def store(self, entry: ErroneousEvent) -> None:
        import json

        with self._lock:
            if entry.id == 0:
                entry.id = next(self._ids)
            if entry.stored_at_ms == 0:
                entry.stored_at_ms = int(time.time() * 1000)
            with open(self._app_file(entry.app_name), "a", encoding="utf-8") as f:
                f.write(json.dumps(self._to_json(entry), default=str) + "\n")
            self._count += 1
            if self._count > self.capacity:
                self._enforce_capacity()

    def _enforce_capacity(self) -> None:
        """FIFO eviction across the whole directory (oldest ids first),
        same policy as the in-memory store. Caller holds the lock; only
        invoked when the running count exceeds capacity. Evicts down to 90%
        of capacity, not to capacity exactly — the eviction pass re-parses
        the directory (O(capacity)), and dropping with slack amortizes that
        over the next capacity/10 stores instead of paying it on every
        store() once the directory is full."""
        entries = sorted(self._iter_entries(), key=lambda e: e.id)
        self._count = len(entries)  # re-sync (torn lines are not counted)
        if len(entries) <= self.capacity:
            return
        target = max(1, (self.capacity * 9) // 10)
        evict = {e.id for e in entries[: len(entries) - target]}
        # count only what was ACTUALLY removed (a momentarily unreadable
        # app file skips its rewrite): dropped must reconcile with disk
        removed = self._rewrite_without(evict)
        self.dropped += removed
        self._count -= removed

    def _rewrite_without(self, ids: set) -> int:
        """Rewrite every app file dropping `ids`; returns how many entries
        were removed. Caller holds the lock."""
        import json
        import os

        removed = 0
        for path in self._files():
            keep: list[str] = []
            changed = False
            try:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            eid = json.loads(line).get("id")
                        except Exception:
                            changed = True  # drop torn lines on rewrite
                            continue
                        if eid in ids:
                            removed += 1
                            changed = True
                        else:
                            keep.append(line)
            except OSError:
                continue
            if not changed:
                continue
            if keep:
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write("\n".join(keep) + "\n")
                os.replace(tmp, path)
            else:
                os.unlink(path)
        return removed

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        with self._lock:
            out = [
                e
                for e in self._iter_entries()
                if (app_name is None or e.app_name == app_name)
                and (stream_id is None or e.stream_id == stream_id)
                and (origin is None or e.origin == origin)
            ]
        out.sort(key=lambda e: e.id)
        return out[:limit] if limit is not None else out

    def purge(self, ids: Optional[list[int]] = None) -> int:
        import os

        with self._lock:
            if ids is None:
                n = sum(1 for _ in self._iter_entries())
                for path in self._files():
                    os.unlink(path)
                self._count = 0
                return n
            removed = self._rewrite_without(set(ids))
            self._count = max(0, self._count - removed)
            return removed

    def size(self) -> int:
        """O(1): the running count (seeded by the init scan, adjusted by
        store/purge/eviction) — selfmon polls this every tick, and a
        directory re-parse per poll would stall the scheduler thread."""
        with self._lock:
            return self._count

    def describe_state(self) -> dict:
        """The per-app breakdown does read the directory — describe_state
        is an on-demand introspection pull, not a periodic poll."""
        with self._lock:
            by_app: dict[str, int] = {}
            for e in self._iter_entries():
                by_app[e.app_name] = by_app.get(e.app_name, 0) + 1
            return {
                "depth": self._count,
                "capacity": self.capacity,
                "dropped": self.dropped,
                "by_app": by_app,
                "path": self.base_path,
            }


class SqliteErrorStore(ErrorStore):
    """DB-backed persistent store on stdlib `sqlite3`, through the same
    `store/load/purge` SPI as every other backend.

    One `errors` table; `events`/`payload`/`flight` serialize as JSON text
    (non-JSON payloads are stringified, mirroring `FileErrorStore`). Ids
    ride an AUTOINCREMENT rowid, which sqlite guarantees never reuses even
    after deletes — the same id-uniqueness-across-restarts contract
    `FileErrorStore` keeps by scanning for the max id. Capacity is FIFO:
    over-capacity inserts evict the oldest ids in one DELETE.

    Thread-safe via one connection guarded by one lock (`sqlite3`
    serializes per-connection anyway; the lock keeps the
    capacity-check-then-evict sequence atomic).
    """

    def __init__(self, path: str, capacity: int = 100_000):
        import sqlite3

        if capacity <= 0:
            raise ValueError("error store capacity must be positive")
        self.path = path
        self.capacity = int(capacity)
        self.dropped = 0
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS errors ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " stored_at_ms INTEGER NOT NULL,"
            " app_name TEXT NOT NULL,"
            " origin TEXT NOT NULL,"
            " stream_id TEXT NOT NULL,"
            " error TEXT NOT NULL,"
            " events TEXT,"
            " payload TEXT,"
            " sink_ref TEXT NOT NULL DEFAULT '',"
            " flight TEXT,"
            " lineage TEXT)"
        )
        try:
            # pre-lineage databases lack the new column; the ALTER raises
            # once it exists, making re-opens idempotent
            self._conn.execute("ALTER TABLE errors ADD COLUMN lineage TEXT")
        except sqlite3.OperationalError:
            pass
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS errors_app ON errors(app_name)"
        )
        self._conn.commit()
        # running count: one seed scan here, then maintained by store/purge
        # — `SELECT COUNT(*)` is a full table scan in sqlite, and paying it
        # per insert (capacity check) or per selfmon poll would serialize
        # error bursts behind repeated 100k-row scans
        self._count = int(
            self._conn.execute("SELECT COUNT(*) FROM errors").fetchone()[0]
        )

    @staticmethod
    def _json_or_repr(v) -> Optional[str]:
        import json

        if v is None:
            return None
        try:
            return json.dumps(v)
        except (TypeError, ValueError):
            return json.dumps(repr(v))

    def store(self, entry: ErroneousEvent) -> None:
        import json

        with self._lock:
            if entry.stored_at_ms == 0:
                entry.stored_at_ms = int(time.time() * 1000)
            cols = (
                entry.stored_at_ms, entry.app_name, entry.origin,
                entry.stream_id, entry.error,
                # default=str like FileErrorStore: event rows off a
                # device batch carry numpy scalars, and the STORE path
                # must never throw back at the sender it shields
                json.dumps(entry.events, default=str)
                if entry.events is not None else None,
                self._json_or_repr(entry.payload),
                entry.sink_ref,
                json.dumps(entry.flight, default=str)
                if entry.flight is not None else None,
                json.dumps(entry.lineage, default=str)
                if entry.lineage is not None else None,
            )
            if entry.id:
                # honor a pre-set id like the other stores do (re-storing a
                # loaded entry must stay purgeable by ITS id); OR REPLACE
                # keeps a same-id re-store idempotent. Explicit ids bump
                # sqlite's AUTOINCREMENT sequence, so uniqueness holds.
                replacing = self._conn.execute(
                    "SELECT 1 FROM errors WHERE id = ?", (int(entry.id),)
                ).fetchone() is not None
                self._conn.execute(
                    "INSERT OR REPLACE INTO errors (id, stored_at_ms,"
                    " app_name, origin, stream_id, error, events, payload,"
                    " sink_ref, flight, lineage)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                    (int(entry.id),) + cols,
                )
                if not replacing:
                    self._count += 1
            else:
                cur = self._conn.execute(
                    "INSERT INTO errors (stored_at_ms, app_name, origin,"
                    " stream_id, error, events, payload, sink_ref, flight,"
                    " lineage) VALUES (?,?,?,?,?,?,?,?,?,?)",
                    cols,
                )
                entry.id = int(cur.lastrowid)
                self._count += 1
            if self._count > self.capacity:
                evict = self._count - self.capacity
                self._conn.execute(
                    "DELETE FROM errors WHERE id IN"
                    " (SELECT id FROM errors ORDER BY id LIMIT ?)",
                    (evict,),
                )
                self.dropped += evict
                self._count = self.capacity
            self._conn.commit()

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        import json

        q = "SELECT id, stored_at_ms, app_name, origin, stream_id, error," \
            " events, payload, sink_ref, flight, lineage FROM errors"
        conds, args = [], []
        for col, v in (
            ("app_name", app_name), ("stream_id", stream_id), ("origin", origin),
        ):
            if v is not None:
                conds.append(f"{col} = ?")
                args.append(v)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        q += " ORDER BY id"
        if limit is not None:
            q += " LIMIT ?"
            args.append(int(limit))
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        out = []
        for (
            eid, at, app, origin_, sid, err, events, payload, ref, flight,
            lineage,
        ) in rows:
            ev = json.loads(events) if events is not None else None
            if ev is not None:
                ev = [(int(ts), tuple(row)) for ts, row in ev]
            fl = json.loads(flight) if flight is not None else None
            if fl is not None:
                fl = [(int(ts), tuple(row)) for ts, row in fl]
            out.append(ErroneousEvent(
                id=eid, stored_at_ms=at, app_name=app, origin=origin_,
                stream_id=sid, error=err, events=ev,
                payload=json.loads(payload) if payload is not None else None,
                cause=None, sink_ref=ref, flight=fl,
                lineage=json.loads(lineage) if lineage is not None else None,
            ))
        return out

    def purge(self, ids: Optional[list[int]] = None) -> int:
        with self._lock:
            if ids is None:
                n = self._count
                self._conn.execute("DELETE FROM errors")
                self._conn.commit()
                self._count = 0
                return n
            n = 0
            for i in ids:
                n += self._conn.execute(
                    "DELETE FROM errors WHERE id = ?", (int(i),)
                ).rowcount
            self._conn.commit()
            self._count = max(0, self._count - n)
            return n

    def size(self) -> int:
        """O(1): the running count — selfmon polls this every tick, and a
        COUNT(*) table scan per poll would stall the scheduler thread."""
        with self._lock:
            return self._count

    def describe_state(self) -> dict:
        with self._lock:
            by_app = dict(self._conn.execute(
                "SELECT app_name, COUNT(*) FROM errors GROUP BY app_name"
            ).fetchall())
            depth = self._count
        return {
            "depth": depth,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "by_app": by_app,
            "path": self.path,
        }

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def make_entry(
    app_name: str,
    origin: str,
    stream_id: str,
    error: BaseException | str,
    events: Optional[list[tuple[int, tuple]]] = None,
    payload: Any = None,
    sink_ref: str = "",
) -> ErroneousEvent:
    exc = error if isinstance(error, BaseException) else None
    if exc is not None:
        # drop the frame chains (including chained __cause__/__context__
        # exceptions): a retained traceback pins every frame's locals
        # (decoded events, device batches) for the life of the store
        seen: set[int] = set()
        e: Optional[BaseException] = exc
        while e is not None and id(e) not in seen:
            seen.add(id(e))
            e.__traceback__ = None
            e = e.__cause__ or e.__context__
    return ErroneousEvent(
        id=0,
        stored_at_ms=0,
        app_name=app_name,
        origin=origin,
        stream_id=stream_id,
        error=f"{type(error).__name__}: {error}" if exc is not None else str(error),
        events=events,
        payload=payload,
        cause=exc,
        sink_ref=sink_ref,
    )
