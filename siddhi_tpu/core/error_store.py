"""Error store — durable parking lot for events that failed processing.

Reference: core/util/error/handler/ErrorStoreHelper.java +
siddhi-distribution's DBErrorStore: events rejected by `@OnError(action='STORE')`
streams and `on.error='STORE'` sinks are captured as ErroneousEvent records that
can be queried, replayed into the originating stream/sink, and purged. The
built-in implementation is an in-memory bounded ring; persistent backends plug
in through the same three-method surface (`store` / `load` / `purge`).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Optional

# ErroneousEvent.origin values
ORIGIN_STREAM = "stream"
ORIGIN_SINK = "sink"


@dataclasses.dataclass
class ErroneousEvent:
    """One failed unit of work (reference: util/error/handler/ErroneousEvent).

    Stream-origin entries carry the failing batch's decoded host rows in
    `events` as `(timestamp_ms, data_tuple)` pairs; sink-origin entries carry
    the already-mapped wire `payload` instead.
    """

    id: int
    stored_at_ms: int
    app_name: str
    origin: str  # ORIGIN_STREAM | ORIGIN_SINK
    stream_id: str
    error: str
    events: Optional[list[tuple[int, tuple]]] = None
    payload: Any = None
    cause: Optional[BaseException] = None
    # identifies WHICH sink on stream_id failed (a stream can carry several
    # @sink annotations / @distribution destinations); replay targets it
    sink_ref: str = ""


class ErrorStore:
    """Pluggable SPI; implementations must be thread-safe (dispatch threads,
    sink publish threads, and replay callers all touch the store)."""

    def store(self, entry: ErroneousEvent) -> None:
        raise NotImplementedError

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        raise NotImplementedError

    def purge(self, ids: Optional[list[int]] = None) -> int:
        raise NotImplementedError


class InMemoryErrorStore(ErrorStore):
    """Capacity-bounded FIFO store: when full, the OLDEST entries are evicted
    (the newest failure is the one an operator most wants to see) and counted
    in `dropped`."""

    def __init__(self, capacity: int = 10_000):
        if capacity <= 0:
            raise ValueError("error store capacity must be positive")
        self.capacity = int(capacity)
        self.dropped = 0
        self._entries: dict[int, ErroneousEvent] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def store(self, entry: ErroneousEvent) -> None:
        with self._lock:
            if entry.id == 0:
                entry.id = next(self._ids)
            if entry.stored_at_ms == 0:
                entry.stored_at_ms = int(time.time() * 1000)
            self._entries[entry.id] = entry
            while len(self._entries) > self.capacity:
                self._entries.pop(next(iter(self._entries)))
                self.dropped += 1

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        with self._lock:
            out = [
                e
                for e in self._entries.values()
                if (app_name is None or e.app_name == app_name)
                and (stream_id is None or e.stream_id == stream_id)
                and (origin is None or e.origin == origin)
            ]
        return out[:limit] if limit is not None else out

    def purge(self, ids: Optional[list[int]] = None) -> int:
        with self._lock:
            if ids is None:
                n = len(self._entries)
                self._entries.clear()
                return n
            n = 0
            for i in ids:
                if self._entries.pop(i, None) is not None:
                    n += 1
            return n

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


def make_entry(
    app_name: str,
    origin: str,
    stream_id: str,
    error: BaseException | str,
    events: Optional[list[tuple[int, tuple]]] = None,
    payload: Any = None,
    sink_ref: str = "",
) -> ErroneousEvent:
    exc = error if isinstance(error, BaseException) else None
    if exc is not None:
        # drop the frame chains (including chained __cause__/__context__
        # exceptions): a retained traceback pins every frame's locals
        # (decoded events, device batches) for the life of the store
        seen: set[int] = set()
        e: Optional[BaseException] = exc
        while e is not None and id(e) not in seen:
            seen.add(id(e))
            e.__traceback__ = None
            e = e.__cause__ or e.__context__
    return ErroneousEvent(
        id=0,
        stored_at_ms=0,
        app_name=app_name,
        origin=origin,
        stream_id=stream_id,
        error=f"{type(error).__name__}: {error}" if exc is not None else str(error),
        events=events,
        payload=payload,
        cause=exc,
        sink_ref=sink_ref,
    )
