"""Error store — durable parking lot for events that failed processing.

Reference: core/util/error/handler/ErrorStoreHelper.java +
siddhi-distribution's DBErrorStore: events rejected by `@OnError(action='STORE')`
streams and `on.error='STORE'` sinks are captured as ErroneousEvent records that
can be queried, replayed into the originating stream/sink, and purged. The
built-in implementation is an in-memory bounded ring; persistent backends plug
in through the same three-method surface (`store` / `load` / `purge`).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Optional

# ErroneousEvent.origin values
ORIGIN_STREAM = "stream"
ORIGIN_SINK = "sink"


@dataclasses.dataclass
class ErroneousEvent:
    """One failed unit of work (reference: util/error/handler/ErroneousEvent).

    Stream-origin entries carry the failing batch's decoded host rows in
    `events` as `(timestamp_ms, data_tuple)` pairs; sink-origin entries carry
    the already-mapped wire `payload` instead.
    """

    id: int
    stored_at_ms: int
    app_name: str
    origin: str  # ORIGIN_STREAM | ORIGIN_SINK
    stream_id: str
    error: str
    events: Optional[list[tuple[int, tuple]]] = None
    payload: Any = None
    cause: Optional[BaseException] = None
    # identifies WHICH sink on stream_id failed (a stream can carry several
    # @sink annotations / @distribution destinations); replay targets it
    sink_ref: str = ""
    # flight-recorder dump: the last-N events through the failing junction
    # at capture time, as (timestamp_ms, data_tuple) pairs (None when the
    # junction has no recorder — see observability/flight.py)
    flight: Optional[list[tuple[int, tuple]]] = None


class ErrorStore:
    """Pluggable SPI; implementations must be thread-safe (dispatch threads,
    sink publish threads, and replay callers all touch the store)."""

    def store(self, entry: ErroneousEvent) -> None:
        raise NotImplementedError

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        raise NotImplementedError

    def purge(self, ids: Optional[list[int]] = None) -> int:
        raise NotImplementedError

    def describe_state(self) -> dict:
        """Introspection: depth + per-app breakdown (generic implementation
        rides `load()`; bounded stores override with O(1) reads)."""
        entries = self.load()
        by_app: dict[str, int] = {}
        for e in entries:
            by_app[e.app_name] = by_app.get(e.app_name, 0) + 1
        return {"depth": len(entries), "by_app": by_app}


class InMemoryErrorStore(ErrorStore):
    """Capacity-bounded FIFO store: when full, the OLDEST entries are evicted
    (the newest failure is the one an operator most wants to see) and counted
    in `dropped`."""

    def __init__(self, capacity: int = 10_000):
        if capacity <= 0:
            raise ValueError("error store capacity must be positive")
        self.capacity = int(capacity)
        self.dropped = 0
        self._entries: dict[int, ErroneousEvent] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def store(self, entry: ErroneousEvent) -> None:
        with self._lock:
            if entry.id == 0:
                entry.id = next(self._ids)
            if entry.stored_at_ms == 0:
                entry.stored_at_ms = int(time.time() * 1000)
            self._entries[entry.id] = entry
            while len(self._entries) > self.capacity:
                self._entries.pop(next(iter(self._entries)))
                self.dropped += 1

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        with self._lock:
            out = [
                e
                for e in self._entries.values()
                if (app_name is None or e.app_name == app_name)
                and (stream_id is None or e.stream_id == stream_id)
                and (origin is None or e.origin == origin)
            ]
        return out[:limit] if limit is not None else out

    def purge(self, ids: Optional[list[int]] = None) -> int:
        with self._lock:
            if ids is None:
                n = len(self._entries)
                self._entries.clear()
                return n
            n = 0
            for i in ids:
                if self._entries.pop(i, None) is not None:
                    n += 1
            return n

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def describe_state(self) -> dict:
        with self._lock:
            by_app: dict[str, int] = {}
            for e in self._entries.values():
                by_app[e.app_name] = by_app.get(e.app_name, 0) + 1
            return {
                "depth": len(self._entries),
                "capacity": self.capacity,
                "dropped": self.dropped,
                "by_app": by_app,
            }


class FileErrorStore(ErrorStore):
    """File-backed persistent store: one JSONL file per app under
    `base_path` (layout mirrors `persistence.FileSystemPersistenceStore`'s
    directory-per-concern shape), so error entries — including their
    flight-recorder dumps — survive restart.

    Serialization is plain JSON: `events`/`flight` row tuples become lists
    on disk and are re-tupled on load (replay re-encodes them through the
    input handler either way); the exception object itself (`cause`) does
    not survive — its rendered `error` string does. Non-JSON payloads are
    stringified rather than lost.
    """

    def __init__(self, base_path: str, capacity: int = 100_000):
        import os

        if capacity <= 0:
            raise ValueError("error store capacity must be positive")
        self.base_path = base_path
        self.capacity = int(capacity)
        self.dropped = 0
        self._lock = threading.Lock()
        os.makedirs(base_path, exist_ok=True)
        # ids must stay unique across restarts: resume after the max on disk
        # (the same scan seeds the entry count so the capacity check is O(1)
        # per store instead of re-reading the directory)
        best = 0
        n = 0
        for e in self._iter_entries():
            best = max(best, e.id)
            n += 1
        self._ids = itertools.count(best + 1)
        self._count = n

    def _files(self) -> list[str]:
        import os

        return sorted(
            os.path.join(self.base_path, f)
            for f in os.listdir(self.base_path)
            if f.endswith(".jsonl")
        )

    def _app_file(self, app_name: str) -> str:
        import os

        # app names come from @app:name — keep the file name filesystem-safe
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in app_name
        )
        return os.path.join(self.base_path, f"{safe}.jsonl")

    @staticmethod
    def _to_json(entry: ErroneousEvent) -> dict:
        # built by hand, NOT dataclasses.asdict: asdict deep-copies every
        # field first, and deep-copying the live exception in `cause` fails
        # for exception classes with non-default __init__ signatures —
        # raising from inside the very store() call that was capturing the
        # failure. `error` already carries the rendered message.
        d = {
            "id": entry.id,
            "stored_at_ms": entry.stored_at_ms,
            "app_name": entry.app_name,
            "origin": entry.origin,
            "stream_id": entry.stream_id,
            "error": entry.error,
            "events": entry.events,
            "payload": entry.payload,
            "sink_ref": entry.sink_ref,
            "flight": entry.flight,
        }
        try:
            import json

            json.dumps(d.get("payload"))
        except (TypeError, ValueError):
            d["payload"] = repr(d.get("payload"))
        return d

    @staticmethod
    def _from_json(d: dict) -> ErroneousEvent:
        for key in ("events", "flight"):
            if d.get(key) is not None:
                d[key] = [(int(ts), tuple(row)) for ts, row in d[key]]
        return ErroneousEvent(cause=None, **d)

    def _iter_entries(self):
        import json

        for path in self._files():
            try:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            yield self._from_json(json.loads(line))
                        except Exception:
                            continue  # a torn tail line must not kill load()
            except OSError:
                continue

    def store(self, entry: ErroneousEvent) -> None:
        import json

        with self._lock:
            if entry.id == 0:
                entry.id = next(self._ids)
            if entry.stored_at_ms == 0:
                entry.stored_at_ms = int(time.time() * 1000)
            with open(self._app_file(entry.app_name), "a", encoding="utf-8") as f:
                f.write(json.dumps(self._to_json(entry), default=str) + "\n")
            self._count += 1
            if self._count > self.capacity:
                self._enforce_capacity()

    def _enforce_capacity(self) -> None:
        """FIFO eviction across the whole directory (oldest ids first),
        same policy as the in-memory store. Caller holds the lock; only
        invoked when the running count exceeds capacity. Evicts down to 90%
        of capacity, not to capacity exactly — the eviction pass re-parses
        the directory (O(capacity)), and dropping with slack amortizes that
        over the next capacity/10 stores instead of paying it on every
        store() once the directory is full."""
        entries = sorted(self._iter_entries(), key=lambda e: e.id)
        self._count = len(entries)  # re-sync (torn lines are not counted)
        if len(entries) <= self.capacity:
            return
        target = max(1, (self.capacity * 9) // 10)
        evict = {e.id for e in entries[: len(entries) - target]}
        # count only what was ACTUALLY removed (a momentarily unreadable
        # app file skips its rewrite): dropped must reconcile with disk
        removed = self._rewrite_without(evict)
        self.dropped += removed
        self._count -= removed

    def _rewrite_without(self, ids: set) -> int:
        """Rewrite every app file dropping `ids`; returns how many entries
        were removed. Caller holds the lock."""
        import json
        import os

        removed = 0
        for path in self._files():
            keep: list[str] = []
            changed = False
            try:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            eid = json.loads(line).get("id")
                        except Exception:
                            changed = True  # drop torn lines on rewrite
                            continue
                        if eid in ids:
                            removed += 1
                            changed = True
                        else:
                            keep.append(line)
            except OSError:
                continue
            if not changed:
                continue
            if keep:
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write("\n".join(keep) + "\n")
                os.replace(tmp, path)
            else:
                os.unlink(path)
        return removed

    def load(
        self,
        app_name: Optional[str] = None,
        stream_id: Optional[str] = None,
        origin: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> list[ErroneousEvent]:
        with self._lock:
            out = [
                e
                for e in self._iter_entries()
                if (app_name is None or e.app_name == app_name)
                and (stream_id is None or e.stream_id == stream_id)
                and (origin is None or e.origin == origin)
            ]
        out.sort(key=lambda e: e.id)
        return out[:limit] if limit is not None else out

    def purge(self, ids: Optional[list[int]] = None) -> int:
        import os

        with self._lock:
            if ids is None:
                n = sum(1 for _ in self._iter_entries())
                for path in self._files():
                    os.unlink(path)
                self._count = 0
                return n
            removed = self._rewrite_without(set(ids))
            self._count = max(0, self._count - removed)
            return removed

    def size(self) -> int:
        """O(1): the running count (seeded by the init scan, adjusted by
        store/purge/eviction) — selfmon polls this every tick, and a
        directory re-parse per poll would stall the scheduler thread."""
        with self._lock:
            return self._count

    def describe_state(self) -> dict:
        """The per-app breakdown does read the directory — describe_state
        is an on-demand introspection pull, not a periodic poll."""
        with self._lock:
            by_app: dict[str, int] = {}
            for e in self._iter_entries():
                by_app[e.app_name] = by_app.get(e.app_name, 0) + 1
            return {
                "depth": self._count,
                "capacity": self.capacity,
                "dropped": self.dropped,
                "by_app": by_app,
                "path": self.base_path,
            }


def make_entry(
    app_name: str,
    origin: str,
    stream_id: str,
    error: BaseException | str,
    events: Optional[list[tuple[int, tuple]]] = None,
    payload: Any = None,
    sink_ref: str = "",
) -> ErroneousEvent:
    exc = error if isinstance(error, BaseException) else None
    if exc is not None:
        # drop the frame chains (including chained __cause__/__context__
        # exceptions): a retained traceback pins every frame's locals
        # (decoded events, device batches) for the life of the store
        seen: set[int] = set()
        e: Optional[BaseException] = exc
        while e is not None and id(e) not in seen:
            seen.add(id(e))
            e.__traceback__ = None
            e = e.__cause__ or e.__context__
    return ErroneousEvent(
        id=0,
        stored_at_ms=0,
        app_name=app_name,
        origin=origin,
        stream_id=stream_id,
        error=f"{type(error).__name__}: {error}" if exc is not None else str(error),
        events=events,
        payload=payload,
        cause=exc,
        sink_ref=sink_ref,
    )
