"""SiddhiAppRuntime: app assembly and lifecycle.

Reference: core/SiddhiAppRuntime.java:88-696 + util/parser/SiddhiAppParser.java —
holds junction/query/table/window/aggregation maps, wires receivers into
junctions, start/shutdown ordering, callback registration, store-query API.
Here "parse" is compile: each query becomes a jitted device program; junctions
are host fan-out points between compiled programs.
"""

from __future__ import annotations

import threading
from typing import Callable, Union

import jax
import jax.numpy as jnp

from siddhi_tpu.core.errors import DefinitionNotExistError, SiddhiAppCreationError
from siddhi_tpu.core.event import (
    Event,
    EventBatch,
    KIND_CURRENT,
    KIND_EXPIRED,
    StreamSchema,
)
from siddhi_tpu.core.query_runtime import QueryRuntime
from siddhi_tpu.core.stream_junction import (
    InputHandler,
    StreamJunction,
    system_clock_ms,
)
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.execution import (
    InsertIntoStream,
    JoinInputStream,
    OutputEventsFor,
    Query,
    SingleInputStream,
    StateInputStream,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

DEFAULT_BATCH = 64


class SiddhiAppRuntime:
    def __init__(self, app: SiddhiApp, manager) -> None:
        self.app = app
        self.manager = manager
        self.interner = manager.interner
        self.name = app.name
        self.clock = system_clock_ms
        self._running = False
        self._lock = threading.RLock()
        self._debugger = None

        # @app:playback(idle.time, increment): event-time clock + scheduler
        # (reference: SiddhiAppParser.java:166-212)
        self._playback_clock = None
        pb = find_annotation(app.annotations, "app:playback")
        if pb is not None:
            from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
            from siddhi_tpu.core.timestamp import EventTimeClock, EventTimeScheduler

            idle = pb.element("idle.time")
            inc = pb.element("increment")
            self._playback_clock = EventTimeClock(
                idle_ms=SiddhiCompiler.parse_time_constant(idle) if idle else None,
                increment_ms=SiddhiCompiler.parse_time_constant(inc) if inc else None,
            )
            self.clock = self._playback_clock.now
            self._scheduler = EventTimeScheduler(self._playback_clock)
        else:
            from siddhi_tpu.core.scheduler import SystemTimeScheduler

            self._scheduler = SystemTimeScheduler()

        # @app:watermark(bound, idle.timeout, late.policy, allowed.lateness):
        # event-time robustness — bounded-disorder reordering at every
        # ingress, per-stream watermarks with min-propagation, and late-event
        # policies (core/watermark.py; SIDDHI_TPU_WATERMARK overrides).
        # Without playback, the watermark clock takes over timekeeping so
        # window flushes / pattern deadlines / bucket closes fire on
        # watermark ADVANCE, never on raw (possibly disordered) arrival.
        self._watermark = None
        from siddhi_tpu.core.watermark import resolve_watermark_annotation

        wm_cfg = resolve_watermark_annotation(
            find_annotation(app.annotations, "app:watermark")
        )
        if wm_cfg is not None:
            from siddhi_tpu.core.timestamp import (
                EventTimeClock,
                EventTimeScheduler,
            )
            from siddhi_tpu.core.watermark import WatermarkRuntime

            if self._playback_clock is not None:
                wm_clock = self._playback_clock
            else:
                wm_clock = EventTimeClock()
                self.clock = wm_clock.now
                self._scheduler = EventTimeScheduler(wm_clock)
            self._watermark = WatermarkRuntime(self, wm_cfg, wm_clock)

        # @app:statistics(reporter='console'|'log'|'jsonl'|'prometheus'|'none',
        #                 interval='N', trace.sample='P', trace.seed='S',
        #                 trace.capacity='K', file='...', port='...')
        # (reference: SiddhiAppParser.java:106-142; tracing/exposition are
        # this engine's additions — see siddhi_tpu/observability/)
        self.statistics_manager = None
        self.tracer = None
        st = find_annotation(app.annotations, "app:statistics")
        if st is not None:
            from siddhi_tpu.core.statistics import StatisticsManager

            opts = {k: v for k, v in st.elements if k is not None}
            sample = opts.get("trace.sample")
            if sample is not None:
                from siddhi_tpu.observability.tracing import Tracer

                try:
                    self.tracer = Tracer(
                        float(sample),
                        capacity=int(opts.get("trace.capacity", "256")),
                        seed=(
                            int(opts["trace.seed"])
                            if "trace.seed" in opts
                            else None
                        ),
                    )
                except ValueError as e:
                    raise SiddhiAppCreationError(
                        f"@app:statistics trace options: {e}"
                    ) from e
            try:
                self.statistics_manager = StatisticsManager(
                    self.name,
                    reporter=st.element("reporter", "console"),
                    interval_s=float(st.element("interval", "60")),
                    options=opts,
                    tracer=self.tracer,
                )
            except ValueError as e:
                raise SiddhiAppCreationError(
                    f"@app:statistics options: {e}"
                ) from e
            if str(self.statistics_manager.reporter).lower() == "prometheus":
                try:
                    int(opts.get("port", "9464"))
                except ValueError as e:
                    raise SiddhiAppCreationError(
                        f"@app:statistics(reporter='prometheus'): invalid "
                        f"port '{opts.get('port')}'"
                    ) from e

        self.stream_schemas: dict[str, StreamSchema] = {}
        self.junctions: dict[str, StreamJunction] = {}
        self.queries: dict[str, QueryRuntime] = {}

        batch_ann = find_annotation(app.annotations, "app:batch")
        self.batch_size = int(batch_ann.element("size", str(DEFAULT_BATCH))) if batch_ann else DEFAULT_BATCH
        self.group_capacity = self._capacity_annotation("app:groupCapacity", None)
        # whole-graph fusion escape hatch: @app:fuse(disable='true') /
        # SIDDHI_TPU_FUSE=1|0 (core/fusion_exec.py; malformed options raise
        # here — the runtime analog of the analyzer's SA125)
        from siddhi_tpu.core.fusion_exec import resolve_fuse_annotation

        self._fuse_enabled = resolve_fuse_annotation(
            find_annotation(app.annotations, "app:fuse")
        )
        # compact wire encodings: @app:wire(disable='true',
        # range/dict/delta.<stream>.<col>=...) / SIDDHI_TPU_WIRE=1|0
        # (core/wire.py; malformed options raise here — the runtime analog
        # of the analyzer's SA132). The per-stream WireSpecs are built when
        # the fused engines form (_build_fused_ingest).
        from siddhi_tpu.core.wire import resolve_wire_annotation

        self._wire_enabled, self._wire_hints = resolve_wire_annotation(
            find_annotation(app.annotations, "app:wire")
        )
        # event lineage & provenance: @app:lineage(capacity='N',
        # mode='full|sample') (observability/lineage.py; malformed options
        # raise here — the runtime analog of the analyzer's SA131).
        # Resolved BEFORE any junction/query construction: arenas arm in
        # _junction() and recorders in _add_query*, all ahead of the first
        # trace so the `__lin.*` lane structure is part of every program.
        from siddhi_tpu.observability.lineage import (
            LineageLedger,
            resolve_lineage_annotation,
        )

        self._lineage_cfg = resolve_lineage_annotation(
            find_annotation(app.annotations, "app:lineage")
        )
        self.lineage_ledger = (
            LineageLedger(self, self._lineage_cfg)
            if self._lineage_cfg is not None
            else None
        )
        # black-box incident recorder: @app:blackbox(window, triggers,
        # keep, ...) (observability/blackbox.py; malformed options raise
        # here — the runtime analog of the analyzer's SA140). Resolved
        # BEFORE any junction construction so _junction() arms a seq-lane
        # ring on every junction, the lineage precedent.
        from siddhi_tpu.observability.blackbox import (
            BlackboxRecorder,
            resolve_blackbox_annotation,
        )

        self._blackbox_cfg = resolve_blackbox_annotation(
            find_annotation(app.annotations, "app:blackbox")
        )
        self._blackbox = (
            BlackboxRecorder(self, self._blackbox_cfg)
            if self._blackbox_cfg is not None
            else None
        )
        # first-class sharded execution: @app:shard(devices='N', axis=...)
        # / SIDDHI_TPU_SHARD (parallel/shard.py; malformed options raise
        # here — the runtime analog of the analyzer's SA129). Resolved now,
        # applied at start() once the fused engines exist.
        from siddhi_tpu.parallel.shard import resolve_shard_annotation

        self._shard_conf = resolve_shard_annotation(
            find_annotation(app.annotations, "app:shard")
        )
        self._shard = None  # ShardRuntime, built at start()
        # one app-level processing lock: receive+route for every query runs
        # under it, so cyclic stream topologies cannot lock-order deadlock and
        # timer/input threads deliver outputs in state-step order (analog of
        # the reference's synchronous junction dispatch + ThreadBarrier)
        self._process_lock = threading.RLock()

        # supervised runtime (core/supervision.py, core/admission.py):
        # @app:persist auto-checkpoint, @app:restart policy (validated here,
        # consumed by manager.supervise()), @app:admission ingress gate.
        # All three raise at creation on malformed options — the runtime
        # analogs of SA126/SA127/SA128.
        from siddhi_tpu.core.admission import (
            AdmissionController,
            resolve_admission_annotation,
        )
        from siddhi_tpu.core.supervision import (
            AutoPersist,
            resolve_persist_annotation,
            resolve_restart_annotation,
        )

        self._autopersist = None
        pa = find_annotation(app.annotations, "app:persist")
        if pa is not None:
            interval_ms, keep = resolve_persist_annotation(pa)
            self._autopersist = AutoPersist(self, interval_ms, keep)
        ra = find_annotation(app.annotations, "app:restart")
        if ra is not None:
            resolve_restart_annotation(ra)  # fail fast; supervisor re-reads
        self._admission = None
        aa = find_annotation(app.annotations, "app:admission")
        if aa is not None:
            self._admission = AdmissionController(
                self.name, resolve_admission_annotation(aa)
            )
            if self._blackbox is not None:  # shed spikes freeze incidents
                self._admission.on_incident = self._blackbox.fire
        # supervision health hook (core/supervision.AppHealth), installed by
        # Supervisor.attach(); _junction() wires it onto every junction
        self._health = None
        # callbacks retained for supervised rebuild: a restart re-creates
        # every junction/runtime, so user callbacks must be re-registered
        self._user_callbacks: list[tuple[str, Callable]] = []
        # hot-deploy wiring staging (core/churn.add_query): while set (a
        # list), _wire_subscribe/_wire_fuse_candidate APPEND deferred
        # actions instead of touching the live junctions, so the whole
        # query builds off-line and the splice applies them atomically
        # under the process lock
        self._staged_wiring = None

        # @OnError(action='LOG'|'STREAM'|'STORE') failure policies
        # (reference: StreamJunction OnErrorAction + util/error/handler/*);
        # STREAM auto-defines the fault stream `!S` = S's attributes + _error
        from siddhi_tpu.core.types import AttrType as _AttrType

        self.on_error_actions: dict[str, str] = {}
        for sid, d in app.stream_definitions.items():
            oe = find_annotation(d.annotations, "OnError")
            if oe is None:
                continue
            action = (oe.element("action") or oe.element(None) or "LOG").upper()
            if action not in ("LOG", "STREAM", "STORE"):
                raise SiddhiAppCreationError(
                    f"stream '{sid}': unknown @OnError action '{action}' "
                    "(expected LOG, STREAM, or STORE)"
                )
            self.on_error_actions[sid] = action
            if action == "STREAM":
                if any(a.name == "_error" for a in d.attributes):
                    raise SiddhiAppCreationError(
                        f"stream '{sid}': @OnError(action='STREAM') reserves "
                        "the attribute name '_error'"
                    )
                fid = "!" + sid
                self.stream_schemas[fid] = StreamSchema(
                    fid,
                    [(a.name, a.type) for a in d.attributes]
                    + [("_error", _AttrType.STRING)],
                )

        # @app:watermark late.policy='stream'|'apply' diverts late/correction
        # rows onto each stream's `!S` side stream — auto-define the schemas
        # through the same @OnError STREAM machinery (skipping streams that
        # already carry one)
        if self._watermark is not None and self._watermark.cfg.late_policy in (
            "stream", "apply",
        ):
            for sid, d in app.stream_definitions.items():
                fid = "!" + sid
                if fid in self.stream_schemas:
                    continue
                if any(a.name == "_error" for a in d.attributes):
                    raise SiddhiAppCreationError(
                        f"stream '{sid}': @app:watermark late.policy="
                        f"'{self._watermark.cfg.late_policy}' reserves the "
                        "attribute name '_error'"
                    )
                self.stream_schemas[fid] = StreamSchema(
                    fid,
                    [(a.name, a.type) for a in d.attributes]
                    + [("_error", _AttrType.STRING)],
                )

        # @pipeline(depth='N', disable='true') — per-stream config of the
        # double-buffered fused-ingest pipeline (core/pipeline.py); resolved
        # here (with the SIDDHI_TPU_PIPELINE env override) and applied when
        # start() builds the junction's FusedJunctionIngest
        from siddhi_tpu.core.pipeline import resolve_pipeline_annotation
        from siddhi_tpu.observability.flight import resolve_flight_annotation

        self._pipeline_conf: dict[str, tuple[bool, int]] = {}
        for sid, d in app.stream_definitions.items():
            self.stream_schemas[sid] = StreamSchema(
                sid, [(a.name, a.type) for a in d.attributes]
            )
            try:
                self._pipeline_conf[sid] = resolve_pipeline_annotation(
                    find_annotation(d.annotations, "pipeline")
                )
                # @flightRecorder(size='N') — bounded last-N-events ring on
                # this stream's junction (observability/flight.py; the
                # SIDDHI_TPU_FLIGHT env override is folded in by the
                # resolver, and _junction() applies it to internal
                # junctions too)
                flight_size = resolve_flight_annotation(
                    find_annotation(d.annotations, "flightRecorder")
                )
                if flight_size:
                    self._junction(sid).enable_flight(flight_size)
            except SiddhiAppCreationError as e:
                raise SiddhiAppCreationError(f"stream '{sid}': {e}") from e
            # @async(buffer.size, workers, batch.size.max) — buffered ingress
            # ring with worker batching (reference: StreamJunction.java:87-117)
            a = find_annotation(d.annotations, "async")
            if a is not None:
                j = self._junction(sid)
                j.enable_async(
                    buffer_size=int(a.element("buffer.size", "1024")),
                    workers=int(a.element("workers", "1")),
                    batch_max=int(a.element("batch.size.max", "0")) or None,
                )
            if self.statistics_manager is not None:
                sm = self.statistics_manager
                j = self._junction(sid)
                j.on_publish_stats = sm.throughput_tracker(f"stream.{sid}").add
                sm.buffered_tracker(f"stream.{sid}").register(j.queued)
                j.on_error_stats = sm.error_tracker(f"stream.{sid}").add
                # per-subscriber error attribution: failures are ALSO counted
                # under `stream.<id>.subscriber.<name>` (Prometheus exposes
                # the pair as component/subscriber labels)
                j.error_stats_factory = (
                    lambda sub, _sid=sid: sm.error_tracker(
                        f"stream.{_sid}", subscriber=sub
                    ).add
                )
                # live device budget for this junction's fused dispatch path
                j.device_stats = sm.junction_device_stats(f"stream.{sid}")
                # pipelined-ingest stage budget + occupancy overlap gauge
                j.pipeline_stats = sm.pipeline_stats(f"stream.{sid}")
                # continuous profiler: chunk waterfalls + compile telemetry
                # for the fused chunk program (observability/profiler.py)
                j.profiler = sm.profiler
                j.compile_telemetry = sm.compile_telemetry

        # @app:selfmon(interval='5 sec'): CEP-native self-monitoring — inject
        # the SelfMonitorStream system schema (runtime-side only: the user's
        # AST is not mutated; the analyzer injects the same definition from
        # the annotation, analysis/symbols.py) and build the scheduler-fed
        # monitor armed at start() (observability/selfmon.py)
        self._selfmon = None
        sm_ann = find_annotation(app.annotations, "app:selfmon")
        if sm_ann is not None:
            from siddhi_tpu.observability.selfmon import (
                SELFMON_STREAM_ID,
                SelfMonitor,
                resolve_selfmon_annotation,
            )

            interval_ms = resolve_selfmon_annotation(
                sm_ann, defined_streams=app.stream_definitions
            )
            from siddhi_tpu.observability.selfmon import selfmon_attrs

            self.stream_schemas[SELFMON_STREAM_ID] = StreamSchema(
                SELFMON_STREAM_ID, selfmon_attrs()
            )
            self._selfmon = SelfMonitor(self, interval_ms)

        # @app:slo(p99.latency.ms=..., ...): SLO burn-rate engine — inject
        # the SloAlertStream system schema (same runtime-side-only contract
        # as selfmon) and build the scheduler-fed evaluator armed at
        # start() (observability/slo.py)
        self._slo = None
        slo_ann = find_annotation(app.annotations, "app:slo")
        if slo_ann is not None:
            from siddhi_tpu.observability.slo import (
                SLO_STREAM_ID,
                SloEngine,
                resolve_slo_annotation,
                slo_attrs,
            )

            slo_cfg = resolve_slo_annotation(
                slo_ann, defined_streams=app.stream_definitions
            )
            self.stream_schemas[SLO_STREAM_ID] = StreamSchema(
                SLO_STREAM_ID, slo_attrs()
            )
            self._slo = SloEngine(self, slo_cfg)
            if self.statistics_manager is not None:
                self.statistics_manager.register_slo(
                    self._slo.prometheus_section
                )

        # plan-vs-actual calibration ledger: pairs static predictions with
        # live meters (observability/calibration.py). Gated on
        # @app:statistics — without it no ledger exists and every hot-path
        # touchpoint is one `is None` check (the zero-overhead contract)
        self._calibration = None
        if self.statistics_manager is not None:
            from siddhi_tpu.observability.calibration import (
                CalibrationLedger,
            )

            self._calibration = CalibrationLedger(self)
            self.statistics_manager.register_calibration(
                self._calibration.prometheus_section
            )

        for sid, action in self.on_error_actions.items():
            j = self._junction(sid)
            j.fault_policy = action
            j.app_name = self.name
            if action == "STREAM":
                j.fault_junction = self._junction("!" + sid)
            elif action == "STORE":
                j.error_store_fn = lambda: self.manager.error_store

        # `define function f[python] ...` scripts register into the global
        # function registry (reference: script executors via @Extension SPI;
        # the registry is manager-global, so same-name redefinitions win last)
        from siddhi_tpu.core.extension import extension as _ext
        from siddhi_tpu.core.stream_function import make_script_function

        for fid, fdef in app.function_definitions.items():
            _ext("function", fid)(make_script_function(fdef))

        from siddhi_tpu.core.table import DEFAULT_TABLE_CAPACITY, InMemoryTable

        table_capacity = self._capacity_annotation(
            "app:tableCapacity", DEFAULT_TABLE_CAPACITY
        )
        self.tables: dict[str, InMemoryTable] = {
            tid: InMemoryTable(d, self.interner, capacity=table_capacity)
            for tid, d in app.table_definitions.items()
        }
        self._store_query_cache: dict[str, object] = {}

        # @OnError on table definitions: mutation failures (the mutating
        # query's dispatch + record-store flushes) route to the error store
        # or the log instead of propagating to the sender. STREAM is
        # stream/window-only: the failing unit is the mutating query's
        # input batch, which does not carry the table's schema, so there is
        # no well-typed '!T' row to publish (analyzer analog: SA110).
        from siddhi_tpu.core.error_store import (
            iter_definition_onerror_problems,
            resolve_definition_onerror_action,
        )

        self._table_fault: dict[str, str] = {}
        for tid, td in app.table_definitions.items():
            oe = find_annotation(td.annotations, "OnError")
            if oe is None:
                continue
            for _tag, msg in iter_definition_onerror_problems(
                oe, "table", tid
            ):
                raise SiddhiAppCreationError(msg)
            action = resolve_definition_onerror_action(oe)
            self._table_fault[tid] = action
            t = self.tables[tid]
            t.fault_policy = action
            t.app_name = self.name
            if action == "STORE":
                t.error_store_fn = lambda: self.manager.error_store

        # named windows: input junction under the window id, processing runtime
        # in between, output junction feeding `from W` queries
        from siddhi_tpu.core.window_runtime import NamedWindow

        self.named_windows: dict[str, NamedWindow] = {}
        for wid, wd in app.window_definitions.items():
            nw = NamedWindow(wd, self.interner)
            self.named_windows[wid] = nw
            in_j = StreamJunction(nw.schema, self.interner, self.batch_size)
            in_j.tracer = self.tracer
            self.junctions[wid] = in_j
            nw.out_junction = StreamJunction(
                nw.schema, self.interner, self.batch_size
            )
            nw.out_junction.tracer = self.tracer
            wlt = (
                self.statistics_manager.latency_tracker(f"window.{wid}")
                if self.statistics_manager is not None
                else None
            )

            def receive(batch: EventBatch, now: int, _nw=nw, _lt=wlt) -> None:
                # mark_out in finally: a poison batch caught by the junction's
                # failure policy must not leak an open mark on the TLS stack
                if _lt is not None:
                    _lt.mark_in()
                try:
                    with self._process_lock:
                        out, aux = _nw.receive(batch, now)
                        _nw.out_junction.publish_batch(out, now)
                finally:
                    if _lt is not None:
                        _lt.mark_out()
                if _nw.needs_scheduler:
                    if _nw.host_next_timer is not None:
                        self._scheduler.start()
                        self._scheduler.notify_at(
                            _nw.host_next_timer(self.clock()), _nw.timer_target
                        )
                    else:
                        self._schedule_at(aux, _nw.timer_target)

            in_j.subscribe(receive, name=f"window.{wid}")
            if nw.needs_scheduler:
                def fire(t_ms: int, _nw=nw, _recv=receive) -> None:
                    _recv(self._timer_batch(_nw.schema, t_ms), t_ms)

                nw.timer_target = fire

        # @OnError on named windows: mutation failures (the shared window
        # processor exploding on an inserted batch) ride the SAME junction
        # failure machinery streams use — the window's input junction
        # carries the window's schema, so STREAM routes to a well-typed
        # fault stream '!W' (attributes + _error)
        for wid, wd in app.window_definitions.items():
            oe = find_annotation(wd.annotations, "OnError")
            if oe is None:
                continue
            for _tag, msg in iter_definition_onerror_problems(
                oe, "window", wid, [a.name for a in wd.attributes]
            ):
                raise SiddhiAppCreationError(msg)
            action = resolve_definition_onerror_action(oe)
            j = self.junctions[wid]
            j.fault_policy = action
            j.app_name = self.name
            if action == "STREAM":
                fid = "!" + wid
                self.stream_schemas[fid] = StreamSchema(
                    fid,
                    [(a.name, a.type) for a in wd.attributes]
                    + [("_error", _AttrType.STRING)],
                )
                j.fault_junction = self._junction(fid)
            elif action == "STORE":
                j.error_store_fn = lambda: self.manager.error_store

        # incremental aggregations: duration tables are registered app tables
        # (reference: AggregationParser.java:701-708 table map registration)
        from siddhi_tpu.core.aggregation import AggregationRuntime

        agg_groups = self._capacity_annotation("app:aggGroupCapacity", 64)
        self.aggregations: dict[str, AggregationRuntime] = {}
        self._agg_inputs: dict[str, str] = {}
        for aid, ad in app.aggregation_definitions.items():
            in_sid = ad.basic_single_input_stream.stream_id
            self._agg_inputs[aid] = in_sid
            in_schema = self.stream_schemas.get(in_sid)
            if in_schema is None:
                raise DefinitionNotExistError(
                    f"aggregation '{aid}': stream '{in_sid}' is not defined"
                )
            ar = AggregationRuntime(
                ad, in_schema, self.interner, group_capacity=agg_groups
            )
            if self._lineage_cfg is not None:
                ar.arm_lineage(self._lineage_cfg)
            self.aggregations[aid] = ar
            for t in ar.tables.values():
                self.tables[t.table_id] = t

            alt = (
                self.statistics_manager.latency_tracker(f"aggregation.{aid}")
                if self.statistics_manager is not None
                else None
            )

            def agg_receive(batch: EventBatch, now: int, _ar=ar, _lt=alt) -> None:
                if _lt is not None:
                    _lt.mark_in()
                try:
                    with self._process_lock:
                        aux = _ar.receive(batch, now)
                finally:
                    if _lt is not None:
                        _lt.mark_out()
                if "next_timer" in aux:
                    self._schedule_at(aux, _ar.timer_target)

            self._junction(in_sid).subscribe(
                agg_receive, name=f"aggregation.{aid}"
            )

            def agg_fire(t_ms: int, _ar=ar, _schema=in_schema, _recv=agg_receive) -> None:
                _recv(self._timer_batch(_schema, t_ms), t_ms)

            ar.timer_target = agg_fire

        # triggers: each defines a stream <id>(triggered_time long)
        from siddhi_tpu.core.trigger import TriggerRuntime
        from siddhi_tpu.core.types import AttrType

        self.triggers: dict[str, TriggerRuntime] = {}
        for tid, td in app.trigger_definitions.items():
            schema = StreamSchema(tid, [("triggered_time", AttrType.LONG)])
            self.stream_schemas[tid] = schema
            self.triggers[tid] = TriggerRuntime(
                td, self._junction(tid), self._scheduler, lambda: self.clock()
            )

        # @source/@sink transports on stream definitions
        # (reference: DefinitionParserHelper.addEventSource/Sink :302,419)
        from siddhi_tpu.core.io import (
            build_sink,
            build_source,
            wire_sink_error_handling,
            wire_source_error_handling,
        )
        from siddhi_tpu.query_api.annotation import find_all

        self.sources: list = []
        self.sinks: list = []
        for sid, d in app.stream_definitions.items():
            schema = self.stream_schemas[sid]
            for ann in find_all(d.annotations, "source"):
                # transport payloads carry no timestamps: sourced events are
                # stamped with the app clock (wall time, or the current
                # virtual time in @app:playback apps)
                src = build_source(
                    ann, sid, schema, self.get_input_handler(sid)
                )
                fault_sender = None
                if self.on_error_actions.get(sid) == "STREAM":
                    fj = self._junction("!" + sid)

                    def fault_sender(rows, err, _fj=fj):
                        now = self.clock()
                        _fj.send_rows(
                            [now] * len(rows),
                            [tuple(r) + (err,) for r in rows],
                            now=now,
                        )

                sm = self.statistics_manager
                wire_source_error_handling(
                    src,
                    lambda: self.manager.error_store,
                    self.name,
                    fault_sender,
                    sm.error_tracker(f"source.{sid}").add
                    if sm is not None
                    else None,
                )
                self.sources.append(src)
            for n_sink, ann in enumerate(find_all(d.annotations, "sink")):
                sink = build_sink(ann, sid, schema)
                sm = self.statistics_manager
                wire_sink_error_handling(
                    sink,
                    lambda: self.manager.error_store,
                    self.name,
                    f"{sid}[{n_sink}]",
                    sm.error_tracker(f"sink.{sid}").add
                    if sm is not None
                    else None,
                    on_publish_stats=(
                        sm.throughput_tracker(f"sink.{sid}").add
                        if sm is not None
                        else None
                    ),
                    latency_tracker=(
                        sm.latency_tracker(f"sink.{sid}")
                        if sm is not None
                        else None
                    ),
                )
                self.sinks.append(sink)
                self._junction(sid).add_stream_callback(
                    lambda rows, _s=sink: _s.on_events(
                        [Event(t, data) for t, data in rows]
                    ),
                    name=f"sink.{sid}[{n_sink}]",
                )

        from siddhi_tpu.core.partition import PartitionRuntime
        from siddhi_tpu.query_api.execution import assign_execution_ids

        # query/partition ids come from the ONE shared assignment (auto-ids
        # must not collide with explicit @info names anywhere in the app;
        # the analyzer and the EXPLAIN plan builder use the same helper)
        self.partitions: list[PartitionRuntime] = []
        for ent in assign_execution_ids(app):
            if ent[0] == "query":
                _kind, qid, q = ent
                self._add_query(qid, q)
            else:
                _kind, pid, elem, inner_ids = ent
                self.partitions.append(
                    PartitionRuntime(elem, self, pid, query_ids=inner_ids)
                )

    # ---- assembly --------------------------------------------------------

    def _capacity_annotation(self, name: str, default):
        ann = find_annotation(self.app.annotations, name)
        if ann is None:
            return default
        v = ann.element("size") or ann.element(None)
        if v is None:
            raise SiddhiAppCreationError(
                f"@{name} needs a size, e.g. @{name}(size='4096')"
            )
        return int(v)

    def _junction(self, stream_id: str) -> StreamJunction:
        j = self.junctions.get(stream_id)
        if j is None:
            schema = self.stream_schemas.get(stream_id)
            if schema is None:
                raise DefinitionNotExistError(f"stream '{stream_id}' is not defined")
            j = StreamJunction(schema, self.interner, self.batch_size)
            j.exception_handler = getattr(self, "_exception_handler", None)
            j.tracer = self.tracer
            # snapshot barrier: the fan-out holds the app process lock so a
            # checkpoint can't capture a torn cross-query state mid-batch
            j.process_lock = self._process_lock
            # supervised apps: unguarded dispatch/worker failures signal the
            # manager's Supervisor through the app's health hook
            health = getattr(self, "_health", None)
            if health is not None:
                j.on_fatal = health.mark_fatal
            # SIDDHI_TPU_FLIGHT=N arms the flight recorder on EVERY junction
            # — internal insert-into targets and fault streams included
            # (explicit @flightRecorder sizes are applied after, and win
            # when larger; see the stream-definition loop)
            from siddhi_tpu.observability.flight import flight_env_size

            env_n = flight_env_size()
            if env_n:
                j.enable_flight(env_n)
            # @app:lineage arms a seq-stamping arena on EVERY junction —
            # internal insert-into targets and fault streams included, so
            # multi-hop resolution can walk any chain
            if self._lineage_cfg is not None:
                j.enable_lineage(self._lineage_cfg.capacity)
            # @app:blackbox arms a seq-lane incident ring on EVERY junction
            # — the incident bundle must carry every stream's last window
            if self._blackbox is not None:
                self._blackbox.arm(j)
            self.junctions[stream_id] = j
        return j

    def _wire_subscribe(self, junction, fn, name: str) -> None:
        """Subscribe `fn` to `junction` — or, during a hot-deploy build
        (core/churn.add_query), stage the subscription for the splice."""
        if self._staged_wiring is not None:
            self._staged_wiring.append(
                lambda _j=junction, _f=fn, _n=name: _j.subscribe(_f, name=_n)
            )
        else:
            junction.subscribe(fn, name=name)

    def _wire_fuse_candidate(self, junction, ep) -> None:
        """Register a FuseEndpoint on `junction` — staged during a
        hot-deploy build, exactly like _wire_subscribe."""
        devices, axis = self._shard_conf
        if devices >= 2 and axis == "keys":
            # keyed-sharded state (parallel/keyshard.py) steps under its
            # own shard_map program: a fused chunk body would bypass it.
            # Runtime analog of the planner's H_KEYSHARD blocker.
            from siddhi_tpu.parallel.keyshard import keyed_shardable

            ok, _why = keyed_shardable(ep.qr)
            if ok:
                return
        if self._staged_wiring is not None:
            self._staged_wiring.append(
                lambda _j=junction, _e=ep: _j.fuse_candidates.append(_e)
            )
        else:
            junction.fuse_candidates.append(ep)

    def _wire_insert(self, qr) -> None:
        """Route a query's output batches into its insert-into junction
        (reference: SiddhiAppRuntimeBuilder.addQuery:170-231 output wiring)."""
        out = qr.query.output_stream
        if not isinstance(out, InsertIntoStream):
            return
        target = out.target
        if out.is_fault and target not in self.stream_schemas:
            raise SiddhiAppCreationError(
                f"insert into '{target}': fault streams exist only for "
                f"streams declaring @OnError(action='STREAM') — add it to "
                f"'{target[1:]}'"
            )
        if target in self.tables:
            return  # table writes are compiled into the query step
        existing = self.stream_schemas.get(target)
        if existing is None and target in self.named_windows:
            existing = self.named_windows[target].schema
        inferred = qr.out_schema
        if existing is None:
            self.stream_schemas[target] = inferred
            existing = inferred
        elif [t for _, t in existing.attrs] != [t for _, t in inferred.attrs]:
            raise SiddhiAppCreationError(
                f"insert into '{target}': selector output {inferred.attrs} "
                f"does not match defined stream {existing.attrs}"
            )
        target_junction = self._junction(target)
        transform = _make_insert_transform(out.output_events)
        rename = _make_rename(inferred, existing)

        def publish(
            out_batch: EventBatch, now: int, _t=target_junction, _qr=qr
        ) -> None:
            if (
                not _t.subscribers
                and not _t.stream_callbacks
                and _t.on_publish_stats is None
                and _t.flight is None
                and _t.lineage is None
            ):
                return  # nobody downstream: skip the transform dispatch
            lin = getattr(_qr, "lineage", None)
            if lin is not None and _t.lineage is not None:
                # per-publish producer capture (observability/lineage.py):
                # the arena notes WHICH recorded query stamped this seq
                # range, so multi-producer streams resolve each record to
                # its actual producer instead of listing candidates
                from siddhi_tpu.observability.lineage import (
                    publisher_context,
                )

                with publisher_context(_qr.query_id, lin):
                    _t.publish_batch(rename(transform(out_batch)), now)
                return
            _t.publish_batch(rename(transform(out_batch)), now)

        qr.publish_fn = publish
        # fused-ingest eligibility checks the live target junction directly
        qr._insert_target_junction = target_junction

    def _table_guard(self, qr, receive, in_schema: StreamSchema):
        """Wrap a query receive with the @OnError policy of the table it
        mutates: the mutating query's dispatch is the table's host-side
        failure boundary (mutations compile into the query step), so its
        failures route to the table's policy instead of the input stream's
        — or the sender. Identity when the query mutates no guarded table."""
        tid = getattr(qr, "_mutates_table", None)
        action = self._table_fault.get(tid) if tid is not None else None
        if action is None:
            return receive

        def guarded(batch: EventBatch, now: int, *a, **kw) -> None:
            try:
                receive(batch, now, *a, **kw)
            except Exception as e:
                self._on_table_failure(tid, action, in_schema, batch, now, e)

        return guarded

    def _on_table_failure(
        self, tid: str, action: str, in_schema: StreamSchema,
        batch: EventBatch, now: int, exc: Exception,
    ) -> None:
        import logging

        log = logging.getLogger(__name__)
        sm = self.statistics_manager
        if sm is not None:
            sm.error_tracker(f"table.{tid}").add(1)
        if action == "STORE":
            from siddhi_tpu.core.error_store import ORIGIN_TABLE, make_entry

            store = self.manager.error_store
            try:
                events = in_schema.from_batch(batch, self.interner)
            except Exception:
                events = []
            store.store(make_entry(
                self.name, ORIGIN_TABLE, tid, exc,
                events=[(ts, tuple(d)) for ts, _k, d in events],
                # the mutating query's input stream: replay re-drives the
                # batch through it (the table itself takes no direct input)
                sink_ref=in_schema.stream_id,
            ))
            return
        log.error(
            "table '%s': dropping a failed mutation batch "
            "(@OnError action='LOG'): %s", tid, exc, exc_info=exc,
        )

    def _wire_query_lineage(self, qr) -> None:
        """Arm the query's provenance recorder when @app:lineage is on.
        Runs at construction time — BEFORE anything can trace the jitted
        step, so the `__lin.*` lane structure is part of every program
        (hot-deployed queries ride the same path via _add_query*)."""
        cfg = self._lineage_cfg
        if cfg is None:
            return
        try:
            qr.arm_lineage(cfg)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "lineage could not be armed for query '%s'",
                getattr(qr, "query_id", "?"), exc_info=True,
            )

    def _wire_query_stats(self, qr, qid: str):
        """Attach latency + device-budget trackers to a query runtime;
        returns the latency tracker (or None with statistics off)."""
        sm = self.statistics_manager
        if sm is None:
            return None
        qr.device_step_tracker = sm.device_time_tracker(f"query.{qid}", "step")
        qr.sync_stall_tracker = sm.device_time_tracker(
            f"query.{qid}", "sync_stall"
        )
        # compile telemetry + waterfall sub-stage attribution for the
        # per-batch jitted step (observability/profiler.py)
        qr.compile_telemetry = sm.compile_telemetry
        qr.profiler = sm.profiler
        return sm.latency_tracker(f"query.{qid}")

    def _timer_batch(self, schema: StreamSchema, t_ms: int) -> EventBatch:
        from siddhi_tpu.core.event import KIND_TIMER

        nulls = tuple(None for _ in schema.attrs)
        return schema.to_batch(
            [t_ms], [nulls], self.interner,
            capacity=self.batch_size, kinds=[KIND_TIMER],
        )

    def _add_query(self, qid: str, query: Query) -> None:
        if qid in self.queries:
            raise SiddhiAppCreationError(f"duplicate query name '{qid}'")
        stream = query.input_stream
        if isinstance(stream, JoinInputStream):
            self._add_join_query(qid, query)
            return
        if isinstance(stream, StateInputStream):
            self._add_pattern_query(qid, query)
            return
        if not isinstance(stream, SingleInputStream):
            raise SiddhiAppCreationError(
                f"{type(stream).__name__} queries land in later milestones"
            )
        in_schema = self.stream_schemas.get(stream.stream_id)
        src_junction = None
        if in_schema is None and stream.stream_id in self.named_windows:
            # `from W`: consume the named window's emission stream
            nw = self.named_windows[stream.stream_id]
            in_schema = nw.schema
            src_junction = nw.out_junction
        if in_schema is None:
            raise DefinitionNotExistError(
                f"query '{qid}': stream '{stream.stream_id}' is not defined"
            )
        qr = QueryRuntime(
            query, qid, in_schema, self.interner,
            group_capacity=self.group_capacity,
            tables=self.tables,
        )
        self._wire_query_lineage(qr)
        self.queries[qid] = qr
        self._wire_insert(qr)

        decode = self._decode
        in_junction = src_junction or self._junction(stream.stream_id)
        lt = self._wire_query_stats(qr, qid)

        def receive(
            batch: EventBatch, now: int, _qr=qr, _lt=lt, _qid=qid,
            _schema=in_schema,
        ) -> None:
            dbg = self._debugger
            if dbg is not None:
                from siddhi_tpu.core.debugger import QueryTerminal

                dbg.check(
                    _qid, QueryTerminal.IN,
                    lambda: [Event(t, d) for t, _k, d in decode(_schema, batch)],
                )
            if _lt is not None:
                _lt.mark_in()
            try:
                with self._process_lock:
                    out_batch, aux = _qr.receive(batch, now)
                    _qr.route_output(out_batch, now, decode)
            finally:
                if _lt is not None:
                    _lt.mark_out()
            if dbg is not None:
                dbg.check(
                    _qid, QueryTerminal.OUT,
                    lambda: [
                        Event(t, d)
                        for t, _k, d in decode(_qr.out_schema, out_batch)
                    ],
                )
            self._maybe_schedule(_qr, aux)

        self._wire_subscribe(
            in_junction, self._table_guard(qr, receive, in_schema),
            name=f"query.{qid}",
        )
        from siddhi_tpu.core.ingest import FuseEndpoint

        self._wire_fuse_candidate(in_junction, FuseEndpoint(
            qr,
            impl_factory=lambda _qr=qr: _qr._step_impl,
            init_state=lambda now, _qr=qr: _qr.init_state(),
            latency_tracker=lt,
        ))

        if qr.needs_scheduler:
            def fire(t_ms: int, _qr=qr, _schema=in_schema) -> None:
                if getattr(_qr, "_removed", False):
                    return  # hot-undeployed with a timer still pending
                batch = self._timer_batch(_schema, t_ms)
                with self._process_lock:
                    out_batch, aux = _qr.receive(batch, t_ms)
                    _qr.route_output(out_batch, t_ms, decode)
                self._maybe_schedule(_qr, aux)

            qr.timer_target = fire

    def _add_pattern_query(self, qid: str, query: Query) -> None:
        from siddhi_tpu.core.pattern_runtime import PatternQueryRuntime

        # pre-validate every referenced stream: the NFA builder indexes
        # stream_schemas directly, which would surface a raw KeyError with no
        # stream/query context (fallback path when analysis is disabled)
        from siddhi_tpu.query_api.execution import iter_state_streams

        for s in iter_state_streams(query.input_stream.state):
            if s.stream_id not in self.stream_schemas:
                raise DefinitionNotExistError(
                    f"query '{qid}': pattern stream '{s.stream_id}' is not "
                    "defined (patterns consume streams, not tables or windows)"
                )

        token_capacity = self._capacity_annotation("app:patternCapacity", 128)
        count_capacity = self._capacity_annotation("app:countCapacity", 8)
        pattern_chunk = self._capacity_annotation("app:patternChunk", 0)
        qr = PatternQueryRuntime(
            query,
            qid,
            self.stream_schemas,
            self.interner,
            group_capacity=self.group_capacity,
            token_capacity=token_capacity,
            count_capacity=count_capacity,
            batch_size=self.batch_size,
            tables=self.tables,
            pattern_chunk=pattern_chunk or None,
        )
        self._wire_query_lineage(qr)
        self.queries[qid] = qr
        self._wire_insert(qr)
        decode = self._decode
        lt = self._wire_query_stats(qr, qid)

        def receive(batch: EventBatch, now: int, sid: str, _qr=qr, _lt=lt) -> None:
            if _lt is not None:
                _lt.mark_in()
            try:
                with self._process_lock:
                    out_batch, aux = _qr.receive(batch, now, sid)
                    _qr.route_output(out_batch, now, decode)
            finally:
                if _lt is not None:
                    _lt.mark_out()
            self._maybe_schedule(_qr, aux)

        from siddhi_tpu.core.ingest import FuseEndpoint

        for sid in qr.prog.stream_ids:
            sj = self._junction(sid)
            self._wire_subscribe(
                sj,
                self._table_guard(
                    qr,
                    lambda b, now, _sid=sid: receive(b, now, _sid),
                    self.stream_schemas[sid],
                ),
                name=f"query.{qid}",
            )
            ep = FuseEndpoint(
                qr,
                impl_factory=lambda _qr=qr, _sid=sid: _qr._make_step(_sid),
                init_state=lambda now, _qr=qr: _qr.init_state(now),
                latency_tracker=lt,
            )
            ep.lineage_tag = sid  # recorder shadows are per input stream
            self._wire_fuse_candidate(sj, ep)

        if qr.needs_scheduler:
            def fire(t_ms: int, _qr=qr) -> None:
                if getattr(_qr, "_removed", False):
                    return
                batch = _pattern_timer_batch(t_ms)
                with self._process_lock:
                    out_batch, aux = _qr.receive_timer(batch, t_ms)
                    _qr.route_output(out_batch, t_ms, decode)
                self._maybe_schedule(_qr, aux)

            qr.timer_target = fire

    def _add_join_query(self, qid: str, query: Query) -> None:
        from siddhi_tpu.core.join import DEFAULT_JOIN_CAPACITY, JoinQueryRuntime

        join = query.input_stream
        # aggregation join sides expose the merged buckets view filtered by
        # the join's within/per clause (reference: AggregationRuntime joins)
        agg_findables = {}
        for s in (join.left, join.right):
            if s.stream_id in self.aggregations:
                from siddhi_tpu.core.aggregation import (
                    AggFindable,
                    parse_per,
                    parse_within_value,
                )
                from siddhi_tpu.query_api.expression import (
                    AttributeFunction,
                    Constant,
                )

                if join.per is None or not isinstance(join.per, Constant):
                    raise SiddhiAppCreationError(
                        "joining an aggregation needs per '<duration>'"
                    )
                within = None
                w = join.within
                if isinstance(w, AttributeFunction) and w.name == "__within_range__":
                    lo, hi = w.parameters
                    if not (isinstance(lo, Constant) and isinstance(hi, Constant)):
                        raise SiddhiAppCreationError(
                            "'within' operands must be constants"
                        )
                    within = (
                        parse_within_value(lo.value)[0],
                        parse_within_value(hi.value)[0],
                    )
                elif isinstance(w, Constant):
                    within = parse_within_value(w.value)
                elif w is not None:
                    raise SiddhiAppCreationError(
                        "'within' operands must be constants"
                    )
                agg_findables[s.stream_id] = AggFindable(
                    self.aggregations[s.stream_id],
                    parse_per(join.per.value),
                    within,
                )
        schemas = []
        for s in (join.left, join.right):
            sch = self.stream_schemas.get(s.stream_id)
            if sch is None and s.stream_id in self.tables:
                sch = self.tables[s.stream_id].schema
            if sch is None and s.stream_id in self.named_windows:
                sch = self.named_windows[s.stream_id].schema
            if sch is None and s.stream_id in agg_findables:
                sch = agg_findables[s.stream_id].schema
            if sch is None:
                raise DefinitionNotExistError(
                    f"query '{qid}': join stream '{s.stream_id}' is not defined"
                )
            schemas.append(sch)
        join_capacity = self._capacity_annotation(
            "app:joinCapacity", DEFAULT_JOIN_CAPACITY
        )
        qr = JoinQueryRuntime(
            query, qid, schemas[0], schemas[1], self.interner,
            group_capacity=self.group_capacity, join_capacity=join_capacity,
            tables=self.tables,
            findables={**self.tables, **self.named_windows, **agg_findables},
        )
        self._wire_query_lineage(qr)
        self.queries[qid] = qr
        self._wire_insert(qr)
        decode = self._decode
        lt = self._wire_query_stats(qr, qid)

        def receive_side(
            batch: EventBatch, now: int, side: str, _qr=qr, _lt=lt
        ) -> None:
            if _lt is not None:
                _lt.mark_in()
            try:
                with self._process_lock:
                    out_batch, aux = _qr.receive(batch, now, side)
                    _qr.route_output(out_batch, now, decode)
            finally:
                if _lt is not None:
                    _lt.mark_out()
            if "next_timer" in aux:
                self._schedule_at(aux, _qr.timer_targets.get(side))

        from siddhi_tpu.core.ingest import FuseEndpoint

        # self-joins: one subscription drives left then right, in that order
        # (reference: JoinInputStreamParser self-join double dispatch)
        if join.left.stream_id == join.right.stream_id:
            j = self._junction(join.left.stream_id)
            self._wire_subscribe(
                j,
                self._table_guard(
                    qr,
                    lambda b, now: (
                        receive_side(b, now, "l"), receive_side(b, now, "r")
                    ),
                    schemas[0],
                ),
                name=f"query.{qid}",
            )

            def _both_sides_impl(_qr=qr):
                import jax.numpy as jnp

                def impl(st, tst, b, now):
                    st, tst, _o1, aux1 = _qr._step_impl(st, tst, b, now, "l")
                    st, tst, out, aux2 = _qr._step_impl(st, tst, b, now, "r")
                    # lineage lanes must NOT be bool-merged across the two
                    # halves: re-key them side-tagged (`__lin@l.` / `__lin@r.`)
                    # so the recorder replays l then r, the per-batch order
                    merged = {}
                    for side_aux, tag in ((aux1, "l"), (aux2, "r")):
                        for k, v in side_aux.items():
                            if k.startswith("__lin."):
                                merged[f"__lin@{tag}." + k[len("__lin."):]] = v
                    for k, v in aux2.items():
                        if not k.startswith("__lin"):
                            merged[k] = v
                    for k, v in aux1.items():
                        if k == "next_timer" or k.startswith("__lin"):
                            continue
                        if k in merged:
                            merged[k] = (
                                jnp.asarray(v).astype(bool)
                                | jnp.asarray(merged[k]).astype(bool)
                            )
                        else:
                            merged[k] = v
                    return st, tst, out, merged

                return impl

            self._wire_fuse_candidate(j, FuseEndpoint(
                qr, impl_factory=_both_sides_impl,
                init_state=lambda now, _qr=qr: _qr.init_state(),
                latency_tracker=lt,
            ))
        else:
            for side, stream in (("l", join.left), ("r", join.right)):
                nw = qr.window_sides[side]
                if nw is not None:
                    # named-window side: driven by the window's emissions
                    # (no FuseEndpoint: that junction never sees send_columns,
                    # and the missing candidate keeps it per-batch)
                    self._wire_subscribe(
                        nw.out_junction,
                        lambda b, now, _s=side: receive_side(b, now, _s),
                        name=f"query.{qid}",
                    )
                elif not qr.table_sides[side]:
                    sj = self._junction(stream.stream_id)
                    self._wire_subscribe(
                        sj,
                        self._table_guard(
                            qr,
                            lambda b, now, _s=side: receive_side(b, now, _s),
                            schemas[0 if side == "l" else 1],
                        ),
                        name=f"query.{qid}",
                    )
                    ep = FuseEndpoint(
                        qr,
                        impl_factory=lambda _qr=qr, _s=side: (
                            lambda st, tst, b, now: _qr._step_impl(
                                st, tst, b, now, _s
                            )
                        ),
                        init_state=lambda now, _qr=qr: _qr.init_state(),
                        latency_tracker=lt,
                    )
                    ep.lineage_tag = side  # recorder side shadows
                    self._wire_fuse_candidate(sj, ep)

        for side, schema in qr.side_schemas.items():
            if qr.needs_scheduler[side]:
                def fire(t_ms: int, _side=side, _schema=schema, _qr=qr) -> None:
                    if getattr(_qr, "_removed", False):
                        return
                    receive_side(self._timer_batch(_schema, t_ms), t_ms, _side)

                qr.timer_targets[side] = fire

    def _decode(self, schema: StreamSchema, batch: EventBatch):
        return schema.from_batch(batch, self.interner)

    def _maybe_schedule(self, qr: QueryRuntime, aux: dict) -> None:
        hnt = getattr(qr, "host_next_timer", None)
        if hnt is not None:
            if getattr(qr, "timer_target", None) is not None:
                self._scheduler.start()
                self._scheduler.notify_at(hnt(self.clock()), qr.timer_target)
            return
        if not qr.needs_scheduler or "next_timer" not in aux:
            return
        self._schedule_at(aux, qr.timer_target)

    def _arm_rate_limiter(self, qr) -> None:
        """Recurring flush timer for time/snapshot rate limiters
        (reference: time-based OutputRateLimiter scheduler wiring)."""
        rl = getattr(qr, "rate_limiter", None)
        if rl is None or rl.period_ms is None:
            return
        period = rl.period_ms

        def fire(t_ms: int, _qr=qr, _rl=rl) -> None:
            if not self._running or getattr(_qr, "_removed", False):
                return  # stopped, or hot-undeployed: stop re-arming
            with self._process_lock:
                _qr._deliver(_rl.on_timer(t_ms), t_ms)
            self._scheduler.notify_at(t_ms + period, fire)

        self._scheduler.start()
        self._scheduler.notify_at(self.clock() + period, fire)

    def _schedule_at(self, aux: dict, target) -> None:
        if target is None or "next_timer" not in aux:
            return
        from siddhi_tpu.core.windows import NO_TIMER

        t = int(aux["next_timer"])
        if t < int(NO_TIMER):
            self._scheduler.start()
            self._scheduler.notify_at(t, target)

    # ---- public API (reference: SiddhiAppRuntime callbacks/handlers) -----

    def get_input_handler(self, stream_id: str) -> InputHandler:
        j = self._junction(stream_id)
        h = InputHandler(j, lambda: self.clock())
        if self._playback_clock is not None:
            h = _PlaybackInputHandler(h, self._playback_clock)
        if self._watermark is not None:
            # @app:watermark bounded reorder stage, OUTSIDE the playback
            # wrapper so the clock only advances when ordered rows are
            # RELEASED (never on raw disordered arrival), but inside
            # admission/disorder (core/watermark.py)
            h = _WatermarkInputHandler(
                self._watermark, stream_id, h,
                self.stream_schemas[stream_id].attr_names,
            )
        from siddhi_tpu.testing import faults as _faults

        if _faults.ACTIVE is not None:
            # `ingest_disorder` transform site: only wrapped while a fault
            # plan is live, so normal operation pays nothing
            h = _DisorderInputHandler(h, f"{self.name}:{stream_id}")
        if self._admission is not None:
            # @app:admission gate, outermost: over-quota/over-bound sends
            # block/shed/error BEFORE any encode work (core/admission.py)
            from siddhi_tpu.core.admission import AdmittedInputHandler

            h = AdmittedInputHandler(h, self._admission, j)
        return h

    input_handler = get_input_handler

    def _fault_junction_for(self, stream_id: str):
        """The `!S` side junction of a stream (late-event diversion target),
        or None when no fault schema was defined for it."""
        fid = "!" + stream_id
        if fid not in self.stream_schemas:
            return None
        return self._junction(fid)

    def _aggregations_for_stream(self, stream_id: str) -> list:
        """Aggregation runtimes fed by `stream_id` (the late.policy='apply'
        re-open targets)."""
        return [
            ar for aid, ar in self.aggregations.items()
            if self._agg_inputs.get(aid) == stream_id
        ]

    def drain_watermarks(self) -> None:
        """Flush every @app:watermark reorder buffer and catch the clock up
        to the newest event seen — the explicit end-of-feed signal (also
        run automatically at shutdown). No-op when watermarks are off."""
        if self._watermark is not None:
            self._watermark.drain()

    # ---- zero-downtime churn (core/churn.py) ------------------------------

    def add_query(self, query, seed="checkpoint") -> str:
        """Hot-deploy one query into this (possibly running) app without
        draining it: parse -> SA130 lint against the live symbols ->
        construct + prewarm off-line -> splice into the junction fan-out
        under the app process lock, seeding windows/patterns from the last
        checkpoint when a compatible `query:<id>` element exists
        (`seed='checkpoint'`, the default; `seed='cold'` skips).
        Fusion groups re-form around the grown wiring; surviving queries'
        emissions are byte-identical across the splice. Returns the
        assigned query id. The retained AST grows too, so a supervised
        restart rebuilds the app WITH the hot-deployed query."""
        from siddhi_tpu.core.churn import add_query as _add

        return _add(self, query, seed=seed)

    def remove_query(self, qid: str) -> None:
        """Hot-undeploy one top-level query (inverse of add_query): it is
        unspliced under the process lock, dropped from the retained AST,
        and the fusion groups re-form over the shrunk wiring."""
        from siddhi_tpu.core.churn import remove_query as _remove

        _remove(self, qid)

    def replay_target_available(self, entry) -> bool:
        """May `replay_error(entry)` be dispatched WITHOUT blocking? False
        for sink entries whose target transport is still disconnected and
        publishes under `on.error='WAIT'` (the replay would block until the
        transport reconnects) — `manager.replay_errors(skip_unavailable=
        True)` consults this so one dead sink cannot hold every other app's
        entries hostage."""
        from siddhi_tpu.core.error_store import ORIGIN_SINK

        if not self._running:
            return False
        if entry.origin != ORIGIN_SINK:
            return True
        for sink in self.sinks:
            for s in getattr(sink, "sinks", None) or [sink]:
                if s.stream_id != entry.stream_id:
                    continue
                if entry.sink_ref and s.sink_ref != entry.sink_ref:
                    continue
                return s.on_error != "WAIT" or s.connected
        return True  # no matching sink: replay_error returns False quickly

    def replay_error(self, entry) -> bool:
        """Re-drive one stored ErroneousEvent through its origin. Stream
        (and table-mutation) entries re-enter the input handler (and re-run
        every downstream query); sink entries re-publish their mapped
        payload under the sink's on.error policy; source entries re-deliver
        the raw wire payload through the source's mapper. Returns True when
        the replay was dispatched."""
        from siddhi_tpu.core.error_store import (
            ORIGIN_SINK,
            ORIGIN_SOURCE,
            ORIGIN_STREAM,
            ORIGIN_TABLE,
        )

        if entry.app_name != self.name:
            return False
        if not self._running:
            # sinks/sources aren't connected before start(): the entry stays
            # stored until the app is up (supervisor replays AFTER resume)
            return False
        if entry.origin in (ORIGIN_STREAM, ORIGIN_TABLE):
            # table entries re-drive the mutating query's input batch
            # through its input stream (stashed in sink_ref)
            sid = (
                entry.stream_id
                if entry.origin == ORIGIN_STREAM
                else entry.sink_ref
            )
            if sid not in self.stream_schemas or not entry.events:
                return False
            # RAW handler, not get_input_handler(): the admission gate must
            # not apply — these events were admitted once already, and a
            # quota-starved gate would silently shed the replay while the
            # caller purges the entry (permanent loss). Timestamps are
            # explicit, so the playback wrapper is unnecessary too.
            from siddhi_tpu.core.supervision import failure_ownership

            h = InputHandler(self._junction(sid), lambda: self.clock())
            # failure_ownership: a replay that explodes raises to the
            # replay caller and the entry stays stored — it must not ALSO
            # flag the app as crashed, or a poison entry puts a supervised
            # app into a restart->replay->crash loop
            with failure_ownership():
                h.send_many(
                    [row for _ts, row in entry.events],
                    timestamps=[ts for ts, _row in entry.events],
                )
            return True
        if entry.origin == ORIGIN_SOURCE:
            for src in self.sources:
                if src.stream_id != entry.stream_id:
                    continue
                # replay through the mapper again; True means "safe to
                # purge": delivered, or the source's own on.error path
                # re-captured the payload (STORE re-stores on failure)
                if src.paused:
                    # deliver() returns False WITHOUT running the failure
                    # path — nothing was re-stored, so the entry must stay
                    return False
                # raw handler override: the wired one is admission-gated,
                # and a shed replay would report delivered -> purged
                raw = InputHandler(
                    self._junction(src.stream_id), lambda: self.clock()
                )
                ok = src.deliver(entry.payload, handler=raw)
                if ok:
                    return True
                # STORE only re-captured the payload when a store is
                # actually wired; otherwise _on_deliver_failure dropped it
                # and purging here would make the loss permanent
                return (
                    src.on_error == "STORE"
                    and src.error_store_fn is not None
                    and src.error_store_fn() is not None
                )
            return False
        if entry.origin == ORIGIN_SINK:
            # target the exact sink that failed (by sink_ref); fall back to
            # the first stream_id match for entries from older stores. True
            # means "safe to purge": delivered, or the sink's own failure
            # path re-captured the payload (STORE always re-stores; WAIT only
            # drops at shutdown when no store is wired). A LOG/RETRY sink
            # that fails again DROPS the payload, so the entry must survive.
            for sink in self.sinks:
                for s in getattr(sink, "sinks", None) or [sink]:
                    if s.stream_id != entry.stream_id:
                        continue
                    if entry.sink_ref and s.sink_ref != entry.sink_ref:
                        continue
                    ok = s.publish_guarded(entry.payload)
                    return ok or s.on_error == "STORE" or (
                        s.on_error == "WAIT" and s.error_store_fn is not None
                    )
            return False
        return False

    def set_exception_handler(self, handler) -> None:
        """Route subscriber-dispatch failures to `handler(exc)` instead of
        propagating to the sender (reference: SiddhiAppRuntime.handleExceptionWith
        for the Disruptor ExceptionHandler)."""
        for j in self.junctions.values():
            j.exception_handler = handler
        self._exception_handler = handler

    def debug(self):
        """Step-mode debugger (reference: SiddhiAppRuntime.debug:509)."""
        from siddhi_tpu.core.debugger import SiddhiDebugger

        if self._debugger is None:
            self._debugger = SiddhiDebugger(self)
        return self._debugger

    def enable_stats(self, enabled: bool) -> None:
        """Toggle metric collection AND tracing at runtime (reference:
        SiddhiAppRuntime.enableStats:682). Disabling stops every tracker at
        its gate check — the hot path cost becomes one attribute read."""
        if self.statistics_manager is not None:
            self.statistics_manager.enabled = enabled
        if self.tracer is not None:
            self.tracer.enabled = enabled

    def traces(self) -> list:
        """Completed sampled traces (oldest first), each a JSON-serializable
        dict of spans crossing ingress junction -> query -> sink. Empty when
        `@app:statistics(trace.sample=...)` is not configured."""
        return self.tracer.traces() if self.tracer is not None else []

    # ---- EXPLAIN ANALYZE + profiling (observability/explain.py,
    # observability/profiler.py) --------------------------------------------

    def explain(self, fmt: str = "text"):
        """The app's dataflow plan annotated with live counters (events
        in/out, selectivity, latency, device-time share, compile ledger) —
        EXPLAIN ANALYZE for the running app. fmt='text' renders; 'dict'/
        'json' returns the raw plan. Works without `@app:statistics` too
        (topology only, no counters)."""
        from siddhi_tpu.observability.explain import explain

        return explain(self, fmt=fmt)

    def explain_plan(self) -> dict:
        """`explain(fmt='dict')` — the raw node/edge plan."""
        return self.explain(fmt="dict")

    def profile_report(self) -> dict:
        """Compile telemetry + slowest-chunk waterfalls + high latency
        quantiles (`/profile` payload); None without `@app:statistics`.
        Plan-driven fused groups (core/fusion_exec.py) append their
        achieved-vs-predicted dispatch-reduction ledger under
        `fused_groups`, keyed by the cost model's component taxonomy
        (`stream.<S>.fusedgroup.<g>`)."""
        sm = self.statistics_manager
        if sm is None:
            return None
        rep = sm.profile_report()
        groups = []
        for j in list(self.junctions.values()):
            fi = j.fused_ingest
            gr = fi.group_report() if fi is not None else None
            if gr is not None:
                groups.append({"stream": j.schema.stream_id, **gr})
        if groups:
            rep["fused_groups"] = groups
        if self._shard is not None:
            # per-device dispatch/event counts of the sharded runtime mode
            # (parallel/shard.py), beside the fused-group ledger
            rep["shard"] = self._shard.describe_state()
        return rep

    def calibration_report(self):
        """Plan-vs-actual calibration ledger: every static prediction
        paired with its live meter, error ratios + EWMA drift, mispricing
        flags (`/calibration` payload, observability/calibration.py); None
        without `@app:statistics` (the zero-overhead gate)."""
        c = self._calibration
        return c.report() if c is not None else None

    def slo_report(self):
        """Multi-window SLO burn rates for this app's `@app:slo`
        objectives (`/slo` payload, observability/slo.py); None without
        the annotation."""
        s = self._slo
        return s.report() if s is not None else None

    # ---- state introspection (observability/introspect.py) ----------------

    def snapshot_status(self) -> dict:
        """Live per-component state of this app: junction queue depths and
        wiring, window type/fill/capacity, NFA active-instance counts,
        aggregation buckets/watermarks, table row counts, ingest-pipeline
        depth/occupancy/slots in flight. Pull-only: nothing is collected
        until asked (served as `/status` + `/status.json` when
        `manager.serve_metrics()` is up)."""
        # list() snapshots: junctions are created lazily (selfmon's system
        # junction arms from the scheduler thread, store-query targets from
        # callers), and a plain dict iteration racing an insert raises
        status: dict = {
            "app": self.name,
            "running": self._running,
            "streams": {
                sid: j.describe_state()
                for sid, j in list(self.junctions.items())
            },
            "queries": {
                qid: qr.describe_state() for qid, qr in self.queries.items()
            },
            "windows": {
                wid: nw.describe_state()
                for wid, nw in self.named_windows.items()
            },
            "tables": {
                tid: t.describe_state() for tid, t in self.tables.items()
            },
            "aggregations": {
                aid: ar.describe_state()
                for aid, ar in self.aggregations.items()
            },
        }
        if self._watermark is not None:
            status["watermark"] = self._watermark.describe_state()
            # the stream-level watermark beside each aggregation's
            # per-duration bucket watermarks (ISSUE 16 satellite: uniform
            # watermark surfacing)
            for aid, agg_status in status["aggregations"].items():
                agg_status["stream_watermark_ms"] = self._watermark.watermark_of(
                    self._agg_inputs.get(aid, "")
                )
        if self._shard is not None:
            status["shard"] = self._shard.describe_state()
        if self._selfmon is not None:
            status["selfmon"] = self._selfmon.describe_state()
        if self._slo is not None:
            status["slo"] = self._slo.describe_state()
        if self._calibration is not None:
            status["calibration"] = self._calibration.describe_state()
        if self._admission is not None:
            status["admission"] = self._admission.describe_state()
        if self._autopersist is not None:
            status["autopersist"] = self._autopersist.describe_state()
        if self._blackbox is not None:
            status["blackbox"] = self._blackbox.describe_state()
        health = getattr(self, "_health", None)
        if health is not None:
            status["health"] = health.describe_state()
        # churn ledger (core/churn.py; manager-owned so it survives
        # redeploys and supervised restarts)
        churn = self.manager.churn_stats(self.name, create=False)
        if churn is not None:
            status["churn"] = churn.describe_state()
        return status

    # ---- flight recorder (observability/flight.py) ------------------------

    def flight_record(self, stream_id: str) -> list[tuple[int, tuple]]:
        """The last-N events through `stream_id`'s junction, oldest first,
        as (timestamp_ms, data_tuple) pairs. Raises when the stream has no
        recorder (enable with @flightRecorder(size='N') or
        SIDDHI_TPU_FLIGHT=N)."""
        j = self.junctions.get(stream_id)
        if j is None:
            raise DefinitionNotExistError(
                f"no stream '{stream_id}' in app '{self.name}'"
            )
        if j.flight is None:
            raise SiddhiAppCreationError(
                f"stream '{stream_id}' has no flight recorder — enable it "
                "with @flightRecorder(size='N') or SIDDHI_TPU_FLIGHT=N"
            )
        return j.flight.events()

    def flight_records(self) -> dict[str, list[tuple[int, tuple]]]:
        """Every recorded junction's ring, keyed by stream id (empty dict
        when no junction has a recorder)."""
        return {
            sid: j.flight.events()
            for sid, j in list(self.junctions.items())
            if j.flight is not None
        }

    # ---- black box & incident replay (observability/blackbox.py) ----------

    def incidents(self) -> list[dict]:
        """Incident bundles frozen by this runtime's black-box recorder,
        oldest first (empty when @app:blackbox is not armed)."""
        if self._blackbox is None:
            return []
        return self._blackbox.incident_index()

    def replay_incident(self, bundle, debug: bool = False, streams=None):
        """Deterministically replay an incident bundle (dict or path):
        rebuild the app from the bundle's retained AST under
        @app:playback, restore the pinned checkpoint, and re-feed the
        recorded rings in arrival order. With `debug=True` the returned
        IncidentReplay holds a live runtime with a SiddhiDebugger
        attached and feeding deferred to the caller."""
        from siddhi_tpu.observability.blackbox import replay_incident

        return replay_incident(bundle, debug=debug, streams=streams)

    # ---- lineage & provenance (observability/lineage.py) ------------------

    def lineage(self, target: str, index: int | None = None,
                depth: int = 6) -> dict:
        """Explain output `index` of `target` back to the exact input
        events (@app:lineage required). `target` is a query id (index = the
        query's k-th recorded output row) or a stream id (index = the
        junction's lineage seq id — its k-th valid CURRENT event); None
        picks the latest. The chain walks insert-into hops backward and
        decodes the contributing events from the per-stream arenas."""
        if self.lineage_ledger is None:
            raise SiddhiAppCreationError(
                f"app '{self.name}' has no lineage — enable it with "
                "@app:lineage(capacity='N')"
            )
        return self.lineage_ledger.resolve(target, index, depth)

    def lineage_report(self, resolve_recent: int = 1) -> dict:
        """The app's /lineage.json payload: per-stream arenas, per-query
        fan-in + recorded provenance, per-aggregation buckets (empty dict
        when @app:lineage is off)."""
        if self.lineage_ledger is None:
            return {}
        return self.lineage_ledger.report(resolve_recent=resolve_recent)

    def dump_traces(self, path: str | None = None, indent: int = 1) -> str:
        """JSON dump of `traces()`; also written to `path` when given."""
        import json as _json

        text = _json.dumps(self.traces(), indent=indent)
        if path is not None:
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
        return text

    def add_callback(self, name: str, callback: Callable) -> None:
        """Stream callback `cb(events: list[Event])` or query callback
        `cb(timestamp, in_events, removed_events)` — dispatched on arity by
        target: stream name vs @info query name (reference: addCallback overloads).
        """
        # retained for supervised rebuild (core/supervision.Supervisor
        # re-registers these on the replacement runtime after a restart)
        self._user_callbacks.append((name, callback))
        if name in self.queries:
            qr = self.queries[name]

            # all-C construction path: namedtuple __new__ measured ~1.5 us
            # per event against ~0.3 us for map(partial(tuple.__new__, ...))
            import operator
            from functools import partial

            _mk = partial(tuple.__new__, Event)
            _td = operator.itemgetter(0, 2)

            def qcb(ts, ins, removed, _cb=callback, _mk=_mk, _td=_td):
                _cb(
                    ts,
                    list(map(_mk, map(_td, ins))) if ins else None,
                    list(map(_mk, map(_td, removed))) if removed else None,
                )

            qr.query_callbacks.append(qcb)
            # raw-callback registry: the fused egress drain builds Event
            # lists once and invokes user callbacks directly, skipping the
            # triple->Event re-extraction (only valid while the two lists
            # stay in 1:1 correspondence; the drain checks)
            if not hasattr(qr, "raw_query_callbacks"):
                qr.raw_query_callbacks = []
            qr.raw_query_callbacks.append(callback)
            return
        if name in self.stream_schemas:
            j = self._junction(name)
            j.add_stream_callback(
                lambda rows, _cb=callback: _cb([Event(t, d) for t, d in rows])
            )
            return
        raise DefinitionNotExistError(f"no stream or query named '{name}'")

    def query(self, store_query) -> list:
        """One-shot pull query over tables (reference:
        SiddhiAppRuntime.query:264-299, cached per query string)."""
        from siddhi_tpu.core.store_query import StoreQueryRuntime
        from siddhi_tpu.query_api.execution import StoreQuery

        if isinstance(store_query, str):
            sqr = self._store_query_cache.get(store_query)
            if sqr is None:
                from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

                sq = SiddhiCompiler.parse_store_query(store_query)
                sqr = StoreQueryRuntime(
                    sq, self.tables, self.interner,
                    group_capacity=self.group_capacity,
                    windows=self.named_windows,
                    aggregations=self.aggregations,
                )
                self._store_query_cache[store_query] = sqr
        else:
            assert isinstance(store_query, StoreQuery)
            sqr = StoreQueryRuntime(
                store_query, self.tables, self.interner,
                group_capacity=self.group_capacity,
                windows=self.named_windows,
                aggregations=self.aggregations,
            )
        from siddhi_tpu.observability.metrics import timed

        lt = (
            self.statistics_manager.latency_tracker("storequery")
            if self.statistics_manager is not None
            else None
        )
        with timed(lt):
            with self._process_lock:
                return sqr.execute(self.clock())

    def _build_fused_ingest(self) -> None:
        """(Re)build the per-junction fused ingest engines from the LIVE
        wiring + the current FusionPlan (core/ingest.py, core/fusion_exec.py):
        plan-driven GROUP engines first (the FusionPlan's fusable subset
        runs as one chunk program, blocked queries ride the residual
        per-batch path, shared-window candidates reference one ring), then
        the legacy all-or-nothing engine for junctions where every
        subscriber registered a FuseEndpoint. Called by start() and by the
        churn splice (core/churn.py) after the wiring grows/shrinks — the
        fusion groups re-form around the new query set. Batch shard
        routers re-arm on the rebuilt engines."""
        from siddhi_tpu.core.ingest import FusedJunctionIngest
        from siddhi_tpu.core.pipeline import resolve_pipeline_annotation

        chunk = self._capacity_annotation("app:ingestChunk", 32)
        fusion_configs: dict = {}
        try:
            from siddhi_tpu.core.fusion_exec import junction_fusion_configs

            fusion_configs = junction_fusion_configs(self)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "fusion planning failed for app '%s'; falling back to "
                "per-junction fusion only", self.name, exc_info=True,
            )
        from siddhi_tpu.core.wire import (
            build_wire_spec,
            wire_inference_enabled,
        )

        # value-analysis inferred wire hints (analysis/values.py): one
        # cheap AST pass per rebuild, overlaid under the declared hints
        # (declared wins per lane). Inference failure degrades to
        # declared-only, never to no wire.
        inferred: dict = {}
        if self._wire_enabled and wire_inference_enabled():
            from siddhi_tpu.analysis.values import infer_wire_hints_for_app

            inferred = infer_wire_hints_for_app(self.app)
        for j in list(self.junctions.values()):
            sid = j.schema.stream_id
            pipe_on, pipe_depth = self._pipeline_conf.get(
                sid, resolve_pipeline_annotation(None)
            )
            # analyzer-chosen per-column wire encodings (core/wire.py):
            # the static spec from declared types + @app:wire hints +
            # inferred overlay; None when nothing is statically encodable
            # (the sampled narrow wire stands alone) or wire encoding is
            # disabled
            spec = (
                build_wire_spec(
                    sid, j.schema.attrs, self._wire_hints,
                    capacity=j.batch_size, inferred=inferred,
                )
                if self._wire_enabled
                else None
            )
            cfg = fusion_configs.get(sid)
            if cfg is not None:
                j.fused_ingest = FusedJunctionIngest(
                    self, j, cfg["endpoints"], chunk_batches=chunk,
                    pipeline_enabled=pipe_on, pipeline_depth=pipe_depth,
                    component=cfg["component"], residual=cfg["residual"],
                    share_sets=cfg["share_sets"],
                    plan_group=cfg["plan_group"],
                    wire_spec=spec, wire_enabled=self._wire_enabled,
                )
            elif j.fuse_candidates and len(j.fuse_candidates) == len(j.subscribers):
                j.fused_ingest = FusedJunctionIngest(
                    self, j, j.fuse_candidates, chunk_batches=chunk,
                    pipeline_enabled=pipe_on, pipeline_depth=pipe_depth,
                    wire_spec=spec, wire_enabled=self._wire_enabled,
                )
        if self._shard is not None:
            self._shard.rearm_keyshard()
            self._shard.rearm_routers()
        # re-pair the calibration ledger against the AST that just formed
        # these engines: churn splices and fused re-formations re-price
        # automatically while cumulative mispriced counters survive (the
        # rearm_routers precedent — rebuild-owned re-arming)
        if self._calibration is not None:
            self._calibration.pair()

    def _teardown_fused_ingest(self) -> None:
        """Disable and close every fused ingest engine, splitting any
        cross-query aliased chain states first (PR 8's `_maybe_unshare`:
        followers get device copies, losslessly re-shareable by the next
        fused send). MUST run OUTSIDE the app process lock: a pipelined
        sender holds the engine's send lock while acquiring the process
        lock per chunk, so closing under the process lock would deadlock
        against it. While engines are down, sends ride the per-batch path
        — byte-identical by the fuse-on/off CI contract."""
        for j in list(self.junctions.values()):
            fi = j.fused_ingest
            if fi is None:
                continue
            j.fused_ingest = None  # new sends fall back per-batch now
            fi._disabled = True  # senders that already read `fi` bail out
            # close FIRST: it serializes on the engine's send lock, so an
            # in-flight send (already past the _disabled check) finishes —
            # and its writeback may re-alias shared chains — before the
            # unshare below splits them. Unsharing first would leave those
            # late-aliased states guardless: two per-batch steps donating
            # the same ring buffers.
            fi.close()
            try:
                fi._maybe_unshare()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "unsharing stream '%s' during churn teardown failed",
                    j.schema.stream_id,
                )
            # shared-ring bookkeeping detaches: the members' states are
            # private buffers again until a rebuilt engine re-shares
            for ep in fi.endpoints:
                if getattr(ep.qr, "shared_ring", None) is not None:
                    ep.qr.shared_ring = None
                ep.qr._unshare_guard = None

    def start(self) -> None:
        self._running = True
        # @app:fuse(disable='true') / SIDDHI_TPU_FUSE=0 skips the fused
        # ingest engines entirely (see _build_fused_ingest)
        if self._fuse_enabled:
            self._build_fused_ingest()
        # first-class sharded execution (parallel/shard.py): place
        # partitioned [P] state on the device mesh and arm batch-axis
        # routers on junctions whose fused endpoints are all stateless —
        # resolved from @app:shard / SIDDHI_TPU_SHARD at creation
        shard_devices, shard_axis = self._shard_conf
        if shard_devices >= 2:
            from siddhi_tpu.parallel.shard import ShardRuntime

            self._shard = ShardRuntime(self, shard_devices, shard_axis)
            self._shard.apply()
        if self.statistics_manager is not None:
            # device-memory metric per component (reference analog:
            # util/statistics/memory/ObjectSizeCalculator — here the bytes
            # are HBM buffers held by each component's carried state)
            def _tree_bytes(get_tree):
                def fn():
                    return sum(
                        getattr(leaf, "nbytes", 0)
                        for leaf in jax.tree_util.tree_leaves(get_tree())
                    )
                return fn

            sm = self.statistics_manager
            for qid, qr in self.queries.items():
                sm.register_memory(
                    f"query.{qid}", _tree_bytes(lambda _q=qr: _q.state)
                )
            for tid, t in self.tables.items():
                sm.register_memory(
                    f"table.{tid}", _tree_bytes(lambda _t=t: _t.state)
                )
                # table-op accounting: mutating steps + record-store flushes
                # (wired here so aggregation duration tables are covered too)
                t.mutation_stats = sm.throughput_tracker(f"table.{tid}").add
                t.flush_latency = sm.latency_tracker(f"table.{tid}.flush")
            for wid, w in self.named_windows.items():
                sm.register_memory(
                    f"window.{wid}", _tree_bytes(lambda _w=w: _w.state)
                )
            for aid, ar in self.aggregations.items():
                sm.register_memory(
                    f"aggregation.{aid}", _tree_bytes(lambda _a=ar: _a.state)
                )
            # pair the calibration ledger at start when no fused rebuild
            # already did (fuse disabled or no fusable junctions)
            if self._calibration is not None and \
                    self._calibration.generation == 0:
                self._calibration.pair()
            sm.start_reporting()
            if str(sm.reporter).lower() == "prometheus":
                # pull-based exposition: serve every app on this manager
                port = int(sm.options.get("port", "9464"))
                self.manager.serve_metrics(port)
        if self._playback_clock is not None:
            self._playback_clock.start_heartbeat()
        if self._watermark is not None:
            # idle heartbeat: quiet sources flush + go idle after
            # idle.timeout so they cannot stall the app watermark
            self._watermark.start()
            if self.statistics_manager is not None:
                self.statistics_manager.register_watermark(
                    self._watermark.describe_state
                )
        # absent-at-start patterns must arm their timers before any event
        # (reference: SiddhiAppRuntime.start -> eternalReferencedHolders.start)
        for qr in self.queries.values():
            if getattr(qr, "needs_scheduler", False) and hasattr(qr, "prime"):
                aux = qr.prime(self.clock())
                self._maybe_schedule(qr, aux)
            if getattr(qr, "host_next_timer", None) and getattr(qr, "timer_target", None):
                self._scheduler.start()
                self._scheduler.notify_at(
                    qr.host_next_timer(self.clock()), qr.timer_target
                )
            self._arm_rate_limiter(qr)
        # CEP-native self-monitoring: materialize the system junction NOW
        # (its lazy creation would otherwise happen on the scheduler thread,
        # racing concurrent junction-map readers) and arm the recurring feed
        # (observability/selfmon.py) before sources start publishing
        if self._selfmon is not None:
            from siddhi_tpu.observability.selfmon import SELFMON_STREAM_ID

            self._junction(SELFMON_STREAM_ID)
            self._selfmon.start()
        # SLO burn-rate evaluation (observability/slo.py): same junction
        # materialization + recurring-target contract as selfmon
        if self._slo is not None:
            from siddhi_tpu.observability.slo import SLO_STREAM_ID

            self._junction(SLO_STREAM_ID)
            self._slo.start()
        # @app:persist auto-checkpoint (core/supervision.AutoPersist): armed
        # only when a persistence store is actually wired — a missing store
        # would otherwise fail EVERY interval until someone noticed
        if self._autopersist is not None:
            if self.manager.persistence_store is None:
                import logging

                logging.getLogger(__name__).warning(
                    "app '%s' declares @app:persist but the manager has no "
                    "persistence store; auto-checkpointing is disabled "
                    "(call manager.set_persistence_store(...))", self.name,
                )
            else:
                self._autopersist.start()
        # @app:blackbox checkpoint pinner: pin the first base checkpoint
        # and re-pin every checkpoint.interval (default: window) so ring +
        # checkpoint always cover a coherent replayable interval
        if self._blackbox is not None:
            self._blackbox.start()
        # lifecycle ordering (reference: SiddhiAppRuntime.start:353-394):
        # sinks connect before sources so no event finds a dead egress;
        # triggers and sources begin last, into fully-wired queries
        for sink in self.sinks:
            sink.connect_with_retry()
        for src in self.sources:
            src.connect_with_retry()
        for tr in self.triggers.values():
            tr.start()

    def shutdown(self) -> None:
        self._running = False
        if self._watermark is not None:
            # tail delivery FIRST: release every buffered row through the
            # still-live junctions and fire the timers the final watermark
            # unlocks, before any ingest machinery stops
            self._watermark.stop()
            self._watermark.drain()
        for src in self.sources:
            src.stop()  # cancels pending reconnect retries too
        for tr in self.triggers.values():
            tr.stop()
        for j in self.junctions.values():
            if j.is_async:
                j.stop_async()
            if j.fused_ingest is not None:
                j.fused_ingest.close()  # stops the pipeline drain worker
        for sink in self.sinks:
            sink.stop()
        if self.statistics_manager is not None:
            self.statistics_manager.stop_reporting()
        if self._playback_clock is not None:
            self._playback_clock.stop()
        for qr in self.queries.values():
            qr.flush_aux_warnings()
        self._scheduler.shutdown()
        # flush AFTER the scheduler stops so no timer can re-dirty a table
        for t in self.tables.values():
            t.close_record_store()

    # ---- snapshot / persistence (reference: SiddhiAppRuntime.persist/
    # restore/restoreRevision/restoreLastRevision :560-600) -----------------

    @property
    def snapshot_service(self):
        svc = getattr(self, "_snapshot_service", None)
        if svc is None:
            from siddhi_tpu.core.persistence import SnapshotService

            svc = self._snapshot_service = SnapshotService(self)
        return svc

    def snapshot(self) -> bytes:
        return self.snapshot_service.full_snapshot()

    def restore(self, snapshot: bytes) -> None:
        self.snapshot_service.restore(snapshot)

    def _store(self):
        store = self.manager.persistence_store
        if store is None:
            raise SiddhiAppCreationError(
                "no persistence store set; call "
                "manager.set_persistence_store(...) first"
            )
        return store

    def persist(self) -> str:
        import time as _time

        for t in self.tables.values():
            t.flush_record_store()
        store = self._store()
        svc = self.snapshot_service
        if getattr(store, "incremental", False):
            data = svc.incremental_snapshot()
        else:
            data = svc.full_snapshot(track_base=True)
        # strictly monotone revision ids (two persists can share a millisecond)
        now = int(_time.time() * 1000)
        last = getattr(self, "_last_rev_ms", -1)
        now = max(now, last + 1)
        self._last_rev_ms = now
        revision = f"{now}_{self.name}"
        # fault-injection site `persist_save` (testing/faults.py): a failing
        # store save surfaces to the caller — AutoPersist counts it and
        # retries next interval, a manual persist() raises
        from siddhi_tpu.testing import faults as _faults

        if _faults.ACTIVE is not None:
            _faults.ACTIVE.check("persist_save", self.name)
        store.save(self.name, revision, data)
        # only now is the full payload durable: promote the staged delta
        # base (a failed save must NOT shift it, or every later cycle
        # emits deltas against a base revision that never reached the
        # store and restore silently no-ops or applies the wrong base)
        svc.commit_base()
        return revision

    def restore_revision(self, revision: str) -> None:
        store = self._store()
        # fault-injection site `persist_load` (testing/faults.py)
        from siddhi_tpu.testing import faults as _faults

        if _faults.ACTIVE is not None:
            _faults.ACTIVE.check("persist_load", self.name)
        data = store.load(self.name, revision)
        if data is None:
            raise SiddhiAppCreationError(f"no revision '{revision}'")
        if getattr(store, "incremental", False):
            # replay: the latest full snapshot at-or-before this revision,
            # plus every delta after it up to this revision
            chain = self._incremental_chain(store, upto=revision)
            self.snapshot_service.restore(*chain)
        else:
            self.snapshot_service.restore(data)

    def restore_last_revision(self) -> None:
        store = self._store()
        last = store.get_last_revision(self.name)
        if last is None:
            return
        self.restore_revision(last)

    def _incremental_chain(self, store, upto: str) -> list[bytes]:
        """[latest full at-or-before `upto`] + [the target delta] — every
        delta is diffed against the last persisted FULL snapshot, so earlier
        deltas must NOT be replayed (their leaves may have reverted since)."""
        import pickle as _pickle

        revs = [
            r for r in store.list_revisions(self.name)
            if int(r.split("_", 1)[0]) <= int(upto.split("_", 1)[0])
        ]
        base: bytes | None = None
        target: bytes | None = None
        for r in revs:
            data = store.load(self.name, r)
            if data is None:
                continue
            if _pickle.loads(data)["type"] == "full":
                base, target = data, None
            elif r == upto:
                target = data
        if base is None:
            return []
        return [base] if target is None else [base, target]


def _pattern_timer_batch(t_ms: int) -> EventBatch:
    from siddhi_tpu.core.event import KIND_TIMER
    import jax.numpy as _jnp

    return EventBatch(
        ts=_jnp.asarray([t_ms], dtype=_jnp.int64),
        kind=_jnp.asarray([KIND_TIMER], dtype=_jnp.int8),
        valid=_jnp.asarray([True]),
        cols={},
    )


class _PlaybackInputHandler:
    """Advances the playback clock to each event's timestamp before dispatch
    (reference: EventTimeBasedMillisTimestampGenerator wiring)."""

    def __init__(self, inner: InputHandler, clock):
        self._inner = inner
        self._pb = clock

    def send(self, data, timestamp=None):
        if timestamp is not None:
            self._pb.advance(timestamp)
        self._inner.send(data, timestamp)

    def send_many(self, rows, timestamps=None):
        if timestamps:
            self._pb.advance(max(timestamps))
        self._inner.send_many(rows, timestamps)

    def send_columns(self, timestamps, cols, now=None):
        import numpy as np

        if len(timestamps):
            self._pb.advance(int(np.max(timestamps)))
        self._inner.send_columns(timestamps, cols, now)


class _WatermarkInputHandler:
    """The @app:watermark bounded reorder stage (core/watermark.py): every
    send buffers into the stream's ReorderTracker, which re-emits rows at
    or below the watermark as ONE stably-sorted columnar send through the
    inner handler chain — so the fused/pipelined/sharded paths downstream
    always see ordered input — then drives the app watermark clock."""

    def __init__(self, wm, stream_id: str, inner, attr_names) -> None:
        self._wm = wm
        self._attrs = list(attr_names)
        self._tracker = wm.tracker(
            stream_id,
            deliver=lambda ts, cols, _h=inner: _h.send_columns(ts, cols),
        )

    def send(self, data, timestamp=None):
        import numpy as np

        if timestamp is None:
            timestamp = self._wm.runtime.clock()
        cols = {k: np.asarray([v]) for k, v in zip(self._attrs, data)}
        self._tracker.offer([int(timestamp)], cols)
        self._wm.advance_clock()

    def send_many(self, rows, timestamps=None):
        import numpy as np

        if not rows:
            return
        if timestamps is None:
            timestamps = [self._wm.runtime.clock()] * len(rows)
        cols = {
            k: np.asarray([r[i] for r in rows])
            for i, k in enumerate(self._attrs)
        }
        self._tracker.offer(timestamps, cols)
        self._wm.advance_clock()

    def send_columns(self, timestamps, cols, now=None):
        self._tracker.offer(timestamps, cols)
        self._wm.advance_clock()


class _DisorderInputHandler:
    """testing/faults `ingest_disorder` transform site: shuffles batch
    timestamps within a seeded jitter budget BEFORE the watermark reorder
    stage sees them (installed by get_input_handler only while a fault
    plan is active)."""

    def __init__(self, inner, key: str) -> None:
        self._inner = inner
        self._key = key

    def send(self, data, timestamp=None):
        self._inner.send(data, timestamp)

    def send_many(self, rows, timestamps=None):
        from siddhi_tpu.testing import faults

        if timestamps:
            perm = faults.permutation("ingest_disorder", self._key, timestamps)
            if perm is not None:
                rows = [rows[i] for i in perm]
                timestamps = [timestamps[i] for i in perm]
        self._inner.send_many(rows, timestamps)

    def send_columns(self, timestamps, cols, now=None):
        import numpy as np

        from siddhi_tpu.testing import faults

        perm = faults.permutation(
            "ingest_disorder", self._key, [int(t) for t in timestamps]
        )
        if perm is not None:
            idx = np.asarray(perm)
            timestamps = np.asarray(timestamps)[idx]
            cols = {k: np.asarray(v)[idx] for k, v in cols.items()}
        self._inner.send_columns(timestamps, cols, now)


def _make_insert_transform(output_events: OutputEventsFor):
    @jax.jit
    def t(batch: EventBatch) -> EventBatch:
        if output_events is OutputEventsFor.CURRENT:
            keep = batch.kind == KIND_CURRENT
        elif output_events is OutputEventsFor.EXPIRED:
            keep = batch.kind == KIND_EXPIRED
        else:
            keep = jnp.ones_like(batch.valid)
        return EventBatch(
            ts=batch.ts,
            kind=jnp.zeros_like(batch.kind),  # inserted events become CURRENT
            valid=batch.valid & keep,
            cols=batch.cols,
        )

    return t


def _make_rename(src: StreamSchema, dst: StreamSchema):
    """Map selector output column names onto the target stream's attribute names
    (positional, like the reference's insert-into meta mapping)."""
    if src.attr_names == dst.attr_names:
        return lambda b: b
    dst_names = dst.attr_names

    def rename(b: EventBatch) -> EventBatch:
        cols = dict(zip(dst_names, b.cols.values()))
        return EventBatch(b.ts, b.kind, b.valid, cols)

    return rename
