"""Selector compilation: projection + aggregation + having (+ group-by in M5).

Reference: query/selector/QuerySelector.java:44-430 — attribute processors over
each event, aggregator state mutation, having filter, then output. Here the
whole selector is one vectorized transform over the Flow; aggregator calls inside
selection expressions are lifted out, computed as running columns, and re-injected
as synthetic attributes of a pseudo-stream "__agg__".
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from siddhi_tpu.core.aggregators import CompiledAggregator, FlowInfo, build_aggregator
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import EventBatch, KIND_CURRENT, KIND_EXPIRED
from siddhi_tpu.core.executor import (
    CompiledExpr,
    Env,
    Scope,
    compile_expression,
    is_aggregator,
)
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.execution import OutputAttribute, Selector
from siddhi_tpu.query_api.expression import AttributeFunction, Expression, Variable

_AGG_REF = "__agg__"


def _lift_aggregators(expr: Expression, found: list[AttributeFunction]) -> Expression:
    """Replace aggregator calls with Variables into the __agg__ pseudo-stream."""
    if is_aggregator(expr):
        found.append(expr)
        return Variable(f"a{len(found) - 1}", stream_id=_AGG_REF)
    if dataclasses.is_dataclass(expr):
        kwargs = {}
        changed = False
        for f in dataclasses.fields(expr):
            v = getattr(expr, f.name)
            if isinstance(v, Expression):
                nv = _lift_aggregators(v, found)
                changed |= nv is not v
                kwargs[f.name] = nv
            elif isinstance(v, list) and v and isinstance(v[0], Expression):
                nv = [_lift_aggregators(x, found) for x in v]
                changed |= any(a is not b for a, b in zip(nv, v))
                kwargs[f.name] = nv
            else:
                kwargs[f.name] = v
        if changed:
            return type(expr)(**kwargs)
    return expr


class CompiledSelector:
    """Stateful selector stage: (state, Flow) -> (state, output EventBatch)."""

    def __init__(
        self,
        selector: Selector,
        scope: Scope,
        input_attrs: list[tuple[str, AttrType]] | None = None,
    ):
        self.selector = selector
        sel_list = list(selector.selection_list)
        if selector.select_all:
            if input_attrs is None:
                raise SiddhiAppCreationError("select * unsupported for this input")
            sel_list = [OutputAttribute(None, Variable(n)) for n, _ in input_attrs]

        # lift aggregator calls out of the selection expressions
        agg_calls: list[AttributeFunction] = []
        lifted = [(oa.name, _lift_aggregators(oa.expression, agg_calls)) for oa in sel_list]
        self.aggregators: list[CompiledAggregator] = []
        agg_types: dict[str, AttrType] = {}
        for i, call in enumerate(agg_calls):
            args = [compile_expression(p, scope) for p in call.parameters]
            agg = build_aggregator(call.name, args)
            self.aggregators.append(agg)
            agg_types[f"a{i}"] = agg.type

        inner = scope.child()
        inner.add_stream(_AGG_REF, agg_types)
        if inner.default_ref == _AGG_REF:
            inner.default_ref = scope.default_ref

        self.projections: list[tuple[str, CompiledExpr]] = []
        names = set()
        for name, expr in lifted:
            if name in names:
                raise SiddhiAppCreationError(f"duplicate output attribute '{name}'")
            names.add(name)
            self.projections.append((name, compile_expression(expr, inner)))

        self.out_attrs: list[tuple[str, AttrType]] = [
            (n, c.type) for n, c in self.projections
        ]

        # having can reference output attrs (by name) or input attrs
        # (reference: QuerySelector having executor compiled over output meta)
        self.having = None
        if selector.having is not None:
            hav_scope = inner.child()
            hav_scope.add_stream("__out__", dict(self.out_attrs))
            hav_scope.default_ref = scope.default_ref
            lifted_h = _lift_aggregators(selector.having, agg_calls)
            if len(agg_calls) > len(self.aggregators):
                for i in range(len(self.aggregators), len(agg_calls)):
                    call = agg_calls[i]
                    args = [compile_expression(p, scope) for p in call.parameters]
                    agg = build_aggregator(call.name, args)
                    self.aggregators.append(agg)
                    agg_types[f"a{i}"] = agg.type
                inner.add_stream(_AGG_REF, agg_types)  # refresh
            self.having = compile_expression(lifted_h, hav_scope)
            if self.having.type is not AttrType.BOOL:
                raise SiddhiAppCreationError("having must be a boolean expression")

    def init_state(self):
        return [a.init() for a in self.aggregators]

    def apply(self, state, flow: Flow):
        env = flow.env()
        info = FlowInfo(
            sign=flow.sign,
            active=flow.current,
            reset=flow.reset,
            member=flow.member,
            member_env=flow.member_env,
        )
        new_state = []
        agg_cols: dict = {}
        for i, agg in enumerate(self.aggregators):
            s, col = agg.apply(state[i], info, env)
            new_state.append(s)
            agg_cols[(_AGG_REF, None, f"a{i}")] = col
        env2 = Env({**env.columns, **agg_cols}, now=flow.now)

        out_cols = {}
        out_col_keys = {}
        for name, cexpr in self.projections:
            col = cexpr(env2)
            col = jnp.broadcast_to(col, flow.batch.valid.shape)
            out_cols[name] = col
            out_col_keys[("__out__", None, name)] = col

        valid = flow.batch.valid & (
            (flow.batch.kind == KIND_CURRENT) | (flow.batch.kind == KIND_EXPIRED)
        )
        if self.having is not None:
            env3 = Env({**env2.columns, **out_col_keys}, now=flow.now)
            valid = valid & self.having(env3)

        out = EventBatch(
            ts=flow.batch.ts, kind=flow.batch.kind, valid=valid, cols=out_cols
        )
        return new_state, out
