"""Selector compilation: projection + aggregation + group-by + having +
order-by/limit/offset.

Reference: query/selector/QuerySelector.java:44-430 — attribute processors over
each event, aggregator state mutation, group-by key via GroupByKeyGenerator,
having filter, order-by/limit (OrderByEventComparator), then output. Here the
whole selector is one vectorized transform over the Flow; aggregator calls inside
selection expressions are lifted out, computed as running columns, and re-injected
as synthetic attributes of a pseudo-stream "__agg__".
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from siddhi_tpu.core.aggregators import CompiledAggregator, FlowInfo, build_aggregator
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import EventBatch, KIND_CURRENT, KIND_EXPIRED
from siddhi_tpu.core.executor import (
    CompiledExpr,
    Env,
    Scope,
    compile_expression,
    is_aggregator,
)
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.groupby import CompiledGroupBy
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.ops.group import keep_last_in_sorted, keep_last_per_group
from siddhi_tpu.query_api.execution import OutputAttribute, Selector
from siddhi_tpu.query_api.expression import AttributeFunction, Expression, Variable

_AGG_REF = "__agg__"
_BIG = jnp.iinfo(jnp.int32).max


def _lift_aggregators(expr: Expression, found: list[AttributeFunction]) -> Expression:
    """Replace aggregator calls with Variables into the __agg__ pseudo-stream."""
    if is_aggregator(expr):
        found.append(expr)
        return Variable(f"a{len(found) - 1}", stream_id=_AGG_REF)
    if dataclasses.is_dataclass(expr):
        kwargs = {}
        changed = False
        for f in dataclasses.fields(expr):
            v = getattr(expr, f.name)
            if isinstance(v, Expression):
                nv = _lift_aggregators(v, found)
                changed |= nv is not v
                kwargs[f.name] = nv
            elif isinstance(v, list) and v and isinstance(v[0], Expression):
                nv = [_lift_aggregators(x, found) for x in v]
                changed |= any(a is not b for a, b in zip(nv, v))
                kwargs[f.name] = nv
            else:
                kwargs[f.name] = v
        if changed:
            return type(expr)(**kwargs)
    return expr


class CompiledSelector:
    """Stateful selector stage: (state, Flow) -> (state, output EventBatch)."""

    def __init__(
        self,
        selector: Selector,
        scope: Scope,
        input_attrs: list[tuple[str, AttrType]] | None = None,
        batch_mode: bool = False,
        group_capacity: int | None = None,
    ):
        self.selector = selector
        self.batch_mode = batch_mode
        sel_list = list(selector.selection_list)
        if selector.select_all:
            if input_attrs is None:
                raise SiddhiAppCreationError("select * unsupported for this input")
            sel_list = [OutputAttribute(None, Variable(n)) for n, _ in input_attrs]

        # group-by (reference: GroupByKeyGenerator over the input meta)
        self.group: CompiledGroupBy | None = None
        if selector.group_by:
            if group_capacity is not None:
                self.group = CompiledGroupBy(
                    selector.group_by, scope, capacity=group_capacity
                )
            else:
                self.group = CompiledGroupBy(selector.group_by, scope)

        # lift aggregator calls out of the selection expressions
        agg_calls: list[AttributeFunction] = []
        lifted = [(oa.name, _lift_aggregators(oa.expression, agg_calls)) for oa in sel_list]
        self.aggregators: list[CompiledAggregator] = []
        agg_types: dict[str, AttrType] = {}
        for i, call in enumerate(agg_calls):
            args = [compile_expression(p, scope) for p in call.parameters]
            agg = build_aggregator(call.name, args, group=self.group)
            self.aggregators.append(agg)
            agg_types[f"a{i}"] = agg.type

        inner = scope.child()
        inner.add_stream(_AGG_REF, agg_types)
        if inner.default_ref == _AGG_REF:
            inner.default_ref = scope.default_ref

        self.projections: list[tuple[str, CompiledExpr]] = []
        names = set()
        for name, expr in lifted:
            if name in names:
                raise SiddhiAppCreationError(f"duplicate output attribute '{name}'")
            names.add(name)
            self.projections.append((name, compile_expression(expr, inner)))

        self.out_attrs: list[tuple[str, AttrType]] = [
            (n, c.type) for n, c in self.projections
        ]

        # having can reference output attrs (by name) or input attrs
        # (reference: QuerySelector having executor compiled over output meta)
        self.having = None
        if selector.having is not None:
            hav_scope = inner.child()
            hav_scope.add_stream("__out__", dict(self.out_attrs))
            hav_scope.default_ref = scope.default_ref
            lifted_h = _lift_aggregators(selector.having, agg_calls)
            if len(agg_calls) > len(self.aggregators):
                for i in range(len(self.aggregators), len(agg_calls)):
                    call = agg_calls[i]
                    args = [compile_expression(p, scope) for p in call.parameters]
                    agg = build_aggregator(call.name, args, group=self.group)
                    self.aggregators.append(agg)
                    agg_types[f"a{i}"] = agg.type
                inner.add_stream(_AGG_REF, agg_types)  # refresh
            self.having = compile_expression(lifted_h, hav_scope)
            if self.having.type is not AttrType.BOOL:
                raise SiddhiAppCreationError("having must be a boolean expression")

        # order-by: keys resolve against output attrs first, then input streams
        # (reference: OrderByEventComparator over output stream attributes)
        self.order_by: list[tuple[CompiledExpr, bool]] = []
        for ob in selector.order_by:
            var = ob.variable
            out_names = dict(self.out_attrs)
            if var.stream_id is None and var.attribute in out_names:
                cexpr = compile_expression(
                    Variable(var.attribute, stream_id="__out__"), _out_scope(inner, self.out_attrs)
                )
            else:
                cexpr = compile_expression(var, scope)
            if cexpr.type in (AttrType.STRING, AttrType.OBJECT):
                raise SiddhiAppCreationError(
                    "order by on STRING/OBJECT attributes is not supported yet "
                    "(interned ids are not lexicographic)"
                )
            self.order_by.append((cexpr, ob.order.name == "DESC"))
        self.limit = selector.limit
        self.offset = selector.offset

    def init_state(self):
        st = {"aggs": [a.init() for a in self.aggregators]}
        if self.group is not None:
            st["group"] = self.group.init_state()
        return st

    def apply(self, state, flow: Flow):
        env = flow.env()
        keyed_rows = flow.sign != 0
        group_state = state.get("group")
        ctx = None
        if self.group is not None:
            group_state, ctx = self.group.assign(
                group_state, env, keyed_rows, reset=flow.reset
            )
            # surfaced to the host, which warns on slot-table exhaustion
            flow.aux["groupby_overflow"] = ctx.overflow
        info = FlowInfo(
            sign=flow.sign,
            active=flow.current,
            reset=flow.reset,
            member=flow.member,
            member_env=flow.member_env,
            group=ctx,
        )
        new_aggs = []
        agg_cols: dict = {}
        for i, agg in enumerate(self.aggregators):
            s, col = agg.apply(state["aggs"][i], info, env)
            new_aggs.append(s)
            agg_cols[(_AGG_REF, None, f"a{i}")] = col
        env2 = Env({**env.columns, **agg_cols}, now=flow.now, tables=env.tables)

        out_cols = {}
        out_col_keys = {}
        for name, cexpr in self.projections:
            col = cexpr(env2)
            col = jnp.broadcast_to(col, flow.batch.valid.shape)
            out_cols[name] = col
            out_col_keys[("__out__", None, name)] = col

        valid = flow.batch.valid & (
            (flow.batch.kind == KIND_CURRENT) | (flow.batch.kind == KIND_EXPIRED)
        )
        env3 = Env({**env2.columns, **out_col_keys}, now=flow.now, tables=env.tables)
        if self.having is not None:
            valid = valid & self.having(env3)

        # batch-mode group-by: one output per key per flush bucket — the last
        # *having-passing* event of each (kind, bucket, key) survives
        # (reference: QuerySelector.processInBatchGroupBy checks having BEFORE
        # groupedEvents.put, so having order matches; the reference's map is
        # kind-agnostic per chunk — we key by (kind, bucket), which only
        # diverges for `output all events` where a bucket's CURRENT would
        # shadow the previous bucket's EXPIRED of the same key)
        if self.batch_mode and ctx is not None:
            # the (reset-era, key) segments of the group-by's sorted view are
            # exactly the (bucket, key) groups — collapse inside it instead of
            # re-lexsorting (ops/group.py:keep_last_in_sorted)
            valid = keep_last_in_sorted(ctx.sorted, flow.batch.kind, valid)
        elif self.batch_mode and self.aggregators:
            # batch + aggregators + no group-by: only the LAST allowed-kind
            # event of each flush chunk survives, carrying the final running
            # aggregate (reference: QuerySelector.processInBatchNoGroupBy —
            # lastEvent spans kinds, restricted by currentOn/expiredOn)
            from siddhi_tpu.query_api.execution import OutputEventsFor

            # a flush CHUNK is [prev-bucket EXPIREDs, RESET, bucket CURRENTs]:
            # expireds precede their reset, so they shift one segment forward
            # to land with their flush's currents
            kind = flow.batch.kind
            seg = jnp.cumsum(flow.reset.astype(jnp.int32)) + (
                kind == KIND_EXPIRED
            ).astype(jnp.int32)
            want = getattr(self, "output_events_for_batch", None)
            if want is OutputEventsFor.EXPIRED:
                allowed = valid & (kind == KIND_EXPIRED)
            elif want is OutputEventsFor.ALL:
                allowed = valid
            else:  # CURRENT (the reference default)
                allowed = valid & (kind == KIND_CURRENT)
            valid = keep_last_per_group([seg], allowed)

        # per-group rate limiters need each row's group key beside it
        # (reference: GroupByKeyGenerator key threading into rate limiters)
        if getattr(self, "emit_group_key", False) and ctx is not None:
            out_cols["__group_key__"] = jnp.broadcast_to(
                ctx.key, flow.batch.valid.shape
            )

        out = EventBatch(
            ts=flow.batch.ts, kind=flow.batch.kind, valid=valid, cols=out_cols
        )
        out = self._order_limit(out, env3)
        new_state = {"aggs": new_aggs}
        if self.group is not None:
            new_state["group"] = group_state
        return new_state, out

    def _order_limit(self, out: EventBatch, env: Env) -> EventBatch:
        """Per-chunk order-by + offset/limit (reference: QuerySelector
        orderEventChunk/limitEventChunk)."""
        if not self.order_by and self.limit is None and self.offset is None:
            return out
        if self.order_by:
            keys = []
            for cexpr, desc in self.order_by:
                col = cexpr(env)
                col = jnp.broadcast_to(col, out.valid.shape)
                if desc:
                    col = -col.astype(jnp.float32) if col.dtype == jnp.bool_ else -col
                keys.append(col)
            # primary = validity (valid rows first), then keys in order;
            # jnp.lexsort treats the LAST key as primary
            perm = jnp.lexsort(tuple(reversed(keys)) + (~out.valid,)).astype(jnp.int32)
            out = EventBatch(
                ts=out.ts[perm],
                kind=out.kind[perm],
                valid=out.valid[perm],
                cols={n: c[perm] for n, c in out.cols.items()},
            )
        if self.limit is not None or self.offset is not None:
            rank = jnp.cumsum(out.valid.astype(jnp.int32)) - out.valid.astype(jnp.int32)
            lo = 0 if self.offset is None else int(self.offset)
            hi = _BIG if self.limit is None else lo + int(self.limit)
            out = EventBatch(
                ts=out.ts,
                kind=out.kind,
                valid=out.valid & (rank >= lo) & (rank < hi),
                cols=out.cols,
            )
        return out


def _out_scope(parent: Scope, out_attrs):
    s = parent.child()
    s.add_stream("__out__", dict(out_attrs))
    return s
