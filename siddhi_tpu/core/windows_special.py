"""Sort, frequent, and lossyFrequent windows — per-event scan kernels.

Reference: query/processor/stream/window/SortWindowProcessor.java:145-173
(keep N smallest per comparator, evict the greatest as EXPIRED),
FrequentWindowProcessor.java:106-160 (Misra-Gries top-N counting),
LossyFrequentWindowProcessor.java:139-200 (lossy counting with
support/error bounds).

These windows have per-event sequential semantics (each arrival can evict a
data-dependent victim), so the device program is a `lax.scan` over the batch
rows carrying the buffer state, with emissions accumulated into a
fixed-capacity output buffer — the same shape the NFA engine uses.
"""

from __future__ import annotations

import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_EXPIRED,
    KIND_RESET,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.core.windows import WindowStage
from siddhi_tpu.ops.group import mix_keys


# ---------------------------------------------------------------------------
# shared: fixed-capacity emission accumulator
# ---------------------------------------------------------------------------


def _out_init(cap: int, schema: StreamSchema):
    empty = schema.empty_batch(cap)
    return {
        "ts": empty.ts,
        "kind": empty.kind,
        "valid": empty.valid,
        "cols": empty.cols,
    }


def _out_append(out, n, ovf, cols, ts, kind, flag, cap):
    """Append one row when `flag`; silently drops (sets ovf) past capacity."""
    pos = jnp.where(flag & (n < cap), n, cap)  # cap == out-of-bounds: dropped
    new = {
        "ts": out["ts"].at[pos].set(ts, mode="drop"),
        "kind": out["kind"].at[pos].set(np.int8(kind), mode="drop"),
        "valid": out["valid"].at[pos].set(True, mode="drop"),
        "cols": {
            k: out["cols"][k].at[pos].set(v.astype(out["cols"][k].dtype), mode="drop")
            for k, v in cols.items()
        },
    }
    return (
        new,
        (n + (flag & (n < cap)).astype(jnp.int32)).astype(jnp.int32),
        ovf | (flag & (n >= cap)),
    )


def _out_append_many(out, n, ovf, cols, ts, kind, flags, cap):
    """Append every flagged row (vectorized compaction into the buffer)."""
    flags_i = flags.astype(jnp.int32)
    rank = jnp.cumsum(flags_i) - flags_i
    pos = jnp.where(flags & (n + rank < cap), n + rank, cap)
    ts_b = jnp.broadcast_to(ts, flags.shape)
    new = {
        "ts": out["ts"].at[pos].set(ts_b, mode="drop"),
        "kind": out["kind"].at[pos].set(np.int8(kind), mode="drop"),
        "valid": out["valid"].at[pos].set(True, mode="drop"),
        "cols": {
            k: out["cols"][k].at[pos].set(v.astype(out["cols"][k].dtype), mode="drop")
            for k, v in cols.items()
        },
    }
    total = flags_i.sum()
    return (
        new,
        jnp.minimum(n + total, cap).astype(jnp.int32),
        ovf | ((n + total) > cap),
    )


def _out_flow(out, flow: Flow, aux) -> Flow:
    batch = EventBatch(ts=out["ts"], kind=out["kind"], valid=out["valid"], cols=out["cols"])
    return Flow(
        batch=batch, ref=flow.ref, now=flow.now, extra_cols={},
        aux=aux, tables=flow.tables,
    )


def _key_col(cols, ts, attrs, key_attrs):
    """int64 group key from the chosen attributes (all attrs when none given),
    like the reference's string-concat key (FrequentWindowProcessor.generateKey)."""
    names = key_attrs if key_attrs else [n for n, _ in attrs]
    parts = []
    types = dict(attrs)
    for n in names:
        c = cols[n]
        if types[n] in (AttrType.FLOAT, AttrType.DOUBLE):
            c = jnp.asarray(c).view(jnp.int32).astype(jnp.int64)
        parts.append(jnp.asarray(c).astype(jnp.int64))
    return mix_keys(parts)


# ---------------------------------------------------------------------------
# sort window
# ---------------------------------------------------------------------------


class SortWindow(WindowStage):
    """#window.sort(N, attr asc|desc, ...) — retains the N least events per the
    comparator; each overflow evicts the greatest (ties: most recent)."""

    def __init__(self, schema: StreamSchema, ref: str, n: int, keys: list[tuple[str, bool]]):
        self.schema = schema
        self.ref = ref
        self.n = int(n)
        if not keys:
            raise SiddhiAppCreationError("sort window needs at least one sort attribute")
        for name, _desc in keys:
            if schema.type_of(name) in (AttrType.STRING, AttrType.OBJECT):
                raise SiddhiAppCreationError(
                    "sort window on STRING/OBJECT attributes is not supported "
                    "(interned ids are not lexicographic)"
                )
        self.keys = keys

    def init_state(self):
        w = self.n
        return {
            "cols": {
                n: jnp.zeros((w,), a.dtype)
                for n, a in self.schema.empty_batch(1).cols.items()
            },
            "ts": jnp.zeros((w,), jnp.int64),
            "occ": jnp.zeros((w,), jnp.bool_),
            "seq": jnp.zeros((w,), jnp.int64),
            "next": jnp.zeros((), jnp.int64),
        }

    def _sort_keys(self, cols):
        out = []
        for name, desc in self.keys:
            c = cols[name]
            if c.dtype == jnp.bool_:
                c = c.astype(jnp.int32)
            out.append(-c if desc else c)
        return out

    def apply(self, state, flow: Flow):
        b = flow.batch
        bsz = b.capacity
        w = self.n
        cap = 2 * bsz
        out0 = _out_init(cap, self.schema)

        def body(carry, row):
            st, out, n, ovf = carry
            is_cur = row["valid"] & (row["kind"] == KIND_CURRENT)
            row_cols = {k: row[f"c.{k}"] for k in b.cols}
            # emit the arrival
            out, n, ovf = _out_append(
                out, n, ovf, row_cols, row["ts"], KIND_CURRENT, is_cur, cap
            )
            # candidate set: w slots + the arrival
            cand_cols = {
                k: jnp.concatenate([st["cols"][k], row_cols[k][None]])
                for k in st["cols"]
            }
            cand_ts = jnp.concatenate([st["ts"], row["ts"][None]])
            cand_occ = jnp.concatenate([st["occ"], is_cur[None]])
            cand_seq = jnp.concatenate([st["seq"], st["next"][None]])
            full = st["occ"].all() & is_cur
            # victim: lexicographic max by sort keys, ties -> latest insertion
            skeys = self._sort_keys(cand_cols) + [cand_seq]
            best = np.int32(0)
            for i in range(1, w + 1):
                gt = np.bool_(False)
                eq = np.bool_(True)
                for kcol in skeys:
                    a, bb = kcol[i], kcol[best]
                    gt = gt | (eq & (a > bb))
                    eq = eq & (a == bb)
                # unoccupied candidates never win
                gt = gt & cand_occ[i]
                lose = ~cand_occ[best]
                best = jnp.where(gt | lose, np.int32(i), best)
            # if full: emit the victim as EXPIRED (ts = now) and remove it
            out, n, ovf = _out_append(
                out, n, ovf,
                {k: c[best] for k, c in cand_cols.items()},
                flow.now, KIND_EXPIRED, full, cap,
            )
            keep = cand_occ.at[best].set(
                jnp.where(full, False, cand_occ[best])
            )
            # compact candidates back into w slots: new row takes the victim's
            # slot when full, else the first free slot
            free_slot = jnp.where(
                full,
                jnp.where(best == w, w, best),  # best==w: arrival itself evicted
                jnp.argmax(~st["occ"]),
            ).astype(jnp.int32)
            write = is_cur & (free_slot < w) & keep[w]
            slot = jnp.clip(free_slot, 0, w - 1)
            new_st = {
                "cols": {
                    k: jnp.where(
                        write,
                        st["cols"][k].at[slot].set(row_cols[k].astype(st["cols"][k].dtype)),
                        st["cols"][k],
                    )
                    for k in st["cols"]
                },
                "ts": jnp.where(write, st["ts"].at[slot].set(row["ts"]), st["ts"]),
                "occ": jnp.where(
                    write,
                    keep[:w].at[slot].set(True),
                    keep[:w],
                ),
                "seq": jnp.where(write, st["seq"].at[slot].set(st["next"]), st["seq"]),
                "next": st["next"] + is_cur.astype(jnp.int64),
            }
            return (new_st, out, n, ovf), None

        xs = {
            "ts": b.ts, "kind": b.kind, "valid": b.valid,
            **{f"c.{k}": c for k, c in b.cols.items()},
        }
        (st, out, _n, ovf), _ = lax.scan(
            body, (state, out0, np.int32(0), np.bool_(False)), xs
        )
        aux = dict(flow.aux)
        aux["window_overflow"] = ovf
        return st, _out_flow(out, flow, aux)

    def view(self, state):
        order = jnp.argsort(
            jnp.where(state["occ"], state["seq"], jnp.iinfo(jnp.int64).max)
        ).astype(jnp.int32)
        return (
            {k: c[order] for k, c in state["cols"].items()},
            state["ts"][order],
            state["occ"][order],
        )


# ---------------------------------------------------------------------------
# cron window
# ---------------------------------------------------------------------------


class CronWindow(WindowStage):
    """#window.cron('expr') — collect arrivals; at each cron fire emit the
    previous bucket as EXPIRED (ts = now), a RESET, then the collected bucket
    as CURRENT (reference: CronWindowProcessor.dispatchEvents:173-198). The
    fire times are TIMER rows scheduled host-side from the cron expression."""

    is_batch = True
    needs_scheduler = True

    def __init__(self, schema: StreamSchema, ref: str, cron_expr: str, capacity: int = 256):
        from siddhi_tpu.utils.cron import CronSchedule

        self.schema = schema
        self.ref = ref
        self.w = int(capacity)
        try:
            self.cron_schedule = CronSchedule(cron_expr)
        except ValueError as e:
            raise SiddhiAppCreationError(f"cron window: {e}") from None

    def init_state(self):
        w = self.w
        zero = {
            n: jnp.zeros((w,), a.dtype)
            for n, a in self.schema.empty_batch(1).cols.items()
        }
        return {
            "cur_cols": zero,
            "cur_ts": jnp.zeros((w,), jnp.int64),
            "cur_n": jnp.zeros((), jnp.int32),
            "prev_cols": {n: jnp.zeros_like(a) for n, a in zero.items()},
            "prev_ts": jnp.zeros((w,), jnp.int64),
            "prev_n": jnp.zeros((), jnp.int32),
        }

    def apply(self, state, flow: Flow):
        b = flow.batch
        bsz = b.capacity
        w = self.w
        cap = bsz + 2 * (2 * w + 1)  # room for two flushes per batch
        out0 = _out_init(cap, self.schema)
        slots = jnp.arange(w, dtype=jnp.int32)

        def body(carry, row):
            st, out, n, ovf = carry
            is_cur = row["valid"] & (row["kind"] == KIND_CURRENT)
            is_timer = row["valid"] & (row["kind"] == KIND_TIMER)
            row_cols = {k: row[f"c.{k}"] for k in b.cols}

            # flush on a TIMER fire when the open bucket holds anything
            flush = is_timer & (st["cur_n"] > 0)
            prev_mask = flush & (slots < st["prev_n"])
            out, n, ovf = _out_append_many(
                out, n, ovf, st["prev_cols"], flow.now, KIND_EXPIRED, prev_mask, cap
            )
            out, n, ovf = _out_append(
                out, n, ovf,
                {k: v[0] for k, v in st["prev_cols"].items()},
                flow.now, KIND_RESET, flush, cap,
            )
            cur_mask = flush & (slots < st["cur_n"])
            out2 = out
            # currents keep their original arrival timestamps
            flags_i = cur_mask.astype(jnp.int32)
            rank = jnp.cumsum(flags_i) - flags_i
            pos = jnp.where(cur_mask & (n + rank < cap), n + rank, cap)
            out = {
                "ts": out2["ts"].at[pos].set(st["cur_ts"], mode="drop"),
                "kind": out2["kind"].at[pos].set(np.int8(KIND_CURRENT), mode="drop"),
                "valid": out2["valid"].at[pos].set(True, mode="drop"),
                "cols": {
                    k: out2["cols"][k].at[pos].set(st["cur_cols"][k], mode="drop")
                    for k in out2["cols"]
                },
            }
            total = flags_i.sum()
            ovf = ovf | ((n + total) > cap)
            n = jnp.minimum(n + total, cap).astype(jnp.int32)

            st_flushed = {
                "cur_cols": {k: jnp.zeros_like(v) for k, v in st["cur_cols"].items()},
                "cur_ts": jnp.zeros_like(st["cur_ts"]),
                "cur_n": jnp.zeros_like(st["cur_n"]),
                "prev_cols": st["cur_cols"],
                "prev_ts": st["cur_ts"],
                "prev_n": st["cur_n"],
            }
            st1 = {
                k: (
                    {kk: jnp.where(flush, st_flushed[k][kk], st[k][kk]) for kk in st[k]}
                    if isinstance(st[k], dict)
                    else jnp.where(flush, st_flushed[k], st[k])
                )
                for k in st
            }

            # append the arrival into the open bucket
            slot = jnp.clip(st1["cur_n"], 0, w - 1)
            can = is_cur & (st1["cur_n"] < w)
            ovf = ovf | (is_cur & (st1["cur_n"] >= w))
            st2 = {
                "cur_cols": {
                    k: jnp.where(
                        can,
                        st1["cur_cols"][k].at[slot].set(row_cols[k].astype(st1["cur_cols"][k].dtype)),
                        st1["cur_cols"][k],
                    )
                    for k in st1["cur_cols"]
                },
                "cur_ts": jnp.where(can, st1["cur_ts"].at[slot].set(row["ts"]), st1["cur_ts"]),
                "cur_n": st1["cur_n"] + can.astype(jnp.int32),
                "prev_cols": st1["prev_cols"],
                "prev_ts": st1["prev_ts"],
                "prev_n": st1["prev_n"],
            }
            return (st2, out, n, ovf), None

        xs = {
            "ts": b.ts, "kind": b.kind, "valid": b.valid,
            **{f"c.{k}": c for k, c in b.cols.items()},
        }
        (st, out, _n, ovf), _ = lax.scan(
            body, (state, out0, np.int32(0), np.bool_(False)), xs
        )
        aux = dict(flow.aux)
        aux["window_overflow"] = ovf
        return st, _out_flow(out, flow, aux)

    def view(self, state):
        mask = jnp.arange(self.w, dtype=jnp.int32) < state["cur_n"]
        return dict(state["cur_cols"]), state["cur_ts"], mask


# ---------------------------------------------------------------------------
# frequent window (Misra-Gries)
# ---------------------------------------------------------------------------


class FrequentWindow(WindowStage):
    """#window.frequent(N [, attrs...]) — retains the latest event per key for
    the N most frequent keys."""

    def __init__(self, schema: StreamSchema, ref: str, n: int, key_attrs: list[str]):
        self.schema = schema
        self.ref = ref
        self.n = int(n)
        self.key_attrs = key_attrs

    def init_state(self):
        w = self.n
        return {
            "cols": {
                n: jnp.zeros((w,), a.dtype)
                for n, a in self.schema.empty_batch(1).cols.items()
            },
            "ts": jnp.zeros((w,), jnp.int64),
            "occ": jnp.zeros((w,), jnp.bool_),
            "key": jnp.zeros((w,), jnp.int64),
            "cnt": jnp.zeros((w,), jnp.int32),
        }

    def apply(self, state, flow: Flow):
        b = flow.batch
        bsz = b.capacity
        w = self.n
        cap = 2 * bsz + w
        out0 = _out_init(cap, self.schema)

        def body(carry, row):
            st, out, n, ovf = carry
            is_cur = row["valid"] & (row["kind"] == KIND_CURRENT)
            row_cols = {k: row[f"c.{k}"] for k in b.cols}
            key = _key_col(
                {k: v[None] for k, v in row_cols.items()},
                row["ts"][None], self.schema.attrs, self.key_attrs,
            )[0]
            hit = st["occ"] & (st["key"] == key)
            exists = hit.any() & is_cur
            slot_hit = jnp.argmax(hit).astype(jnp.int32)
            has_free = (~st["occ"]).any()
            new_key = is_cur & ~exists

            # new key with the table full: decrement ALL counts; zeros evict
            decr = new_key & ~has_free
            cnt1 = jnp.where(decr & st["occ"], st["cnt"] - 1, st["cnt"])
            evict = decr & st["occ"] & (cnt1 == 0)
            # emit evictions as EXPIRED (ts = now), in slot order
            out, n, ovf = _out_append_many(
                out, n, ovf, st["cols"], flow.now, KIND_EXPIRED, evict, cap
            )
            occ1 = st["occ"] & ~evict
            free_after = (~occ1).any()
            insert = new_key & free_after  # fresh key takes a freed/free slot
            slot_free = jnp.argmax(~occ1).astype(jnp.int32)
            slot = jnp.where(exists, slot_hit, slot_free)
            write = exists | insert
            passed = exists | insert  # dropped new keys do NOT flow downstream
            out, n, ovf = _out_append(
                out, n, ovf, row_cols, row["ts"], KIND_CURRENT, passed, cap
            )
            slot_c = jnp.clip(slot, 0, w - 1)
            new_st = {
                "cols": {
                    k: jnp.where(
                        write,
                        st["cols"][k].at[slot_c].set(row_cols[k].astype(st["cols"][k].dtype)),
                        st["cols"][k],
                    )
                    for k in st["cols"]
                },
                "ts": jnp.where(write, st["ts"].at[slot_c].set(row["ts"]), st["ts"]),
                "occ": jnp.where(write, occ1.at[slot_c].set(True), occ1),
                "key": jnp.where(write, st["key"].at[slot_c].set(key), st["key"]),
                "cnt": jnp.where(
                    exists,
                    cnt1.at[slot_c].add(1),
                    jnp.where(insert, cnt1.at[slot_c].set(1), cnt1),
                ),
            }
            return (new_st, out, n, ovf), None

        xs = {
            "ts": b.ts, "kind": b.kind, "valid": b.valid,
            **{f"c.{k}": c for k, c in b.cols.items()},
        }
        (st, out, _n, ovf), _ = lax.scan(
            body, (state, out0, np.int32(0), np.bool_(False)), xs
        )
        aux = dict(flow.aux)
        aux["window_overflow"] = ovf
        return st, _out_flow(out, flow, aux)

    def view(self, state):
        return dict(state["cols"]), state["ts"], state["occ"]


# ---------------------------------------------------------------------------
# lossyFrequent window (lossy counting)
# ---------------------------------------------------------------------------


class LossyFrequentWindow(WindowStage):
    """#window.lossyFrequent(supportThreshold, errorBound [, attrs...])."""

    def __init__(
        self,
        schema: StreamSchema,
        ref: str,
        support: float,
        error: float,
        key_attrs: list[str],
    ):
        self.schema = schema
        self.ref = ref
        self.support = float(support)
        self.error = float(error)
        if not (0 < self.error < 1) or not (0 < self.support < 1):
            raise SiddhiAppCreationError(
                "lossyFrequent support/error must be in (0, 1)"
            )
        self.width = max(1, int(1.0 / self.error + 0.9999999))
        # lossy counting keeps O((1/e)·log(eN)) keys; 4/e is ample in practice
        self.cap_keys = max(64, int(4.0 / self.error))
        self.key_attrs = key_attrs

    def init_state(self):
        c = self.cap_keys
        return {
            "cols": {
                n: jnp.zeros((c,), a.dtype)
                for n, a in self.schema.empty_batch(1).cols.items()
            },
            "ts": jnp.zeros((c,), jnp.int64),
            "occ": jnp.zeros((c,), jnp.bool_),
            "key": jnp.zeros((c,), jnp.int64),
            "cnt": jnp.zeros((c,), jnp.int64),
            "bucket": jnp.zeros((c,), jnp.int64),
            "total": jnp.zeros((), jnp.int64),
        }

    def apply(self, state, flow: Flow):
        b = flow.batch
        bsz = b.capacity
        c = self.cap_keys
        # worst case per batch: B currents + all keys pruned once
        cap = bsz + c
        out0 = _out_init(cap, self.schema)
        width = self.width

        def body(carry, row):
            st, out, n, ovf = carry
            is_cur = row["valid"] & (row["kind"] == KIND_CURRENT)
            row_cols = {k: row[f"c.{k}"] for k in b.cols}
            key = _key_col(
                {k: v[None] for k, v in row_cols.items()},
                row["ts"][None], self.schema.attrs, self.key_attrs,
            )[0]
            total = st["total"] + is_cur.astype(jnp.int64)
            cur_bucket = jnp.where(
                total <= 1, np.int64(1), (total + width - 1) // width
            )
            hit = st["occ"] & (st["key"] == key)
            exists = hit.any() & is_cur
            slot_hit = jnp.argmax(hit).astype(jnp.int32)
            slot_free = jnp.argmax(~st["occ"]).astype(jnp.int32)
            has_free = (~st["occ"]).any()
            insert = is_cur & ~exists & has_free
            ovf = ovf | (is_cur & ~exists & ~has_free)
            write = exists | insert
            slot = jnp.clip(jnp.where(exists, slot_hit, slot_free), 0, c - 1)
            cnt = jnp.where(
                exists,
                st["cnt"].at[slot].add(1),
                jnp.where(insert, st["cnt"].at[slot].set(1), st["cnt"]),
            )
            bucket = jnp.where(
                insert, st["bucket"].at[slot].set(cur_bucket - 1), st["bucket"]
            )
            occ = jnp.where(write, st["occ"].at[slot].set(True), st["occ"])
            cols = {
                k: jnp.where(
                    write,
                    st["cols"][k].at[slot].set(row_cols[k].astype(st["cols"][k].dtype)),
                    st["cols"][k],
                )
                for k in st["cols"]
            }
            ts = jnp.where(write, st["ts"].at[slot].set(row["ts"]), st["ts"])
            # the arrival flows downstream iff its key meets (s - e) * total
            # (reference: LossyFrequentWindowProcessor.java:172-180)
            my_cnt = cnt[slot]
            passed = is_cur & write & (
                my_cnt.astype(jnp.float32)
                >= (self.support - self.error) * total.astype(jnp.float32)
            )
            out, n, ovf = _out_append(
                out, n, ovf, row_cols, row["ts"], KIND_CURRENT, passed, cap
            )
            # prune at bucket boundaries: cnt + bucket <= current bucket
            prune_now = is_cur & (total % width == 0)
            doomed = prune_now & occ & (cnt + bucket <= cur_bucket)
            out, n, ovf = _out_append_many(
                out, n, ovf, cols, flow.now, KIND_EXPIRED, doomed, cap
            )
            occ = occ & ~doomed
            new_st = {
                "cols": cols, "ts": ts, "occ": occ, "key":
                jnp.where(write, st["key"].at[slot].set(key), st["key"]),
                "cnt": cnt, "bucket": bucket, "total": total,
            }
            return (new_st, out, n, ovf), None

        xs = {
            "ts": b.ts, "kind": b.kind, "valid": b.valid,
            **{f"c.{k}": c2 for k, c2 in b.cols.items()},
        }
        (st, out, _n, ovf), _ = lax.scan(
            body, (state, out0, np.int32(0), np.bool_(False)), xs
        )
        aux = dict(flow.aux)
        aux["window_overflow"] = ovf
        return st, _out_flow(out, flow, aux)

    def view(self, state):
        return dict(state["cols"]), state["ts"], state["occ"]
