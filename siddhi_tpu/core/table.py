"""Device-resident columnar tables.

Reference: core/table/InMemoryTable.java:55-220 + table/holder/IndexEventHolder.java
— list/indexed/primary-key event holders with CRUD under compiled conditions, and
util/collection/ (CollectionExecutors/Operators) — the lookup planner.

TPU-native design: a table is a fixed-capacity columnar arena on device
(`cols/ts/valid/seq` lanes). Lookups are dense masked [B, C] condition
evaluations (one fused XLA kernel — the MXU-friendly analog of the reference's
per-event holder scans); the primary-key "index" is the same dense compare used
for overwrite-on-conflict semantics rather than a host hash map, so every CRUD
op stays inside the jitted query step. Sequential update semantics (later
events in a chunk see earlier events' writes, as in the reference's per-event
loop) are kept via a `lax.scan` over the probe batch.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.ops.prefix import first_indices
from siddhi_tpu.core.event import EventBatch, KIND_CURRENT, StreamSchema
from siddhi_tpu.core.executor import (
    CompiledExpr,
    Env,
    Scope,
    TS_ATTR,
    compile_expression,
)
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.annotation import find_all, find_annotation
from siddhi_tpu.query_api.definition import TableDefinition
from siddhi_tpu.query_api.execution import UpdateSetAttribute

DEFAULT_TABLE_CAPACITY = 4096


class InMemoryTable:
    """Host handle for one table: schema + device state + compiled-op builders.

    State pytree:
      cols:  {attr: [C] array}
      ts:    [C] int64   insertion timestamps
      valid: [C] bool    row occupancy
      seq:   [C] int64   insertion order (stable find/iteration order)
      next:  scalar int64 next sequence number
    """

    def __init__(
        self,
        definition: TableDefinition,
        interner,
        capacity: int = DEFAULT_TABLE_CAPACITY,
    ):
        self.definition = definition
        self.table_id = definition.id
        self.schema = StreamSchema(
            definition.id, [(a.name, a.type) for a in definition.attributes]
        )
        self.interner = interner
        cap_ann = find_annotation(definition.annotations, "capacity")
        self.capacity = (
            int(cap_ann.element("size") or cap_ann.element(None))
            if cap_ann
            else int(capacity)
        )
        pks = find_all(definition.annotations or [], "PrimaryKey")
        if len(pks) > 1:
            # reference: DuplicateAnnotationException for repeated @PrimaryKey
            raise SiddhiAppCreationError(
                f"table '{self.table_id}': @PrimaryKey annotation is repeated"
            )
        pk = pks[0] if pks else None
        self.primary_keys: list[str] = [v for _, v in pk.elements] if pk else []
        if pk is not None and not self.primary_keys:
            raise SiddhiAppCreationError(
                f"table '{self.table_id}': @PrimaryKey needs at least one "
                "attribute"
            )
        for k in self.primary_keys:
            if k not in self.schema.attr_names:
                raise SiddhiAppCreationError(
                    f"table '{self.table_id}': @PrimaryKey attribute '{k}' undefined"
                )
        idxs = find_all(definition.annotations or [], "Index") + find_all(
            definition.annotations or [], "IndexBy"
        )
        if len(idxs) > 1:
            # reference: DuplicateAnnotationException for repeated @Index
            raise SiddhiAppCreationError(
                f"table '{self.table_id}': @Index annotation is repeated"
            )
        idx = idxs[0] if idxs else None
        self.indexes: list[str] = [v for _, v in idx.elements] if idx else []
        if len(set(self.indexes)) != len(self.indexes):
            raise SiddhiAppCreationError(
                f"table '{self.table_id}': @Index lists an attribute twice"
            )
        for k in self.indexes:
            if k not in self.schema.attr_names:
                raise SiddhiAppCreationError(
                    f"table '{self.table_id}': @Index attribute '{k}' undefined"
                )
        # declared @Index columns are maintained from creation (reference:
        # IndexEventHolder builds declared indexes eagerly); equality-probed
        # columns additionally auto-index at query-compile time
        self._indexed_cols = tuple(dict.fromkeys(self.indexes))

        self.lock = threading.RLock()
        self.state = self.init_state()
        # observability hooks (wired by the app runtime when @app:statistics
        # is on): mutation_stats counts mutating steps committed to this
        # table; flush_latency times record-store write-through snapshots
        self.mutation_stats = None
        self.flush_latency = None
        # @OnError on the table definition (wired by the app runtime):
        # mutation failures — the mutating query's dispatch AND record-store
        # flushes here — route to the error store ('STORE') or the log
        # ('LOG') instead of propagating to the sender; None keeps the
        # propagate-to-sender behavior
        self.fault_policy = None
        self.app_name = ""
        self.error_store_fn = None

        # @store(type='...'): external record store — load initial contents,
        # write a snapshot through after each mutation (reference:
        # AbstractRecordTable SPI; see core/record_table.py)
        self.record_store = None
        self.lazy = False
        store_ann = find_annotation(definition.annotations, "store")
        if store_ann is not None:
            from siddhi_tpu.core.record_table import build_record_store

            self.record_store = build_record_store(
                store_ann, self.table_id, self.schema
            )
            rows = self.record_store.load()
            if rows is None:
                # lazy/queryable store: finds push conditions down, nothing
                # materializes (see record_table.RecordStore)
                self.lazy = True
            else:
                if len(rows) > self.capacity:
                    raise SiddhiAppCreationError(
                        f"table '{self.table_id}': record store holds "
                        f"{len(rows)} rows but capacity is {self.capacity}; "
                        "raise it with @capacity(size='N') before restarting"
                    )
                if rows:
                    batch = self.schema.to_batch(
                        [0] * len(rows), rows, interner, capacity=len(rows)
                    )
                    aux: dict = {}
                    self.state = self.insert(self.state, batch, aux)
        self._dirty = False
        self._last_flush = 0.0
        self._flush_lock = threading.Lock()
        self._flush_timer = None

    def notify_change(self) -> None:
        """Mark dirty; snapshots coalesce to at most one per second (the
        full-table host decode would otherwise stall the dispatch pipeline on
        every mutating step). flush_record_store() forces the write."""
        if self.mutation_stats is not None:
            self.mutation_stats(1)
        if self.record_store is None:
            return
        if self.lazy:
            raise SiddhiAppCreationError(
                f"table '{self.table_id}': a lazy (queryable) record store "
                "cannot accept streaming writes; materialize it or write to "
                "the store directly"
            )
        import threading as _threading
        import time as _time

        with self._flush_lock:
            self._dirty = True
            due = _time.monotonic() - self._last_flush >= 1.0
            arm = not due and self._flush_timer is None
            if arm:
                # coalesced: schedule a deferred flush so a final mutation in
                # a quiet period still reaches the store without a clean
                # shutdown
                t = _threading.Timer(1.0, self._deferred_flush)
                t.daemon = True
                self._flush_timer = t
                t.start()
        if due:
            self.flush_record_store()

    def _deferred_flush(self) -> None:
        with self._flush_lock:
            self._flush_timer = None
        self.flush_record_store()

    def flush_record_store(self) -> None:
        import time as _time

        with self._flush_lock:
            store = self.record_store
            if store is None or not self._dirty:
                return
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None
            from siddhi_tpu.observability.metrics import timed

            try:
                with timed(self.flush_latency):
                    rows = self.rows()
                    store.on_change(rows)
            except Exception as e:
                # @OnError on the table owns flush failures too (a record
                # store outage must not poison the mutating dispatch or the
                # deferred-flush timer thread); the table stays dirty so
                # the next flush retries
                if self.fault_policy is None:
                    raise
                import logging

                log = logging.getLogger(__name__)
                # flush failures are NOT stored even under STORE: the table
                # stays dirty and the next flush retries with the full
                # current rows, so nothing is lost — while a stored flush
                # entry carries no events and no input stream (sink_ref),
                # can never be replayed or purged, and a sustained outage
                # would flood the FIFO store, evicting genuinely
                # replayable entries. STORE applies to MUTATION failures
                # (wired by the app runtime around the mutating dispatch,
                # with the query's input batch attached).
                log.error(
                    "table '%s': record-store flush failed (@OnError "
                    "action='%s'); the table stays dirty and the next "
                    "flush retries: %s", self.table_id, self.fault_policy, e,
                )
                return
            self._dirty = False
            self._last_flush = _time.monotonic()

    def close_record_store(self) -> None:
        """Final flush + disconnect; later flush attempts become no-ops."""
        self.flush_record_store()
        with self._flush_lock:
            store, self.record_store = self.record_store, None
            if self._flush_timer is not None:
                self._flush_timer.cancel()
                self._flush_timer = None
        if store is not None:
            store.disconnect()

    # ---- state ------------------------------------------------------------

    # columns carrying a sorted index in state (set at query-compile time by
    # enable_index; a table never probed through an index must not pay an
    # O(C log C) sort per ingest batch). Reference analog: the
    # IndexEventHolder's per-column TreeMap/HashMap indexes
    # (table/holder/IndexEventHolder.java:59-110), here one sorted
    # permutation per column + a duplicate flag (the probe path requires
    # currently-unique keys; duplicates fall back to the dense compare).
    _indexed_cols: tuple = ()

    def describe_state(self) -> dict:
        """Introspection: live row count, capacity, index wiring (see
        observability/introspect.py). One host read per call."""
        import numpy as np

        d: dict = {
            "capacity": self.capacity,
            "primary_keys": list(self.primary_keys),
            "indexes": list(self._indexed_cols),
            "record_store": self.record_store is not None,
        }
        from siddhi_tpu.observability.introspect import device_reads_ok

        if not device_reads_ok():
            d["rows"] = None  # degraded relay: one d2h would poison dispatch
            return d
        try:
            with self.lock:
                d["rows"] = int(np.asarray(self.state["valid"]).sum())
        except Exception:
            d["rows"] = None  # mid-dispatch buffer churn: degrade
        return d

    @property
    def _pk_indexed(self) -> bool:
        return (
            len(self.primary_keys) == 1
            and self.primary_keys[0] in self._indexed_cols
        )

    def enable_index(self, col: str) -> None:
        """Called at query-compile time when an equality probe on `col`
        compiles (PK update, @Index column, or auto-indexed equality update
        probe); upgrades live state in place."""
        if col in self._indexed_cols:
            return
        if col not in self.schema.attr_names:
            raise SiddhiAppCreationError(
                f"table '{self.table_id}': cannot index undefined column '{col}'"
            )
        self._indexed_cols = tuple(self._indexed_cols) + (col,)
        with self.lock:
            self.state = self._rebuild_index(dict(self.state), col)

    def enable_pk_index(self) -> None:
        if len(self.primary_keys) == 1:
            self.enable_index(self.primary_keys[0])

    def init_state(self):
        c = self.capacity
        st = {
            "cols": {
                n: jnp.zeros((c,), a.dtype)
                for n, a in self.schema.empty_batch(1).cols.items()
            },
            "ts": jnp.zeros((c,), jnp.int64),
            "valid": jnp.zeros((c,), jnp.bool_),
            "seq": jnp.full((c,), jnp.iinfo(jnp.int64).max, jnp.int64),
            "next": jnp.zeros((), jnp.int64),
        }
        for col in self._indexed_cols:
            kd = st["cols"][col].dtype
            st[f"ix_order.{col}"] = jnp.arange(c, dtype=jnp.int32)
            st[f"ix_sorted.{col}"] = jnp.full((c,), _sort_sentinel(kd), kd)
            st[f"ix_dups.{col}"] = jnp.zeros((), jnp.bool_)
        return st

    def _rebuild_index(self, state, col: str):
        keys = state["cols"][col]
        sent = _sort_sentinel(keys.dtype)
        # valid rows first then keys ascending: a genuine max-valued key
        # still sorts before the invalid tail, so it remains findable
        order = jnp.lexsort((keys, ~state["valid"])).astype(jnp.int32)
        sk = jnp.where(state["valid"][order], keys[order], sent)
        svalid = state["valid"][order]
        dups = ((sk[1:] == sk[:-1]) & svalid[1:] & svalid[:-1]).any()
        return {
            **state,
            f"ix_order.{col}": order,
            f"ix_sorted.{col}": sk,
            f"ix_dups.{col}": dups,
        }

    def _rebuild_pk_index(self, state):
        for col in self._indexed_cols:
            state = self._rebuild_index(dict(state), col)
        return state

    def view(self, state):
        """(cols, ts, mask) — probe view, same contract as WindowStage.view."""
        return state["cols"], state["ts"], state["valid"]

    # ---- device ops (traced inside query steps) ---------------------------

    def insert(self, state, batch: EventBatch, aux: dict):
        """Insert valid CURRENT rows. Primary-key conflicts DROP the arriving
        row — first writer wins, the duplicate is discarded with a warning
        (reference: IndexEventHolder.add uses putIfAbsent and logs 'dropping
        event ... already an event stored with primary key',
        table/holder/IndexEventHolder.java:177-186). `update or insert into`
        is the overwriting form."""
        rows = batch.valid & (batch.kind == KIND_CURRENT)
        b = rows.shape[0]
        c = self.capacity

        if self.primary_keys:
            # [B, C] key equality against stored rows
            pk_match = jnp.ones((b, c), jnp.bool_)
            for k in self.primary_keys:
                pk_match = pk_match & (batch.cols[k][:, None] == state["cols"][k][None, :])
            pk_match = pk_match & rows[:, None] & state["valid"][None, :]
            # within-batch dedupe: the FIRST row per key wins the slot, later
            # duplicates are dropped like table-resident conflicts
            same_key = jnp.ones((b, b), jnp.bool_)
            for k in self.primary_keys:
                same_key = same_key & (batch.cols[k][:, None] == batch.cols[k][None, :])
            earlier_dup = same_key & rows[None, :] & (
                jnp.arange(b)[None, :] < jnp.arange(b)[:, None]
            )
            is_first = rows & ~earlier_dup.any(axis=1)
            fresh = is_first & ~pk_match.any(axis=1)
            aux["table_pk_duplicate_dropped"] = jnp.asarray(
                aux.get("table_pk_duplicate_dropped", False)
            ) | jnp.any(rows & ~fresh)
            return self._append(state, batch, fresh, aux)
        return self._append(state, batch, rows, aux)

    def _append(self, state, batch: EventBatch, rows, aux: dict):
        b = rows.shape[0]
        c = self.capacity
        # free slots in order; rows ranked by position
        free = ~state["valid"]
        n_free = free.sum()
        n_rows = rows.sum()
        aux["table_overflow"] = aux.get(
            "table_overflow", jnp.zeros((), jnp.bool_)
        ) | (n_rows > n_free)
        free_idx = first_indices(free, b)  # first B free slots
        rank = jnp.cumsum(rows.astype(jnp.int32)) - 1  # rank of each inserting row
        slot = jnp.where(rows, free_idx[jnp.clip(rank, 0, b - 1)], -1)
        ok = rows & (slot >= 0)
        # non-inserting rows scatter out of bounds and are dropped
        slot_c = jnp.where(ok, slot, c)

        def scatter(dst, src):
            # 64-bit lanes (ts/seq/long cols) ride the int32-pair scatter path
            from siddhi_tpu.ops.scatter import set_at

            return set_at(dst, slot_c, src.astype(dst.dtype))

        new_seq = state["next"] + rank
        out = {
            **state,
            "cols": {n: scatter(state["cols"][n], batch.cols[n]) for n in state["cols"]},
            "ts": scatter(state["ts"], batch.ts),
            "valid": scatter(state["valid"], jnp.ones((b,), jnp.bool_)),
            "seq": scatter(state["seq"], new_seq),
            "next": state["next"] + n_rows.astype(jnp.int64),
        }
        return self._rebuild_pk_index(out)

    def match(
        self,
        state,
        probe_cols: dict[str, jnp.ndarray],
        probe_ts,
        probe_ref: str,
        on: Optional[CompiledExpr],
        now,
        extra_probe_cols: Optional[dict] = None,
    ) -> jnp.ndarray:
        """[B, C] condition mask of probe rows against table rows."""
        b = probe_ts.shape[0]
        c = self.capacity
        if on is None:
            return jnp.broadcast_to(state["valid"][None, :], (b, c))
        env_cols = {(probe_ref, None, n): v[:, None] for n, v in probe_cols.items()}
        env_cols[(probe_ref, None, TS_ATTR)] = probe_ts[:, None]
        if extra_probe_cols:
            env_cols.update(
                {k: v[:, None] for k, v in extra_probe_cols.items()}
            )
        env_cols.update(
            {(self.table_id, None, n): v[None, :] for n, v in state["cols"].items()}
        )
        env_cols[(self.table_id, None, TS_ATTR)] = state["ts"][None, :]
        env = Env(env_cols, now=now)
        return jnp.broadcast_to(on(env), (b, c)) & state["valid"][None, :]

    def delete(self, state, batch: EventBatch, on, probe_ref, now, aux: dict):
        rows = batch.valid & (batch.kind == KIND_CURRENT)
        pair = self.match(state, batch.cols, batch.ts, probe_ref, on, now)
        doomed = (pair & rows[:, None]).any(axis=0)
        # rebuild indexes: a deleted row that shadowed a same-key duplicate
        # would otherwise make the sorted probe miss the surviving row
        return self._rebuild_pk_index(
            {**state, "valid": state["valid"] & ~doomed}
        )

    def update(
        self,
        state,
        batch: EventBatch,
        on,
        set_fns: list[tuple[str, Callable]],
        probe_ref,
        now,
        aux: dict,
        parallel_ok: bool = False,
        pk_probe=None,
        reindex_after: bool = False,
        pk_guard: Optional[str] = None,
    ):
        """Update matching table rows from each probe row.

        `parallel_ok` (decided at compile time by
        `_update_parallel_vectorizable`) selects a fully vectorized one-pass
        form: per table slot, the LAST matching probe row wins — provably
        equal to the reference's event-by-event iteration when the set
        values are independent of table state and the on-condition's table
        reads are stable under the update. Otherwise the sequential scan
        reproduces InMemoryTable.update's row-at-a-time semantics exactly."""
        rows = batch.valid & (batch.kind == KIND_CURRENT)
        if parallel_ok and pk_probe is not None:
            col, probe_fn, unique = pk_probe
            if unique:
                out = self._update_indexed(
                    state, batch, col, probe_fn, set_fns, probe_ref, now, rows
                )
            else:
                # the sorted probe is exact only while the indexed column is
                # duplicate-free; tables holding duplicates of the probed key
                # fall back to the dense all-matches compare
                def fast(st):
                    return self._update_indexed(
                        st, batch, col, probe_fn, set_fns, probe_ref, now,
                        rows,
                    )

                def dense(st):
                    return self._update_dense(
                        st, batch, on, set_fns, probe_ref, now, rows
                    )

                out = lax.cond(
                    state[f"ix_dups.{col}"], dense, fast, state
                )
            return self._rebuild_pk_index(out) if reindex_after else out
        if parallel_ok:
            out = self._update_dense(
                state, batch, on, set_fns, probe_ref, now, rows
            )
            return self._rebuild_pk_index(out) if reindex_after else out

        any_conflict0 = jnp.zeros((), jnp.bool_)

        def body(carry, xs):
            cols, any_conflict = carry
            row_cols, row_ts, row_on = xs
            env_cols = {(probe_ref, None, n): v[None] for n, v in row_cols.items()}
            env_cols[(probe_ref, None, TS_ATTR)] = row_ts[None]
            env_cols.update(
                {(self.table_id, None, n): v for n, v in cols.items()}
            )
            env_cols[(self.table_id, None, TS_ATTR)] = state["ts"]
            env = Env(env_cols, now=now)
            m = state["valid"] if on is None else (
                jnp.broadcast_to(on(env), (self.capacity,)) & state["valid"]
            )
            m = m & row_on
            if pk_guard is not None:
                # an update that REKEYS a row onto an existing primary key
                # fails atomically for this update event (the matched set is
                # left untouched) — reference: IndexOperator.update walks the
                # current key set, removes each row's old key, and aborts the
                # whole event on the first colliding add
                # (util/collection/operator/IndexOperator.java:119-161)
                kcol = cols[pk_guard]
                fn = dict(set_fns)[pk_guard]
                vals = jnp.broadcast_to(
                    fn(env).astype(kcol.dtype), (self.capacity,)
                )
                changed = m & (vals != kcol)
                n_changed = changed.sum(dtype=jnp.int32)
                i0 = jnp.argmax(changed)
                new0 = vals[i0]
                exists_other = jnp.any(
                    state["valid"] & (kcol == new0)
                    & (jnp.arange(self.capacity) != i0)
                )
                # >=2 rekeys collide with each other in the reference's
                # one-value-per-event model; per-row-varying values (our
                # extension) conservatively fail the same way
                fail = (n_changed >= 2) | ((n_changed == 1) & exists_other)
                m = jnp.where(fail, jnp.zeros_like(m), m)
                any_conflict = any_conflict | fail
            new_cols = dict(cols)
            for name, fn in set_fns:
                new_cols[name] = jnp.where(m, fn(env).astype(cols[name].dtype), cols[name])
            return (new_cols, any_conflict), None

        xs = (batch.cols, batch.ts, rows)
        (new_cols, any_conflict), _ = lax.scan(
            body, (state["cols"], any_conflict0), xs
        )
        if pk_guard is not None:
            aux["table_pk_conflict"] = (
                jnp.asarray(aux.get("table_pk_conflict", False)) | any_conflict
            )
        out = {**state, "cols": new_cols}
        return self._rebuild_pk_index(out) if reindex_after else out

    def _update_dense(self, state, batch, on, set_fns, probe_ref, now, rows):
        """Vectorized last-writer-wins update via the dense [B, C] match."""
        b = rows.shape[0]
        c = self.capacity
        pair = self.match(
            state, batch.cols, batch.ts, probe_ref, on, now
        ) & rows[:, None]
        # keep every [C]-sized intermediate 2D ([C/128, 128]): 1D
        # reductions/selects of this shape get placed in TPU scalar
        # space (S(1)) and run ~1000x slower (profiled at C=1M)
        two_d = c % 128 == 0 and c >= 128
        if two_d:
            pair = pair.reshape(b, c // 128, 128)
        writer = jnp.where(
            pair,
            jnp.arange(b, dtype=jnp.int32).reshape(
                (b, 1, 1) if two_d else (b, 1)
            ),
            -1,
        ).max(axis=0)  # last matching probe row per slot, -1 if none
        return self._apply_winner(
            state, batch, writer, two_d, set_fns, probe_ref, now
        )

    def _update_indexed(
        self, state, batch, col, probe_fn, set_fns, probe_ref, now, rows
    ):
        """O(B log C + B log B) indexed update: binary-search each probe key
        in the column's sorted index, dedupe writers with a [B] sort, and
        scatter the B set-values — everything is [B]-sized except the final
        column scatters (reference: IndexEventHolder key get/put,
        table/holder/IndexEventHolder.java:59-110). Exact when the indexed
        column is currently duplicate-free (PK uniqueness, or the caller's
        ix_dups cond guard)."""
        b = rows.shape[0]
        c = self.capacity
        keys = state["cols"][col]
        order = state[f"ix_order.{col}"]
        sk = state[f"ix_sorted.{col}"]

        env_cols = {(probe_ref, None, n): v for n, v in batch.cols.items()}
        env_cols[(probe_ref, None, TS_ATTR)] = batch.ts
        probe_raw = probe_fn(Env(env_cols, now=now))
        # cast only to LOCATE the candidate; the hit test compares under
        # numeric promotion so a fractional float probe cannot "match" the
        # integer key it truncates to (parity with the dense-compare path)
        probe = probe_raw.astype(keys.dtype)
        pos = jnp.clip(
            jnp.searchsorted(sk, probe, side="left"), 0, c - 1
        ).astype(jnp.int32)
        cand = order[pos]
        from siddhi_tpu.core.executor import _notnull

        probe_t = getattr(probe_fn, "type", self.schema.attr_types[col])
        hit = (
            rows
            & (keys[cand] == probe_raw)
            & state["valid"][cand]
            & _notnull(probe_raw, probe_t)
        )
        # last duplicate probe key wins, like the sequential iteration:
        # group probes by candidate slot (misses sort before hits), the
        # segment end is the winning probe
        idx = jnp.arange(b, dtype=jnp.int32)
        perm = jnp.lexsort((idx, hit.astype(jnp.int32), cand)).astype(
            jnp.int32
        )
        sc = cand[perm]
        seg_end = jnp.concatenate(
            [sc[1:] != sc[:-1], jnp.ones((1,), jnp.bool_)]
        )
        win_sorted = hit[perm] & seg_end
        win = jnp.zeros((b,), jnp.bool_).at[perm].set(win_sorted)

        # per-probe env: probe row beside ITS candidate table row — all [B]
        env_cols.update(
            {
                (self.table_id, None, n): v[cand]
                for n, v in state["cols"].items()
            }
        )
        env_cols[(self.table_id, None, TS_ATTR)] = state["ts"][cand]
        env = Env(env_cols, now=now)
        target = jnp.where(win, cand, c)
        new_cols = dict(state["cols"])
        from siddhi_tpu.ops.scatter import set_at

        for name, fn in set_fns:
            new_cols[name] = set_at(
                state["cols"][name], target,
                fn(env).astype(state["cols"][name].dtype),
            )
        return {**state, "cols": new_cols}

    def _apply_winner(
        self, state, batch, winner, two_d, set_fns, probe_ref, now
    ):
        """Shared tail of the vectorized update paths: gather each slot's
        winning probe row, build the per-slot env, apply the set clauses.
        `winner` is [C] (or [C/128,128] when two_d) with -1 = no match."""
        b = batch.valid.shape[0]
        c = self.capacity
        has = winner >= 0
        wi = jnp.clip(winner, 0, b - 1)
        env_cols = {(probe_ref, None, n): v[wi] for n, v in batch.cols.items()}
        env_cols[(probe_ref, None, TS_ATTR)] = batch.ts[wi]
        if two_d:
            env_cols = {k: v.reshape(c) for k, v in env_cols.items()}
            has = has.reshape(c)
        env_cols.update(
            {(self.table_id, None, n): v for n, v in state["cols"].items()}
        )
        env_cols[(self.table_id, None, TS_ATTR)] = state["ts"]
        env = Env(env_cols, now=now)
        new_cols = dict(state["cols"])
        for name, fn in set_fns:
            new_cols[name] = jnp.where(
                has, fn(env).astype(state["cols"][name].dtype),
                state["cols"][name],
            )
        return {**state, "cols": new_cols}

    def update_or_insert(
        self,
        state,
        batch: EventBatch,
        on,
        set_fns: list[tuple[str, Callable]],
        probe_ref,
        now,
        aux: dict,
        insert_names: Optional[list[str]] = None,
    ):
        """Per-probe-row: update matches, else insert the row
        (reference: InMemoryTable.updateOrAdd). `insert_names` maps probe
        columns to table columns positionally (selector output order)."""
        rows = batch.valid & (batch.kind == KIND_CURRENT)
        c = self.capacity
        # probe column feeding each table column, by position
        src_of = dict(
            zip(self.schema.attr_names, insert_names or self.schema.attr_names)
        )
        overflow0 = aux.get("table_overflow", jnp.zeros((), jnp.bool_))

        def body(carry, xs):
            cols, ts, valid, seq, nxt, ovf = carry
            row_cols, row_ts, row_on = xs
            env_cols = {(probe_ref, None, n): v[None] for n, v in row_cols.items()}
            env_cols[(probe_ref, None, TS_ATTR)] = row_ts[None]
            env_cols.update({(self.table_id, None, n): v for n, v in cols.items()})
            env_cols[(self.table_id, None, TS_ATTR)] = ts
            env = Env(env_cols, now=now)
            m = valid if on is None else (jnp.broadcast_to(on(env), (c,)) & valid)
            m = m & row_on
            hit = m.any()
            # update path
            upd_cols = dict(cols)
            for name, fn in set_fns:
                upd_cols[name] = jnp.where(m, fn(env).astype(cols[name].dtype), cols[name])
            # insert path: first free slot
            free = ~valid
            has_free = free.any()
            slot = jnp.argmax(free)
            do_insert = row_on & ~hit & has_free
            ovf = ovf | (row_on & ~hit & ~has_free)
            ins_cols = {
                n: jnp.where(
                    do_insert,
                    cols[n].at[slot].set(row_cols[src_of[n]].astype(cols[n].dtype)),
                    upd_cols[n],
                )
                for n in cols
            }
            new_ts = jnp.where(do_insert, ts.at[slot].set(row_ts), ts)
            new_valid = jnp.where(do_insert, valid.at[slot].set(True), valid)
            new_seq = jnp.where(do_insert, seq.at[slot].set(nxt), seq)
            new_next = nxt + do_insert.astype(jnp.int64)
            return (ins_cols, new_ts, new_valid, new_seq, new_next, ovf), None

        carry = (
            state["cols"], state["ts"], state["valid"], state["seq"],
            state["next"], overflow0,
        )
        xs = (batch.cols, batch.ts, rows)
        (cols, ts, valid, seq, nxt, ovf), _ = lax.scan(body, carry, xs)
        aux["table_overflow"] = ovf
        return self._rebuild_pk_index(
            {
                **state,
                "cols": cols, "ts": ts, "valid": valid, "seq": seq,
                "next": nxt,
            }
        )

    # ---- host-side convenience (tests / record-table parity) --------------

    def rows(self) -> list[tuple]:
        """Decode current contents in insertion order (host)."""
        import numpy as np

        with self.lock:
            st = self.state
        valid = np.asarray(st["valid"])
        seq = np.asarray(st["seq"])
        cols = {n: np.asarray(c) for n, c in st["cols"].items()}
        order = np.argsort(np.where(valid, seq, np.iinfo(np.int64).max), kind="stable")
        from siddhi_tpu.core.event import decode_value

        out = []
        for i in order:
            if not valid[i]:
                continue
            out.append(
                tuple(
                    decode_value(cols[n][i], t, self.interner)
                    for n, t in self.schema.attrs
                )
            )
        return out


def compile_table_output(
    output_stream,
    out_schema: StreamSchema,
    tables: dict[str, InMemoryTable],
    interner,
) -> Optional[Callable]:
    """Compile a query/store-query output stream into a table op
    `(tstates, out_batch, now, aux) -> tstates'`, or None when the output
    does not target a table (reference: OutputParser constructing
    Insert/Update/Delete/UpdateOrInsertIntoTableCallback)."""
    from siddhi_tpu.core.errors import DefinitionNotExistError
    from siddhi_tpu.query_api.execution import (
        DeleteStream,
        InsertIntoStream,
        UpdateOrInsertStream,
        UpdateStream,
    )

    target = getattr(output_stream, "target", None)

    if isinstance(output_stream, InsertIntoStream):
        if target not in tables:
            return None
        table = tables[target]
        _check_positional_schema(out_schema, table, "insert into")
        names = table.schema.attr_names
        dtypes = {n: a.dtype for n, a in table.schema.empty_batch(1).cols.items()}
        from siddhi_tpu.query_api.execution import OutputEventsFor

        want = output_stream.output_events

        def op(tstates, out_batch, now, aux, _t=table, _tid=target):
            # honor `insert [current|expired|all] events into T`
            # (reference: InsertIntoTableCallback event-type filtering)
            if want is OutputEventsFor.CURRENT:
                keep = out_batch.kind == KIND_CURRENT
            elif want is OutputEventsFor.EXPIRED:
                keep = out_batch.kind == np.int8(1)  # KIND_EXPIRED
            else:
                keep = jnp.ones_like(out_batch.valid)
            # positional mapping rides the OUT SCHEMA order, not the cols
            # dict order (jit pytree reconstruction sorts dict keys, so a
            # batch crossing a jit boundary arrives alphabetized)
            cols = {
                n: out_batch.cols[sn].astype(dtypes[n])
                for n, sn in zip(names, out_schema.attr_names)
            }
            renamed = EventBatch(
                out_batch.ts,
                jnp.zeros_like(out_batch.kind),  # inserted rows become CURRENT
                out_batch.valid & keep,
                cols,
            )
            tstates = dict(tstates)
            tstates[_tid] = _t.insert(tstates[_tid], renamed, aux)
            return tstates

        return op

    if isinstance(output_stream, (UpdateStream, DeleteStream, UpdateOrInsertStream)):
        table = tables.get(target)
        if table is None:
            raise DefinitionNotExistError(f"'{target}' is not a defined table")
        if isinstance(output_stream, UpdateOrInsertStream):
            _check_positional_schema(out_schema, table, "update or insert into")
        scope = Scope(interner)
        scope.add_stream("__out__", dict(out_schema.attrs))
        scope.add_stream(table.table_id, table.schema.attr_types)
        scope.default_ref = "__out__"
        scope.prefer_default = True
        on = (
            compile_expression(output_stream.on, scope)
            if output_stream.on is not None
            else None
        )
        if on is not None and on.type is not AttrType.BOOL:
            raise SiddhiAppCreationError("'on' must be a boolean expression")
        if isinstance(output_stream, DeleteStream):
            def op(tstates, out_batch, now, aux, _t=table, _tid=target):
                tstates = dict(tstates)
                tstates[_tid] = _t.delete(
                    tstates[_tid], out_batch, on, "__out__", now, aux
                )
                return tstates
        else:
            set_fns = compile_set_attributes(
                table, output_stream.set_attributes, scope
            )
            if isinstance(output_stream, UpdateOrInsertStream):
                ins_names = list(out_schema.attr_names)

                def op(tstates, out_batch, now, aux, _t=table, _tid=target):
                    tstates = dict(tstates)
                    tstates[_tid] = _t.update_or_insert(
                        tstates[_tid], out_batch, on, set_fns, "__out__", now,
                        aux, insert_names=ins_names,
                    )
                    return tstates
            else:
                par_ok = _update_parallel_vectorizable(
                    output_stream.on, output_stream.set_attributes,
                    table, out_schema,
                )
                # single-@PrimaryKey tables whose update writes the key
                # column take the sequential path with the atomic rekey-
                # collision guard (reference: IndexOperator.update aborts an
                # update event whose new key collides) — EXCEPT when the
                # on-clause equality-pins the written key to the same
                # expression (`on T.pk == e` with `set pk = e`): the key
                # provably cannot change, so the vectorized fast path stays
                pk_guard = None
                if len(table.primary_keys) == 1:
                    pk_col = table.primary_keys[0]
                    if pk_col in {n for n, _ in set_fns}:
                        found0 = _eq_probe_expr(
                            output_stream.on, table, out_schema
                        )
                        smap = _set_map(
                            output_stream.set_attributes, table, out_schema
                        )
                        pinned = (
                            found0 is not None
                            and found0[0] == pk_col
                            and found0[1] == smap.get(pk_col)
                        )
                        if not pinned:
                            pk_guard = pk_col
                            par_ok = False
                pk_probe = None
                if par_ok:
                    found = _eq_probe_expr(output_stream.on, table, out_schema)
                    if found is not None:
                        col, p_side = found
                        # planner decision (reference: util/collection
                        # CollectionExecutors choosing an indexed lookup):
                        # a single-column equality probe auto-indexes that
                        # column; @PrimaryKey uniqueness skips the dup guard
                        unique = table.primary_keys == [col]
                        pk_probe = (
                            col, compile_expression(p_side, scope), unique
                        )
                        table.enable_index(col)
                def op(tstates, out_batch, now, aux, _t=table, _tid=target):
                    # reindex decided at TRACE time (not compile time): later
                    # queries may have enabled more indexes by then, and an
                    # update that can rewrite an indexed column to a value
                    # the match does not pin must rebuild its sorted index
                    reindex = _index_written_unpinned(
                        output_stream.on, output_stream.set_attributes,
                        _t, out_schema,
                    )
                    tstates = dict(tstates)
                    tstates[_tid] = _t.update(
                        tstates[_tid], out_batch, on, set_fns, "__out__", now,
                        aux, parallel_ok=par_ok, pk_probe=pk_probe,
                        reindex_after=reindex, pk_guard=pk_guard,
                    )
                    return tstates

        return op

    return None


def _sort_sentinel(dtype):
    """Largest value of a column dtype (numpy, never a device const) — used
    to push invalid rows to the tail of the sorted-key view."""
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return np.asarray(np.inf, dt)
    return np.asarray(np.iinfo(dt).max, dt)


def _conjuncts(e):
    from siddhi_tpu.query_api.expression import And

    if isinstance(e, And):
        yield from _conjuncts(e.left)
        yield from _conjuncts(e.right)
    else:
        yield e


def _eq_probe_expr(on_expr, table: InMemoryTable, out_schema: StreamSchema):
    """(column, probe expression) when the condition is exactly
    `T.col == <probe expr>` over one table column, else None."""
    from siddhi_tpu.query_api.expression import Compare, CompareOp, Variable

    if on_expr is None:
        return None
    conj = list(_conjuncts(on_expr))
    if len(conj) != 1 or not (
        isinstance(conj[0], Compare) and conj[0].op is CompareOp.EQ
    ):
        return None
    c = conj[0]
    for t_side, p_side in ((c.left, c.right), (c.right, c.left)):
        if (
            isinstance(t_side, Variable)
            and _reads_table(t_side, table, out_schema)
            and t_side.attribute in table.schema.attr_names
            and not _reads_table(p_side, table, out_schema)
        ):
            return t_side.attribute, p_side
    return None


def _set_map(set_attributes, table, out_schema):
    from siddhi_tpu.query_api.expression import Variable

    if set_attributes:
        return {
            sa.table_variable.attribute: sa.expression for sa in set_attributes
        }
    return {
        name: Variable(name)
        for name, _t in table.schema.attrs
        if name in out_schema.attr_names
    }


def _eq_sources(on_expr, table, out_schema):
    from siddhi_tpu.query_api.expression import Compare, CompareOp, Variable

    out: dict = {}
    if on_expr is None:
        return out
    for c in _conjuncts(on_expr):
        if isinstance(c, Compare) and c.op is CompareOp.EQ:
            for t_side, p_side in ((c.left, c.right), (c.right, c.left)):
                if (
                    isinstance(t_side, Variable)
                    and _reads_table(t_side, table, out_schema)
                    and not _reads_table(p_side, table, out_schema)
                ):
                    out[t_side.attribute] = p_side
    return out


def _index_written_unpinned(on_expr, set_attributes, table, out_schema) -> bool:
    """True when an update's set clause may change ANY indexed column to a
    value the on-condition does not pin to its current value — the sorted
    indexes must be rebuilt after such an update."""
    sm = _set_map(set_attributes, table, out_schema)
    eq = _eq_sources(on_expr, table, out_schema)
    return any(
        col in sm and eq.get(col) != sm[col]
        for col in table._indexed_cols
    )


def _reads_table(expr, table: InMemoryTable, out_schema: StreamSchema) -> bool:
    """True when an expression AST can read a column of `table` under the
    update scope (prefer_default resolves unqualified names to the output
    stream first, so a table read needs `T.col` or an attr only the table
    has)."""
    import dataclasses as _dc

    from siddhi_tpu.query_api.expression import Variable

    if isinstance(expr, Variable):
        if expr.stream_id == table.table_id:
            return True
        return (
            expr.stream_id is None
            and expr.attribute not in out_schema.attr_names
            and expr.attribute in table.schema.attr_names
        )
    if _dc.is_dataclass(expr) and not isinstance(expr, type):
        return any(
            _reads_table(getattr(expr, f.name), table, out_schema)
            for f in _dc.fields(expr)
        )
    if isinstance(expr, (list, tuple)):
        return any(_reads_table(x, table, out_schema) for x in expr)
    return False


def _update_parallel_vectorizable(
    on_expr, set_attributes, table: InMemoryTable, out_schema: StreamSchema
) -> bool:
    """Decide whether `update T on <cond> [set ...]` may run as one
    vectorized last-writer-wins pass instead of the reference's sequential
    row-at-a-time iteration. Safe iff

    1. every set VALUE is independent of table state (so the last matching
       probe row's values equal what the sequential loop would leave), and
    2. every table column the on-condition reads is either not written, or
       is written from exactly the probe expression it is equated with in a
       top-level conjunct (`on T.c == e ... set T.c = e` / the positional
       default set) — so earlier updates within the batch cannot change
       later rows' match results.
    """
    from siddhi_tpu.query_api.expression import Variable

    set_map = _set_map(set_attributes, table, out_schema)
    for src in set_map.values():
        if _reads_table(src, table, out_schema):
            return False

    # table columns read by the condition, and the equality conjuncts
    if on_expr is None:
        return True

    eq_sources = _eq_sources(on_expr, table, out_schema)

    def table_cols_read(e, acc):
        import dataclasses as _dc

        if isinstance(e, Variable):
            if _reads_table(e, table, out_schema):
                acc.add(e.attribute)
            return acc
        if _dc.is_dataclass(e) and not isinstance(e, type):
            for f in _dc.fields(e):
                table_cols_read(getattr(e, f.name), acc)
        elif isinstance(e, (list, tuple)):
            for x in e:
                table_cols_read(x, acc)
        return acc

    for col in table_cols_read(on_expr, set()):
        if col not in set_map:
            continue  # not written: always stable
        if eq_sources.get(col) != set_map[col]:
            return False  # written to a value the match does not pin
    return True


def collect_used_tables(query, tables: dict[str, InMemoryTable]) -> set[str]:
    """Table ids a query touches: `in <table>` conditions anywhere in its AST,
    table-backed join sides, and the table-output target."""
    import dataclasses as _dc

    from siddhi_tpu.query_api.execution import JoinInputStream
    from siddhi_tpu.query_api.expression import In

    used: set[str] = set()

    def walk(obj):
        if isinstance(obj, In):
            if obj.source_id in tables:
                used.add(obj.source_id)
            walk(obj.expression)
        elif _dc.is_dataclass(obj) and not isinstance(obj, type):
            for f in _dc.fields(obj):
                walk(getattr(obj, f.name))
        elif isinstance(obj, (list, tuple)):
            for x in obj:
                walk(x)
        elif isinstance(obj, dict):
            for x in obj.values():
                walk(x)

    walk(query)
    target = getattr(query.output_stream, "target", None)
    if target in tables:
        used.add(target)
    ins = query.input_stream
    if isinstance(ins, JoinInputStream):
        for s in (ins.left, ins.right):
            if s.stream_id in tables:
                used.add(s.stream_id)
    return used


def _check_positional_schema(
    out_schema: StreamSchema, table: InMemoryTable, what: str
) -> None:
    """Positional attribute mapping requires matching arity and types, with
    Java implicit numeric widening allowed (reference: DefinitionParserHelper
    validateOutputStream; StoreQueryParser coerces numeric constants into
    wider columns — e.g. an INT literal inserts into a LONG column)."""
    from siddhi_tpu.core.types import NUMERIC_TYPES, promote

    if len(out_schema.attrs) != len(table.schema.attrs):
        raise SiddhiAppCreationError(
            f"{what} table '{table.table_id}': selector emits "
            f"{len(out_schema.attrs)} attributes, table has "
            f"{len(table.schema.attrs)}"
        )
    for (on_, ot), (tn, tt) in zip(out_schema.attrs, table.schema.attrs):
        if ot is tt:
            continue
        if (
            ot in NUMERIC_TYPES
            and tt in NUMERIC_TYPES
            and promote(ot, tt) is tt
        ):
            continue  # widening coercion; the op's astype performs it
        raise SiddhiAppCreationError(
            f"{what} table '{table.table_id}': output attribute "
            f"'{on_}' is {ot.name} but table column '{tn}' is {tt.name}"
        )


def compile_set_attributes(
    table: InMemoryTable,
    set_attributes: Optional[list[UpdateSetAttribute]],
    scope: Scope,
) -> list[tuple[str, CompiledExpr]]:
    """`set T.a = expr, ...`; absent => overwrite every table column with the
    same-named output attribute (reference: InMemoryTable default update)."""
    out: list[tuple[str, CompiledExpr]] = []
    if set_attributes:
        for sa in set_attributes:
            name = sa.table_variable.attribute
            if name not in table.schema.attr_names:
                raise SiddhiAppCreationError(
                    f"set target '{name}' is not a column of '{table.table_id}'"
                )
            out.append((name, compile_expression(sa.expression, scope)))
    else:
        from siddhi_tpu.query_api.expression import Variable

        for name, _t in table.schema.attrs:
            try:
                out.append((name, compile_expression(Variable(name), scope)))
            except KeyError:
                continue  # no same-named output attribute: column untouched
    return out
