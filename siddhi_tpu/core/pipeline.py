"""Double-buffered ingest pipeline: overlap encode, h2d, dispatch, drain.

The fused ingest path (core/ingest.py) runs three host-visible stages per
chunk — host encode, host->device transfer, jitted dispatch — plus, in
deliver mode, a blocking d2h readback + decode + callback delivery. Run
strictly serialized, the sender's wall-clock per chunk is
`encode + h2d + device + d2h` even though the stages use disjoint resources
(Python/numpy on the host, the wire, the device, and the readback path).

This module keeps those stages concurrently busy (the Hazelcast Jet
"pipeline stages must stay busy" argument, PAPERS.md):

1. host encode writes into one of `depth` POOLED wire buffers, so chunk
   N+1's encode can start while chunk N's buffer is still being shipped
   (a slot is reused only after its transfer completed);
2. chunk N+1 is encoded and `jax.device_put` while chunk N's donated-state
   dispatch is still in flight — JAX dispatch is already async, so the win
   is moving encode (and the transfer submit) off the dispatch critical
   path;
3. a bounded background drain worker syncs each chunk's packed output
   buffer, decodes it, and runs query-callback delivery in chunk order,
   with backpressure (at most `depth` undrained chunks in flight) so state
   donation stays safe and device memory for packed outputs is bounded.

Ordering and failure semantics are preserved exactly:

* `try_send` still BARRIERS on the drain before returning, so callbacks
  fire in chunk order and complete before `send_columns` returns — any
  later per-batch `send` observes the same ordering as the serial path;
* a delivery failure on the drain worker goes through the junction's
  existing failure machinery (`_on_worker_error`: log + error stats +
  exception handler), mirroring the @async drain workers; when the
  junction has NO handler and NO @OnError policy the error is re-raised
  to the sender at the barrier, like the serial path's in-line drain.

On backends where a device->host read from a non-main thread permanently
degrades dispatch (tunneled PJRT relays — see
utils/backend.transfer_degrades_dispatch), the drain worker is not used:
drains run on the caller's thread one chunk late, which still overlaps the
decode with the next chunk's device compute.

Configuration: the `@pipeline(depth='N', disable='true')` stream
annotation, overridden process-wide by SIDDHI_TPU_PIPELINE=1 (force on) /
SIDDHI_TPU_PIPELINE=0 (force off).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Optional

import numpy as np

DEFAULT_DEPTH = 2
_MAX_DEPTH = 8

PIPELINE_ENV = "SIDDHI_TPU_PIPELINE"

_TRUE = ("1", "on", "true", "force")
_FALSE = ("0", "off", "false")


def pipeline_env_override() -> Optional[bool]:
    """Process-wide pipeline toggle: True (forced on), False (forced off),
    or None (defer to the stream's @pipeline annotation)."""
    v = os.environ.get(PIPELINE_ENV, "").strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    return None


def iter_pipeline_annotation_problems(ann):
    """Yield one message per malformed `@pipeline` element — THE validation
    rules, shared by the runtime resolver (raises on the first) and the
    analyzer's SA112 diagnostics (reports them all), so the two can never
    drift."""
    for k, v in ann.elements:
        if k == "depth":
            try:
                ok = 1 <= int(v) <= _MAX_DEPTH
            except (TypeError, ValueError):
                ok = False
            if not ok:
                yield (
                    f"@pipeline depth '{v}' must be an integer in "
                    f"1..{_MAX_DEPTH}"
                )
        elif k == "disable":
            if str(v).strip().lower() not in ("true", "false"):
                yield f"@pipeline disable '{v}' must be true or false"
        else:
            yield (
                f"unknown @pipeline option '{k if k is not None else v}' "
                "(expected depth, disable)"
            )


def resolve_pipeline_annotation(ann) -> tuple[bool, int]:
    """(enabled, depth) for one stream from its `@pipeline` annotation (or
    None) plus the SIDDHI_TPU_PIPELINE env override. Raises
    SiddhiAppCreationError on malformed options — the runtime analog of the
    analyzer's SA112 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    enabled = True
    depth = DEFAULT_DEPTH
    if ann is not None:
        for problem in iter_pipeline_annotation_problems(ann):
            raise SiddhiAppCreationError(problem)
        depth = int(ann.element("depth", str(DEFAULT_DEPTH)))
        enabled = (
            str(ann.element("disable", "false")).strip().lower() != "true"
        )
    env = pipeline_env_override()
    if env is not None:
        enabled = env
    return enabled, depth


class _WireSlot:
    """One pooled host wire buffer + the device array gating its reuse.

    `jax.device_put` of a numpy array may ALIAS the host buffer instead of
    copying (the CPU backend does, depending on the buffer's size and
    alignment — so it cannot be probed once globally). ship() detects it
    per shipment by comparing buffer POINTERS (no device->host transfer,
    which would flip tunneled relays out of their fast mode):

    * copied: `ref` is the shipped device array — reuse is safe once the
      TRANSFER completed;
    * aliased (or unknown): retire() swaps `ref` for a completion array of
      the dispatch that READ the wire — only the program finishing frees
      the buffer for overwrite.

    Copied shipments additionally form a device-side STAGING RING: the
    slot keeps `dev` (the device wire) and `dev_gate` (a completion array
    of the consuming dispatch), and the next acquire() of the slot
    explicitly deletes the retired device buffer once the dispatch that
    read it finished — steady-state ingest then cycles `depth` device
    staging buffers through the allocator deterministically instead of
    letting GC lag grow device memory (the h2d-wall work's
    "persistent donated device-side staging rings")."""

    __slots__ = ("buf", "ref", "aliased", "dev", "dev_gate")

    def __init__(self, shape):
        self.buf = np.zeros(shape, dtype=np.uint8)
        self.ref = None
        self.aliased = True
        self.dev = None
        self.dev_gate = None


class IngestPipeline:
    """Per-junction pipeline engine owned by a FusedJunctionIngest.

    Senders are serialized by the ingest's send lock, so acquire/ship run
    from one thread at a time; the drain worker is the only other thread
    touching this object (via the queue/condvar only).
    """

    def __init__(self, junction, depth: int = DEFAULT_DEPTH, drain_fn=None):
        self.junction = junction
        self.depth = max(1, int(depth))
        self.drain_fn = drain_fn  # fn(packs, K): the ingest's _drain
        self.stats = None  # PipelineStats | None, set by the owner
        self._pool: dict[tuple, dict] = {}  # (K, nb) -> {slots, next}
        self._cv = threading.Condition()
        self._inflight = 0  # submitted, not yet drained (thread mode)
        self._error: Optional[BaseException] = None
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._pending_inline = None  # (packs, K) in inline-drain mode
        self._closed = False
        self._use_thread: Optional[bool] = None

    # ---- wire buffer pool ------------------------------------------------

    def acquire(self, K: int, wire_bytes: int) -> _WireSlot:
        """A host buffer for one [K, wire_bytes] chunk, safe to overwrite:
        pooled, blocking on the slot's reuse gate (see _WireSlot)."""
        key = (int(K), int(wire_bytes))
        ent = self._pool.get(key)
        if ent is None:
            ent = self._pool[key] = {
                "slots": [
                    _WireSlot(key) for _ in range(max(2, self.depth))
                ],
                "next": 0,
            }
        slots = ent["slots"]
        slot = slots[ent["next"]]
        ent["next"] = (ent["next"] + 1) % len(slots)
        if slot.ref is not None:
            try:
                slot.ref.block_until_ready()
            except Exception:
                # failed execution: the gating work is no longer running,
                # so the buffer is free (gate arrays are never donated —
                # see _dispatch_chunk's completion contract — so deletion
                # cannot race this wait)
                pass
            slot.ref = None
        if slot.dev is not None:
            # staging ring: free the previous cycle's device wire once the
            # dispatch that READ it completed (dev_gate) — but only when
            # that completion is ALREADY ready (steady state): a blocking
            # wait here would re-serialize the encode-under-dispatch
            # overlap the pipeline exists for. Not-yet-ready (or gateless:
            # failed submit / donated-only outputs) buffers are abandoned
            # to GC — deleting under a possibly-running program would be a
            # device UAF.
            gate, slot.dev_gate = slot.dev_gate, None
            dev, slot.dev = slot.dev, None
            if gate is not None:
                try:
                    if gate.is_ready():
                        dev.delete()
                except Exception:
                    pass
        return slot

    def ship(self, slot: _WireSlot):
        """Start the async host->device transfer of the slot's buffer and
        return the device array; detects per shipment whether the backend
        aliased the host buffer (see _WireSlot) and gates the slot
        accordingly. (The batch shard router — parallel/shard.py — does
        NOT ride these pooled slots: it stages every chunk of a send
        before dispatching any, so a slot could be re-acquired before its
        first occupant shipped; it uses a fresh buffer per chunk and a
        plain pinned device_put instead.)"""
        import jax

        dev = jax.device_put(slot.buf)
        try:
            slot.aliased = (
                dev.unsafe_buffer_pointer() == slot.buf.ctypes.data
            )
        except Exception:
            slot.aliased = True  # can't tell: assume the worst
        slot.ref = dev
        return dev

    def retire(self, slot: _WireSlot, completion) -> None:
        """For an ALIASED shipment, swap the slot's reuse gate for an
        output array of the dispatch that consumed the wire (acquire()
        then waits for the program, not the no-op transfer). With no
        non-donated completion available (None: the dispatch failed at
        submit, or its only outputs are donated query states) there is
        nothing safe to gate on — the aliased buffer is ABANDONED to the
        shipped array's reference and the slot gets a virgin buffer, so a
        still-running program can never see the next chunk's bytes. No-op
        for copied shipments: ship()'s transfer gate suffices."""
        if not slot.aliased:
            # copied shipment: the host buffer only needs the transfer
            # gate (ship() set it), but the DEVICE wire joins the staging
            # ring — record the consuming dispatch's completion so the
            # next cycle can free it deterministically (see acquire())
            slot.dev = slot.ref
            slot.dev_gate = completion
            return
        if completion is not None:
            slot.ref = completion
        else:
            slot.buf = np.zeros_like(slot.buf)
            slot.ref = None

    def in_flight(self) -> int:
        """Chunks submitted but not yet drained (inline mode: the one
        pending chunk)."""
        if self._thread is None:
            return 1 if self._pending_inline is not None else 0
        with self._cv:
            return self._inflight

    def describe_state(self) -> dict:
        """Introspection: depth, slots in flight, pooled wire slots, drain
        mode (see observability/introspect.py)."""
        return {
            "depth": self.depth,
            "in_flight": self.in_flight(),
            "wire_slots": sum(
                len(ent["slots"]) for ent in self._pool.values()
            ),
            "drain_thread": self._thread is not None,
            "closed": self._closed,
        }

    # ---- drain -----------------------------------------------------------

    def is_drain_thread(self) -> bool:
        return (
            self._thread is not None
            and threading.current_thread() is self._thread
        )

    def _thread_ok(self) -> bool:
        if self._use_thread is None:
            from siddhi_tpu.utils.backend import transfer_degrades_dispatch

            # a non-main-thread d2h read permanently degrades dispatch on
            # tunneled relays: drain inline (one chunk late) there instead
            self._use_thread = not transfer_degrades_dispatch()
        return self._use_thread

    def submit(self, packs, K: int, wf=None) -> None:
        """Queue one chunk's packed outputs for ordered delivery (`wf`:
        the chunk's stage waterfall, closed by the drain). Blocks while
        `depth` chunks are already in flight (backpressure)."""
        if self._thread_ok():
            if self._thread is None:
                self._start_thread()
            with self._cv:
                while self._inflight >= self.depth and not self._closed:
                    self._cv.wait()
                self._inflight += 1
            self._q.put((packs, K, wf))
        else:
            prev = self._pending_inline
            self._pending_inline = (packs, K, wf)
            if prev is not None:
                self._drain_inline(*prev)

    def pending_error(self) -> bool:
        """True once an unguarded drain failure is stashed for barrier():
        the sender polls this per chunk and stops ingesting, bounding the
        extra chunks committed past a poisoned delivery to the pipeline
        depth (the serial path's drain-one-late commits one extra)."""
        with self._cv:
            return self._error is not None

    def barrier(self) -> None:
        """Wait until every submitted chunk has been delivered; re-raise a
        drain failure here when the junction has no handler/policy to own it
        (the pipelined analog of the serial path's in-line drain raising)."""
        if self._pending_inline is not None:
            prev, self._pending_inline = self._pending_inline, None
            self._drain_inline(*prev)
        if self._thread is not None:
            with self._cv:
                while self._inflight > 0:
                    self._cv.wait()
        err, self._error = self._error, None
        if err is not None:
            raise err

    def _start_thread(self) -> None:
        self._q = queue.Queue()
        self._thread = threading.Thread(
            target=self._drain_loop,
            daemon=True,
            name=f"siddhi-pipeline-{self.junction.schema.stream_id}",
        )
        self._thread.start()

    def _drain_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            packs, K, wf = item
            try:
                self._drain_one(packs, K, wf)
            except Exception as exc:  # must not kill the worker
                self._on_drain_error(exc)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _drain_one(self, packs, K: int, wf=None) -> None:
        import time

        from siddhi_tpu.testing import faults as _faults

        # fault-injection site `drain_worker` (testing/faults.py): the
        # pipelined analog of the @async drain-worker site — an injected
        # fault rides the same guarded/unguarded routing a poisoned
        # delivery takes (_route_drain_error / barrier re-raise)
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.check(
                "drain_worker", self.junction.schema.stream_id
            )
        ps = self.stats
        t0 = time.perf_counter_ns() if ps is not None else 0
        try:
            self.drain_fn(packs, K, wf)
        finally:
            if t0:
                ps.drain.record_ns(time.perf_counter_ns() - t0)

    def _route_drain_error(self, exc: Exception) -> bool:
        """True when the junction's failure machinery owned the error —
        same machinery as the @async drain workers (log + error stats +
        exception handler); unguarded junctions get False and the failure
        goes back to the sender."""
        j = self.junction
        if j.exception_handler is not None or j.fault_policy is not None:
            j._on_worker_error(exc, "pipeline drain")
            return True
        return False

    def _drain_inline(self, packs, K: int, wf=None) -> None:
        """Caller-thread drain (degraded-transfer backends) with the same
        error contract as the worker: guarded junctions route, unguarded
        ones re-raise to the sender."""
        try:
            self._drain_one(packs, K, wf)
        except Exception as exc:
            if not self._route_drain_error(exc):
                raise

    def _on_drain_error(self, exc: Exception) -> None:
        if self._route_drain_error(exc):
            return
        with self._cv:
            if self._error is None:
                self._error = exc  # surfaces to the sender at barrier()

    def close(self) -> None:
        """Flush nothing (callers barrier first); stop the drain worker."""
        self._closed = True
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            self._q.put(None)
            t.join(timeout=2.0)
        self._thread = None
