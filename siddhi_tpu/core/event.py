"""Columnar event substrate.

Replaces the reference's pooled linked-list event representation
(reference: core/event/ComplexEvent.java:48-53, event/stream/StreamEvent.java:37-120,
event/ComplexEventChunk.java:29-246) with a fixed-capacity columnar `EventBatch`:
one device array per attribute plus timestamp / kind / validity lanes. The four
reference event types CURRENT/EXPIRED/TIMER/RESET become an int8 `kind` lane;
pool-borrowing becomes padding to a static batch capacity.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.types import (
    PHYSICAL_DTYPE,
    AttrType,
    InternTable,
    null_value,
)

# ComplexEvent.Type equivalents (reference: core/event/ComplexEvent.java:48-53).
KIND_CURRENT = 0
KIND_EXPIRED = 1
KIND_TIMER = 2
KIND_RESET = 3

# Host-side event (reference: core/event/Event.java — timestamp + Object[] data).
Event = collections.namedtuple("Event", ["timestamp", "data"])


class WireNarrowMisfit(ValueError):
    """A value in this batch does not fit the chosen narrow wire dtype; the
    sender must rebuild with the full-width wire and retry."""


def _bitcast_split(buf, offset: int, cap: int, dt: np.dtype):
    """Slice one column section out of a packed uint8 buffer and bitcast it
    to its dtype — shared by packed_codec and wire_codec so the 1-byte-wide
    special case lives in exactly one place."""
    seg = jax.lax.slice(buf, (offset,), (offset + cap * dt.itemsize,))
    w = dt.itemsize
    if np.dtype(dt) == np.bool_:
        # bitcast refuses bool targets; the encode side wrote 0/1 bytes
        return seg.astype(jnp.bool_)
    if w == 1:
        return jax.lax.bitcast_convert_type(seg, jnp.dtype(dt))
    return jax.lax.bitcast_convert_type(
        seg.reshape(cap, w), jnp.dtype(dt)
    ).reshape(cap)




@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EventBatch:
    """A fixed-capacity micro-batch of events for one stream.

    ts:    [B] int64 — epoch milliseconds (reference StreamEvent.timestamp)
    kind:  [B] int8  — KIND_* lane
    valid: [B] bool  — row occupancy (padding rows are False)
    cols:  {attr_name: [B] array} in schema order
    """

    ts: jax.Array
    kind: jax.Array
    valid: jax.Array
    cols: dict[str, jax.Array]

    @property
    def capacity(self) -> int:
        return self.ts.shape[-1]

    def col_list(self) -> list[jax.Array]:
        return list(self.cols.values())


class StreamSchema:
    """Typed stream definition (reference: query-api definition/StreamDefinition.java)."""

    def __init__(self, stream_id: str, attrs: Sequence[tuple[str, AttrType]]):
        self.stream_id = stream_id
        self.attrs: list[tuple[str, AttrType]] = list(attrs)
        self.attr_names = [n for n, _ in self.attrs]
        self.attr_types = {n: t for n, t in self.attrs}
        if len(self.attr_types) != len(self.attrs):
            raise ValueError(f"duplicate attribute in stream '{stream_id}'")

    def type_of(self, name: str) -> AttrType:
        try:
            return self.attr_types[name]
        except KeyError:
            raise KeyError(
                f"no attribute '{name}' in stream '{self.stream_id}' "
                f"(has {self.attr_names})"
            ) from None

    def index_of(self, name: str) -> int:
        return self.attr_names.index(name)

    def __repr__(self) -> str:
        return f"StreamSchema({self.stream_id}, {self.attrs})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, StreamSchema)
            and self.stream_id == other.stream_id
            and self.attrs == other.attrs
        )

    def __hash__(self) -> int:
        return hash((self.stream_id, tuple(self.attrs)))

    # ---- host <-> device conversion -------------------------------------

    def empty_batch(self, capacity: int) -> EventBatch:
        cols = {
            name: jnp.zeros((capacity,), dtype=PHYSICAL_DTYPE[t])
            for name, t in self.attrs
        }
        return EventBatch(
            ts=jnp.zeros((capacity,), dtype=jnp.int64),
            kind=jnp.zeros((capacity,), dtype=jnp.int8),
            valid=jnp.zeros((capacity,), dtype=jnp.bool_),
            cols=cols,
        )

    def to_batch(
        self,
        timestamps: Sequence[int],
        rows: Sequence[Sequence[Any]],
        interner: InternTable,
        capacity: int | None = None,
        kinds: Sequence[int] | None = None,
    ) -> EventBatch:
        """Pack host events into a padded columnar batch (numpy staging)."""
        n = len(rows)
        cap = capacity if capacity is not None else n
        if n > cap:
            raise ValueError(f"{n} events exceed batch capacity {cap}")
        ts = np.zeros((cap,), dtype=np.int64)
        ts[:n] = np.asarray(list(timestamps), dtype=np.int64)
        kind = np.zeros((cap,), dtype=np.int8)
        if kinds is not None:
            kind[:n] = np.asarray(list(kinds), dtype=np.int8)
        valid = np.zeros((cap,), dtype=np.bool_)
        valid[:n] = True
        for i, r in enumerate(rows):
            if len(r) != len(self.attrs):
                raise ValueError(
                    f"stream '{self.stream_id}' expects {len(self.attrs)} "
                    f"attributes {self.attr_names}, got {len(r)}: {r!r}"
                )
        cols: dict[str, jax.Array] = {}
        for j, (name, t) in enumerate(self.attrs):
            dt = PHYSICAL_DTYPE[t]
            arr = np.full((cap,), null_value(t), dtype=np.dtype(dt))
            for i in range(n):
                v = rows[i][j]
                if t in (AttrType.STRING, AttrType.OBJECT):
                    arr[i] = interner.intern(v)
                elif v is None:
                    arr[i] = null_value(t)
                else:
                    arr[i] = v
            cols[name] = jnp.asarray(arr)
        return EventBatch(
            ts=jnp.asarray(ts), kind=jnp.asarray(kind), valid=jnp.asarray(valid), cols=cols
        )

    def to_batch_cols(
        self,
        timestamps: np.ndarray,
        cols: dict[str, np.ndarray],
        interner: InternTable,
        capacity: int | None = None,
    ) -> EventBatch:
        """Vectorized columnar packing: numpy arrays -> device batch.

        String/object columns may be pre-interned int arrays or object arrays
        (interned via np.unique — one table lookup per distinct value). This is
        the high-throughput ingest path; `to_batch` is the per-row convenience.
        """
        ts = np.asarray(timestamps, dtype=np.int64)
        n = ts.shape[0]
        cap = capacity if capacity is not None else n
        if n > cap:
            raise ValueError(f"{n} events exceed batch capacity {cap}")
        out_ts = np.zeros((cap,), dtype=np.int64)
        out_ts[:n] = ts
        valid = np.zeros((cap,), dtype=np.bool_)
        valid[:n] = True
        out_cols: dict[str, jax.Array] = {}
        for name, t in self.attrs:
            dt = np.dtype(PHYSICAL_DTYPE[t])
            src = np.asarray(cols[name])
            if t in (AttrType.STRING, AttrType.OBJECT) and src.dtype.kind in "OUS":
                if t is AttrType.OBJECT or src.dtype.kind == "O":
                    # objects may not be orderable (np.unique sorts) — intern
                    # per item like the row path
                    src = np.asarray(
                        [interner.intern(v) for v in src.tolist()], dtype=dt
                    )
                else:
                    uniq, inv = np.unique(src, return_inverse=True)
                    ids = np.asarray(
                        [interner.intern(v) for v in uniq.tolist()], dtype=dt
                    )
                    src = ids[inv]
            arr = np.full((cap,), null_value(t), dtype=dt)
            arr[:n] = src.astype(dt)
            out_cols[name] = jnp.asarray(arr)
        return EventBatch(
            ts=jnp.asarray(out_ts),
            kind=jnp.zeros((cap,), dtype=jnp.int8),
            valid=jnp.asarray(valid),
            cols=out_cols,
        )

    def packed_codec(self, capacity: int):
        """Single-transfer ingest codec: the host packs timestamps + all
        columns into ONE contiguous byte buffer; a jitted device program
        bitcast-splits it back into the columnar lanes. One host->device
        transfer per batch instead of one per column — the dominant cost when
        the device sits behind a network tunnel."""
        cache = self.__dict__.setdefault("_packed_codecs", {})
        cached = cache.get(capacity)
        if cached is not None:
            return cached
        import jax

        cap = int(capacity)
        sections: list[tuple[str, np.dtype]] = [("__ts__", np.dtype(np.int64))]
        for name, t in self.attrs:
            sections.append((name, np.dtype(PHYSICAL_DTYPE[t])))
        offsets = []
        off = 0
        for _name, dt in sections:
            offsets.append(off)
            off += cap * dt.itemsize
        total = off

        def encode(timestamps: np.ndarray, cols: dict, n: int) -> np.ndarray:
            buf = np.zeros((total,), dtype=np.uint8)
            for (name, dt), o in zip(sections, offsets):
                dst = buf[o : o + cap * dt.itemsize].view(dt)
                src = timestamps if name == "__ts__" else cols[name]
                dst[:n] = src[:n].astype(dt, copy=False)
            return buf

        @jax.jit
        def decode(buf, n):
            cols_out = {}
            ts = None
            for (name, dt), o in zip(sections, offsets):
                arr = _bitcast_split(buf, o, cap, dt)
                if name == "__ts__":
                    ts = arr
                else:
                    cols_out[name] = arr
            valid = jnp.arange(cap, dtype=jnp.int32) < n
            return EventBatch(
                ts=ts,
                kind=jnp.zeros((cap,), jnp.int8),
                valid=valid,
                cols=cols_out,
            )

        codec = (encode, decode)
        cache[capacity] = codec
        return codec

    def propose_narrow(
        self,
        timestamps: np.ndarray,
        cols: dict,
        keep: frozenset | None = None,
        margin: int = 4,
    ) -> dict:
        """Sample-driven narrow wire dtypes: for each integer lane (and the
        ts-delta lane), the smallest dtype whose range covers `margin`x the
        sample's extremes. Used once at fused-ingest engagement; a later
        batch that does not fit raises WireNarrowMisfit and the caller falls
        back to the full-width wire (one rebuild, then permanent)."""
        narrow: dict[str, np.dtype] = {}

        def pick(lo: int, hi: int, wide: np.dtype) -> np.dtype | None:
            for nd in (np.int16, np.int32):
                dt = np.dtype(nd)
                if dt.itemsize >= wide.itemsize:
                    return None
                info = np.iinfo(dt)
                if lo * margin >= info.min and hi * margin <= info.max:
                    return dt
            return None

        n = len(timestamps)
        if n:
            # tsd rides as CONSECUTIVE diffs (decode reconstructs with a
            # device cumsum), so steady event streams narrow to int8/int16
            # even when the whole batch spans more than the dtype's range
            d = np.diff(timestamps[:n].astype(np.int64), prepend=timestamps[0])
            lo, hi = int(d.min()), int(d.max())
            for nd in (np.int8, np.int16):
                info = np.iinfo(nd)
                if lo * margin >= info.min and hi * margin <= info.max:
                    narrow["__tsd__"] = np.dtype(nd)
                    break
        for name, t in self.attrs:
            if keep is not None and name not in keep:
                continue
            wide = np.dtype(PHYSICAL_DTYPE[t])
            if wide.kind != "i" or name not in cols or n == 0:
                continue
            src = np.asarray(cols[name])[:n]
            if src.dtype.kind not in "iu":
                continue  # un-interned strings etc. — leave wide
            got = pick(int(src.min()), int(src.max()), wide)
            if got is not None:
                narrow[name] = got
        return narrow

    def wire_codec(
        self,
        capacity: int,
        keep: frozenset | None = None,
        narrow: dict | None = None,
    ):
        """Projected/narrowed single-transfer codec for fused ingest.

        Cuts wire bytes/event — the dominant cost through a bandwidth-limited
        tunnel — three ways vs `packed_codec`:
        - timestamps ride as int32 (or int16, see below) deltas from a
          per-batch int64 base (the caller guarantees the span fits; a
          micro-batch spanning >24 days of millis falls back to the wide
          path);
        - columns not in `keep` (attributes no subscriber of the junction
          ever reads, from Scope.used_keys) are not shipped at all; decode
          fills them with the null sentinel so schema shape is preserved;
        - `narrow` maps lane names ("__tsd__" or attribute names) to smaller
          integer dtypes chosen from a data sample (propose_narrow); encode
          verifies every value fits and raises WireNarrowMisfit otherwise,
          decode upcasts back to the physical dtype.

        - `narrow` entries may also be the richer encoding tuples of
          core/wire.py — ("dict", code_dtype, card) per-chunk dictionaries,
          ("delta", dtype) base+diff columns, ("bitpack",) 1-bit bools —
          chosen statically by the analysis package (`@app:wire` hints,
          WireSpec) rather than sampled; every one is guarded by the same
          WireNarrowMisfit -> full-width-rebuild fallback.

        encode(ts, cols, n) -> (buf uint8[total], base int64)
        decode(buf, n, base) -> EventBatch
        """
        from siddhi_tpu.core.wire import build_codec

        narrow = narrow or {}
        key = (
            capacity,
            keep,
            tuple(sorted((k, str(v)) for k, v in narrow.items())),
        )
        cache = self.__dict__.setdefault("_wire_codecs", {})
        cached = cache.get(key)
        if cached is not None:
            return cached
        codec = build_codec(self, capacity, keep, narrow)
        cache[key] = codec
        return codec

    def d2h_codec(self, capacity: int):
        """Single-transfer device->host codec: a jitted pack bitcasts every
        lane of an EventBatch into ONE contiguous uint8 buffer, so the host
        readback is one PJRT transfer instead of one per lane — behind a
        tunneled relay each transfer pays its own round-trip share (measured
        ~10 ms per extra lane on a degraded relay).
        pack(batch) -> u8[total]; unpack(host_buf) -> (ts, kind, valid, cols).
        """
        cache = self.__dict__.setdefault("_d2h_codecs", {})
        cached = cache.get(capacity)
        if cached is not None:
            return cached
        cap = int(capacity)
        sections: list[tuple[str, np.dtype]] = [
            ("__ts__", np.dtype(np.int64)),
            ("__kind__", np.dtype(np.int8)),
            ("__valid__", np.dtype(np.uint8)),
        ]
        for name, t in self.attrs:
            sections.append((name, np.dtype(PHYSICAL_DTYPE[t])))
        # widest lanes first: every section offset is then a multiple of its
        # itemsize for ANY capacity, so the host .view() slices stay aligned
        sections.sort(key=lambda s: -s[1].itemsize)
        offsets = []
        off = 0
        for _name, dt in sections:
            offsets.append(off)
            off += cap * dt.itemsize
        total = off

        @jax.jit
        def pack(batch: EventBatch):
            segs = []
            for name, dt in sections:
                if name == "__ts__":
                    x = batch.ts
                elif name == "__kind__":
                    x = batch.kind
                elif name == "__valid__":
                    x = batch.valid.astype(jnp.uint8)
                else:
                    x = batch.cols[name]
                if x.dtype == jnp.bool_:
                    x = x.astype(jnp.uint8)  # bitcast refuses bool
                u8 = jax.lax.bitcast_convert_type(x, jnp.uint8)
                segs.append(u8.reshape(-1))
            return jnp.concatenate(segs)

        def unpack(buf: np.ndarray):
            out = {}
            for (name, dt), o in zip(sections, offsets):
                out[name] = buf[o : o + cap * dt.itemsize].view(dt)
            ts = out.pop("__ts__")
            kind = out.pop("__kind__")
            valid = out.pop("__valid__").astype(bool)
            return ts, kind, valid, out

        codec = (pack, unpack, total)
        cache[capacity] = codec
        return codec

    def from_batch(
        self, batch: EventBatch, interner: InternTable
    ) -> list[tuple[int, int, tuple]]:
        """Unpack valid rows to host `(timestamp, kind, data_tuple)` triples."""
        # ONE device->host transfer for all lanes: a pytree device_get moves
        # one array per lane, and each transfer pays its own relay round-trip
        # share on tunneled backends. Host decode rides the vectorized
        # column_lists path (one compaction + bulk .tolist() per column).
        pack, unpack, _total = self.d2h_codec(batch.capacity)
        buf = np.asarray(pack(batch))
        ts, kind, valid, host_cols = unpack(buf)
        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return []
        return rows_from_arrays(
            self,
            ts[idx],
            kind[idx],
            {n: c[idx] for n, c in host_cols.items()},
            idx.size,
            interner,
        )


def column_lists(schema, cols: dict, n: int, interner) -> list[list]:
    """Vectorized host decode of n packed rows into per-attribute Python
    lists (bulk .tolist() + fix-ups; ~10x faster than per-row decode_value)."""
    col_lists = []
    for name, t in schema.attrs:
        arr = np.asarray(cols[name])[:n]
        if t in (AttrType.STRING, AttrType.OBJECT):
            col_lists.append(interner.lookup_many(arr))
        elif t is AttrType.BOOL:
            col_lists.append(arr.astype(bool).tolist())
        elif t in (AttrType.FLOAT, AttrType.DOUBLE):
            vals = arr.tolist()
            nan = np.isnan(arr)
            if nan.any():
                for i in np.nonzero(nan)[0]:
                    vals[i] = None
            col_lists.append(vals)
        else:
            vals = arr.tolist()
            nv = null_value(t)
            if nv is not None:
                isnull = arr == np.asarray(nv, arr.dtype)
                if isnull.any():
                    for i in np.nonzero(isnull)[0]:
                        vals[i] = None
            col_lists.append(vals)
    return col_lists


def rows_from_arrays(
    schema, ts: np.ndarray, kind: np.ndarray, cols: dict, n: int, interner
) -> list[tuple[int, int, tuple]]:
    """Vectorized host decode of n packed rows -> (ts, kind, data) triples."""
    if n <= 0:
        return []
    col_lists = column_lists(schema, cols, n, interner)
    # .tolist() already yields Python ints; zip builds the triples directly
    ts_l = np.asarray(ts)[:n].tolist()
    if isinstance(kind, int):  # single-kind fast path (deliver drain)
        kind_l = [kind] * n
    else:
        kind_l = np.asarray(kind)[:n].tolist()
    return list(zip(ts_l, kind_l, zip(*col_lists)))


def events_from_arrays(
    schema, ts: np.ndarray, cols: dict, n: int, interner
) -> list:
    """Vectorized host decode straight to Event objects (single-kind fused
    egress fast path — skips the triple intermediate entirely)."""
    if n <= 0:
        return []
    import functools

    col_lists = column_lists(schema, cols, n, interner)
    ts_l = np.asarray(ts)[:n].tolist()
    mk = functools.partial(tuple.__new__, Event)
    return list(map(mk, zip(ts_l, zip(*col_lists))))


def decode_value(v, t: AttrType, interner: InternTable):
    """Device scalar -> host Python value (reversing interning / null sentinels)."""
    if t in (AttrType.STRING, AttrType.OBJECT):
        return interner.lookup(int(v))
    if t is AttrType.BOOL:
        return bool(v)
    if t in (AttrType.FLOAT, AttrType.DOUBLE):
        f = float(v)
        return None if np.isnan(f) else f
    iv = int(v)
    if iv == int(null_value(t)):
        return None
    return iv


def concat_batches(a: EventBatch, b: EventBatch) -> EventBatch:
    """Concatenate two batches of the same stream (static shapes)."""
    return EventBatch(
        ts=jnp.concatenate([a.ts, b.ts]),
        kind=jnp.concatenate([a.kind, b.kind]),
        valid=jnp.concatenate([a.valid, b.valid]),
        cols={n: jnp.concatenate([a.cols[n], b.cols[n]]) for n in a.cols},
    )
