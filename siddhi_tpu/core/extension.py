"""Extension registry — the analog of the reference's @Extension SPI.

Reference: siddhi-annotations .../annotation/Extension.java +
core/util/SiddhiExtensionLoader.java:47-130. Java classpath scanning becomes
decorator registration into per-kind registries keyed `namespace:name`.
"""

from __future__ import annotations

from typing import Callable, Optional

# kind -> {"ns:name" | "name": factory}
_REGISTRY: dict[str, dict[str, object]] = {
    "function": {},
    "window": {},
    "aggregator": {},
    "stream_processor": {},
    "stream_function": {},
    "source": {},
    "sink": {},
    "source_mapper": {},
    "sink_mapper": {},
    "store": {},
    "script": {},
}


def extension(kind: str, name: str, namespace: Optional[str] = None) -> Callable:
    """Register an extension factory, e.g.

        @extension("function", "plus", namespace="custom")
        def _plus(params, scope): ...
    """

    def deco(obj):
        key = f"{namespace}:{name}" if namespace else name
        reg = _REGISTRY.get(kind)
        if reg is None:
            raise KeyError(f"unknown extension kind '{kind}'")
        reg[key] = obj
        return obj

    return deco


def lookup(kind: str, name: str):
    return _REGISTRY[kind].get(name)


def lookup_function(name: str):
    return _REGISTRY["function"].get(name)
