"""Expression compiler: query-api Expression AST -> vectorized jax functions.

The analog of the reference's compiled scalar executor trees
(reference: core/executor/ExpressionExecutor.java and the per-type classes built by
core/util/parser/ExpressionParser.java:215-530) — except each compiled node maps a
whole columnar batch at once: `fn(env) -> Array` where `env` supplies `[B]`- (or
`[B, W]`- for join probes) shaped attribute columns. Type promotion follows the
reference's executor-selection matrix (DOUBLE > FLOAT > LONG > INT); integer
divide/mod use Java truncation semantics via lax.div/lax.rem.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.types import (
    NUMERIC_TYPES,
    PHYSICAL_DTYPE,
    AttrType,
    InternTable,
    null_value,
    promote,
)
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)

# Canonical variable key: (stream_ref, stream_index, attribute). stream_ref is the
# scope-canonicalized alias; TS_ATTR keys the timestamp lane.
VarKey = tuple[str, Optional[int], str]
TS_ATTR = "__ts__"
VALID_ATTR = "__valid__"


class Env:
    """Runtime (trace-time) column provider for a compiled expression."""

    def __init__(
        self,
        columns: dict[VarKey, jnp.ndarray],
        now: jnp.ndarray | None = None,
        tables: dict[str, dict] | None = None,
    ):
        self.columns = columns
        self._now = now
        self.tables = tables or {}

    def read(self, key: VarKey) -> jnp.ndarray:
        try:
            return self.columns[key]
        except KeyError:
            raise KeyError(f"env missing column {key}; has {list(self.columns)}") from None

    def now(self) -> jnp.ndarray:
        if self._now is None:
            raise ValueError("this site does not provide currentTimeMillis")
        return self._now


@dataclasses.dataclass
class CompiledExpr:
    type: AttrType
    fn: Callable[[Env], jnp.ndarray]
    # compile-time constant value, when statically known (for window params etc.)
    const: object = None
    is_const: bool = False

    def __call__(self, env: Env) -> jnp.ndarray:
        return self.fn(env)


class Scope:
    """Compile-time name resolution: Variable -> (VarKey, AttrType).

    Concrete scopes are built by the query parser layer for each expression site
    (filter over one stream, join condition over two, pattern over state refs,
    having over selector outputs...).
    """

    def __init__(self, interner: InternTable, default_ref: str | None = None):
        self.interner = interner
        self.default_ref = default_ref
        # every VarKey any expression compiled against this scope (or a child)
        # resolved — env builders consult this to materialize indexed-capture
        # columns (e1[3], e2[last]) including out-of-range/-negative indices
        self.used_keys: set[VarKey] = set()
        # pattern-node filters resolve unqualified attrs to the CURRENT event's
        # stream even when earlier state refs carry the same attribute
        # (reference: MatchingMetaInfoHolder default stream-event index)
        self.prefer_default = False
        # in-table conditions resolve unqualified attrs against the OUTER
        # (stream) scope before the table's own columns (reference:
        # CollectionExpressionParser matching-side resolution)
        self.prefer_parent = False
        self._streams: dict[str, dict[str, AttrType]] = {}
        self._tables: dict[str, object] = {}
        self._parent: Scope | None = None

    def add_table(self, table) -> "Scope":
        """Register an InMemoryTable handle for `in <table>` conditions."""
        self._tables[table.table_id] = table
        return self

    def resolve_table(self, name: str):
        scope: Scope | None = self
        while scope is not None:
            if name in scope._tables:
                return scope._tables[name]
            scope = scope._parent
        return None

    def add_stream(self, ref: str, attrs: dict[str, AttrType]) -> "Scope":
        self._streams[ref] = dict(attrs)
        if self.default_ref is None:
            self.default_ref = ref
        return self

    def child(self) -> "Scope":
        c = Scope(self.interner, self.default_ref)
        c._parent = self
        return c

    def refs(self) -> list[str]:
        return list(self._streams)

    def record_key(self, key: VarKey) -> None:
        # record at every level so a compile site can read exactly the keys
        # ITS expressions resolved from its own child scope, while the root
        # accumulates the full set for env builders
        scope: Scope | None = self
        while scope is not None:
            scope.used_keys.add(key)
            scope = scope._parent

    def root_used_keys(self) -> set[VarKey]:
        scope: Scope = self
        while scope._parent is not None:
            scope = scope._parent
        return scope.used_keys

    def resolve(self, var: Variable) -> tuple[VarKey, AttrType]:
        key, t = self._resolve(var)
        self.record_key(key)
        return key, t

    def _resolve(self, var: Variable) -> tuple[VarKey, AttrType]:
        if var.stream_id is not None:
            scope: Scope | None = self
            while scope is not None:
                if var.stream_id in scope._streams:
                    attrs = scope._streams[var.stream_id]
                    if var.attribute not in attrs:
                        raise KeyError(
                            f"no attribute '{var.attribute}' in '{var.stream_id}'"
                        )
                    return (
                        (var.stream_id, var.stream_index, var.attribute),
                        attrs[var.attribute],
                    )
                scope = scope._parent
            raise KeyError(f"unknown stream reference '{var.stream_id}'")
        # unqualified: unique attribute across in-scope streams (reference
        # resolves unprefixed attrs the same way)
        if self.prefer_parent and self._parent is not None:
            try:
                return self._parent.resolve(var)
            except KeyError:
                pass
        if self.prefer_default and self.default_ref is not None:
            scope = self
            while scope is not None:
                attrs = scope._streams.get(self.default_ref)
                if attrs is not None and var.attribute in attrs:
                    return (
                        (self.default_ref, var.stream_index, var.attribute),
                        attrs[var.attribute],
                    )
                scope = scope._parent
        scope = self
        while scope is not None:
            hits = [
                (ref, attrs[var.attribute])
                for ref, attrs in scope._streams.items()
                if var.attribute in attrs
            ]
            if len(hits) > 1:
                raise KeyError(f"ambiguous attribute '{var.attribute}' in {sorted(r for r, _ in hits)}")
            if hits:
                ref, t = hits[0]
                return (ref, var.stream_index, var.attribute), t
            scope = scope._parent
        raise KeyError(f"unknown attribute '{var.attribute}'")

    def ts_key(self, ref: str | None = None) -> VarKey:
        return (ref or self.default_ref, None, TS_ATTR)


def _cast(x: jnp.ndarray, t: AttrType) -> jnp.ndarray:
    return x.astype(PHYSICAL_DTYPE[t])


def _const_expr(value, t: AttrType, interner: InternTable) -> CompiledExpr:
    # numpy (NOT jnp): a concrete jax.Array captured as a jaxpr const forces
    # the PJRT dispatch path off its fast lane on some backends (measured
    # ~2.5 ms/dispatch process-wide on tunneled TPUs); numpy consts embed as
    # HLO literals and stay on the fast path.
    if t in (AttrType.STRING, AttrType.OBJECT):
        dev = np.asarray(interner.intern(value), dtype=np.int32)
    elif value is None:
        dev = np.asarray(null_value(t), dtype=PHYSICAL_DTYPE[t])
    else:
        dev = np.asarray(value, dtype=PHYSICAL_DTYPE[t])
    return CompiledExpr(t, lambda env: dev, const=value, is_const=True)


def _arith(op_name: str, le: CompiledExpr, re_: CompiledExpr) -> CompiledExpr:
    t = promote(le.type, re_.type)

    def fn(env: Env) -> jnp.ndarray:
        a, b = _cast(le(env), t), _cast(re_(env), t)
        if op_name == "add":
            return a + b
        if op_name == "sub":
            return a - b
        if op_name == "mul":
            return a * b
        if op_name == "div":
            if t in (AttrType.INT, AttrType.LONG):
                return lax.div(a, b)  # Java truncating integer division
            return a / b
        if op_name == "mod":
            return lax.rem(a, b)  # Java remainder: sign of dividend
        raise AssertionError(op_name)

    const = None
    is_const = le.is_const and re_.is_const
    if is_const:
        py = {"add": lambda a, b: a + b, "sub": lambda a, b: a - b,
              "mul": lambda a, b: a * b,
              "div": (lambda a, b: int(a / b) if t in (AttrType.INT, AttrType.LONG) else a / b),
              "mod": lambda a, b: a - b * int(a / b) if t in (AttrType.INT, AttrType.LONG) else a % b}
        try:
            const = py[op_name](le.const, re_.const)
        except Exception:
            is_const = False
    return CompiledExpr(t, fn, const=const, is_const=is_const)


_CMP = {
    CompareOp.LT: jnp.less,
    CompareOp.LE: jnp.less_equal,
    CompareOp.GT: jnp.greater,
    CompareOp.GE: jnp.greater_equal,
    CompareOp.EQ: jnp.equal,
    CompareOp.NEQ: jnp.not_equal,
}


def _notnull(v: jnp.ndarray, t: AttrType):
    """Mask of rows whose value is NOT the type's null encoding."""
    if t in (AttrType.FLOAT, AttrType.DOUBLE):
        return ~jnp.isnan(v)
    if t in (AttrType.INT, AttrType.LONG):
        return v != np.asarray(null_value(t), dtype=v.dtype)
    if t in (AttrType.STRING, AttrType.OBJECT):
        return v != 0
    return True  # BOOL: never null


def _compare(op: CompareOp, le: CompiledExpr, re_: CompiledExpr) -> CompiledExpr:
    lt, rt = le.type, re_.type
    if lt in NUMERIC_TYPES and rt in NUMERIC_TYPES:
        t = promote(lt, rt)

        def fn(env: Env) -> jnp.ndarray:
            lv, rv = le(env), re_(env)
            # a null operand makes ANY comparison false, NEQ included
            # (reference: CompareConditionExpressionExecutor.java:42)
            ok = _notnull(lv, lt) & _notnull(rv, rt)
            return _CMP[op](_cast(lv, t), _cast(rv, t)) & ok

    elif lt == rt and lt in (AttrType.BOOL, AttrType.STRING, AttrType.OBJECT):
        if op not in (CompareOp.EQ, CompareOp.NEQ):
            raise TypeError(f"operator {op.value} not defined for {lt!r}")

        def fn(env: Env) -> jnp.ndarray:
            lv, rv = le(env), re_(env)
            ok = _notnull(lv, lt) & _notnull(rv, rt)
            return _CMP[op](lv, rv) & ok

    else:
        raise TypeError(f"cannot compare {lt!r} {op.value} {rt!r}")
    return CompiledExpr(AttrType.BOOL, fn)


def _require_bool(c: CompiledExpr, what: str) -> None:
    if c.type is not AttrType.BOOL:
        raise TypeError(f"{what} requires BOOL, got {c.type!r}")


def compile_expression(expr: Expression, scope: Scope) -> CompiledExpr:
    """Recursively compile an expression tree against a name-resolution scope."""
    if isinstance(expr, Constant):
        return _const_expr(expr.value, expr.type, scope.interner)

    if isinstance(expr, Variable):
        key, t = scope.resolve(expr)
        return CompiledExpr(t, lambda env, k=key: env.read(k))

    if isinstance(expr, Add):
        return _arith("add", compile_expression(expr.left, scope), compile_expression(expr.right, scope))
    if isinstance(expr, Subtract):
        return _arith("sub", compile_expression(expr.left, scope), compile_expression(expr.right, scope))
    if isinstance(expr, Multiply):
        return _arith("mul", compile_expression(expr.left, scope), compile_expression(expr.right, scope))
    if isinstance(expr, Divide):
        return _arith("div", compile_expression(expr.left, scope), compile_expression(expr.right, scope))
    if isinstance(expr, Mod):
        return _arith("mod", compile_expression(expr.left, scope), compile_expression(expr.right, scope))

    if isinstance(expr, Compare):
        return _compare(expr.op, compile_expression(expr.left, scope), compile_expression(expr.right, scope))

    if isinstance(expr, And):
        le, re_ = compile_expression(expr.left, scope), compile_expression(expr.right, scope)
        _require_bool(le, "and"), _require_bool(re_, "and")
        return CompiledExpr(AttrType.BOOL, lambda env: le(env) & re_(env))
    if isinstance(expr, Or):
        le, re_ = compile_expression(expr.left, scope), compile_expression(expr.right, scope)
        _require_bool(le, "or"), _require_bool(re_, "or")
        return CompiledExpr(AttrType.BOOL, lambda env: le(env) | re_(env))
    if isinstance(expr, Not):
        ce = compile_expression(expr.expression, scope)
        _require_bool(ce, "not")
        return CompiledExpr(AttrType.BOOL, lambda env: ~ce(env))

    if isinstance(expr, IsNull):
        if expr.expression is not None:
            ce = compile_expression(expr.expression, scope)
            return CompiledExpr(AttrType.BOOL, _is_null_fn(ce))
        # stream-null form (`S1 is null` in patterns): the pattern engine
        # provides a per-state arrival flag column.
        key = (expr.stream_id, expr.stream_index, "__arrived__")
        scope.record_key(key)
        return CompiledExpr(AttrType.BOOL, lambda env, k=key: ~env.read(k))

    if isinstance(expr, In):
        table = scope.resolve_table(expr.source_id)
        if table is None:
            raise KeyError(
                f"'in {expr.source_id}': no such table in scope"
            )
        inner_scope = scope.child()
        inner_scope.add_stream(expr.source_id, table.schema.attr_types)
        inner_scope.prefer_parent = True
        cond = compile_expression(expr.expression, inner_scope)
        _require_bool(cond, "in-table condition")
        tid = table.table_id

        def fn(env: Env) -> jnp.ndarray:
            state = env.tables.get(tid)
            if state is None:
                raise KeyError(
                    f"table '{tid}' state not provided at this site"
                )
            # probe rows [B] -> [B,1]; table rows -> [1,C]; any-match over C
            cols2 = {k: v[:, None] for k, v in env.columns.items()}
            cols2.update(
                {(tid, None, n): v[None, :] for n, v in state["cols"].items()}
            )
            cols2[(tid, None, TS_ATTR)] = state["ts"][None, :]
            env2 = Env(cols2, now=env._now, tables=env.tables)
            pair = cond(env2) & state["valid"][None, :]
            return pair.any(axis=1)

        return CompiledExpr(AttrType.BOOL, fn)

    if isinstance(expr, AttributeFunction):
        return _compile_function(expr, scope)

    raise TypeError(f"cannot compile expression node {type(expr).__name__}")


def _is_null_fn(ce: CompiledExpr):
    t = ce.type

    def fn(env: Env) -> jnp.ndarray:
        v = ce(env)
        if t in (AttrType.FLOAT, AttrType.DOUBLE):
            return jnp.isnan(v)
        if t in (AttrType.STRING, AttrType.OBJECT):
            return v == 0
        if t in (AttrType.INT, AttrType.LONG):
            return v == np.asarray(null_value(t), dtype=v.dtype)
        return jnp.zeros(jnp.shape(v), dtype=jnp.bool_)  # BOOL: never null

    return fn


# ---------------------------------------------------------------------------
# built-in scalar functions
# (reference: core/executor/function/*FunctionExecutor.java — ~20 built-ins)
# ---------------------------------------------------------------------------

_TYPE_NAMES = {
    "string": AttrType.STRING,
    "int": AttrType.INT,
    "long": AttrType.LONG,
    "float": AttrType.FLOAT,
    "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL,
    "object": AttrType.OBJECT,
}

# Aggregator names are handled by the selector layer, never here.
AGGREGATOR_NAMES = {
    "sum", "avg", "count", "min", "max", "stdDev", "stddev",
    "distinctCount", "distinctcount", "minForever", "minforever",
    "maxForever", "maxforever",
}


def is_aggregator(expr: Expression) -> bool:
    return (
        isinstance(expr, AttributeFunction)
        and expr.namespace is None
        and expr.name in AGGREGATOR_NAMES
    )


def _compile_function(expr: AttributeFunction, scope: Scope) -> CompiledExpr:
    if is_aggregator(expr):
        raise TypeError(
            f"aggregator '{expr.name}' is only valid in a select clause"
        )
    name = (f"{expr.namespace}:{expr.name}" if expr.namespace else expr.name)
    params = expr.parameters

    if name in ("cast", "convert"):
        if len(params) != 2 or not isinstance(params[1], Constant):
            raise TypeError(f"{name}(value, 'type') requires a constant type name")
        target = _TYPE_NAMES.get(str(params[1].value).lower())
        if target is None:
            raise TypeError(f"unknown cast target {params[1].value!r}")
        src = compile_expression(params[0], scope)
        if target in (AttrType.STRING, AttrType.OBJECT) or src.type in (
            AttrType.STRING,
            AttrType.OBJECT,
        ):
            if src.type == target:
                return src
            if target is AttrType.STRING and src.type in NUMERIC_TYPES:
                # numeric -> string: host callback formats + interns the
                # distinct values per batch (reference:
                # ConvertFunctionExecutor string conversion)
                from siddhi_tpu.utils.backend import host_callbacks_supported

                if not host_callbacks_supported():
                    raise NotImplementedError(
                        f"{name} to 'string' needs host-callback support, "
                        "which this backend does not provide"
                    )
                interner = scope.interner
                valid_key = (scope.default_ref, None, VALID_ATTR)
                is_int = src.type in (AttrType.INT, AttrType.LONG)
                src_null = _is_null_fn(src)

                def fn(env: Env, _src=src) -> jnp.ndarray:
                    v = _src(env)
                    try:
                        valid = jnp.broadcast_to(env.read(valid_key), jnp.shape(v))
                    except KeyError:
                        valid = jnp.ones(jnp.shape(v), dtype=jnp.bool_)
                    # null inputs convert to null, not to a sentinel's digits
                    # (reference: ConvertFunctionExecutor null propagation)
                    valid = valid & ~src_null(env)

                    def fmt(vals, mask):
                        import numpy as _np

                        flat = _np.asarray(vals).reshape(-1)
                        m = _np.asarray(mask).reshape(-1)
                        out = _np.zeros(flat.shape, dtype=_np.int32)
                        uniq = _np.unique(flat[m])
                        if is_int:
                            strings = [str(int(u)) for u in uniq.tolist()]
                        else:
                            # shortest round-trip form of the DEVICE precision
                            # (f32): widening through float64 repr would print
                            # garbage digits
                            strings = [
                                _np.format_float_positional(
                                    u, unique=True, trim="0"
                                )
                                for u in uniq
                            ]
                        id_arr = _np.array(
                            [interner.intern(s) for s in strings], dtype=_np.int32
                        )
                        if uniq.size:
                            idx = _np.searchsorted(uniq, flat[m])
                            out[m] = id_arr[idx]
                        return out.reshape(_np.shape(vals))

                    import jax
                    from jax.experimental import io_callback

                    return io_callback(
                        fmt,
                        jax.ShapeDtypeStruct(jnp.shape(v), jnp.int32),
                        v, valid,
                    )

                return CompiledExpr(AttrType.STRING, fn)
            raise NotImplementedError(
                f"{name} between {src.type!r} and {target!r} requires host egress"
            )
        if target is AttrType.BOOL or src.type is AttrType.BOOL:
            if src.type == target:
                return src
            raise TypeError(f"cannot {name} {src.type!r} to {target!r}")
        return CompiledExpr(target, lambda env: _cast(src(env), target))

    if name == "coalesce":
        compiled = [compile_expression(p, scope) for p in params]
        t = compiled[0].type
        if any(c.type != t for c in compiled):
            raise TypeError("coalesce requires homogeneous parameter types")

        def fn(env: Env) -> jnp.ndarray:
            out = compiled[-1](env)
            for c in reversed(compiled[:-1]):
                v = c(env)
                out = jnp.where(_is_null_fn(c)(env), out, v)
            return out

        return CompiledExpr(t, fn)

    if name == "ifThenElse":
        cond, a, b = (compile_expression(p, scope) for p in params)
        _require_bool(cond, "ifThenElse condition")
        if a.type in NUMERIC_TYPES and b.type in NUMERIC_TYPES:
            t = promote(a.type, b.type)
        elif a.type == b.type:
            t = a.type
        else:
            raise TypeError(f"ifThenElse branches {a.type!r} vs {b.type!r}")
        return CompiledExpr(
            t, lambda env: jnp.where(cond(env), _cast(a(env), t), _cast(b(env), t))
        )

    if name.startswith("instanceOf"):
        target = _TYPE_NAMES.get(name[len("instanceOf"):].lower())
        if target is None:
            raise TypeError(f"unknown function '{name}'")
        src = compile_expression(params[0], scope)
        matches = src.type == target
        isnull = _is_null_fn(src)
        return CompiledExpr(
            AttrType.BOOL,
            lambda env: (~isnull(env)) & np.asarray(matches),
        )

    if name in ("maximum", "minimum"):
        compiled = [compile_expression(p, scope) for p in params]
        t = compiled[0].type
        for c in compiled[1:]:
            t = promote(t, c.type)
        red = jnp.maximum if name == "maximum" else jnp.minimum

        def fn(env: Env) -> jnp.ndarray:
            out = _cast(compiled[0](env), t)
            for c in compiled[1:]:
                out = red(out, _cast(c(env), t))
            return out

        return CompiledExpr(t, fn)

    if name == "eventTimestamp":
        key = scope.ts_key()
        return CompiledExpr(AttrType.LONG, lambda env: env.read(key))

    if name == "currentTimeMillis":
        return CompiledExpr(AttrType.LONG, lambda env: env.now())

    if name == "UUID":
        # string generation cannot happen on device: a host callback mints
        # one UUID per VALID row and interns it (reference:
        # executor/function/UUIDFunctionExecutor). io_callback (not
        # pure_callback): minting is impure — it must never be CSE'd or
        # replayed, or duplicate/unrecorded ids would appear.
        from siddhi_tpu.utils.backend import host_callbacks_supported

        if not host_callbacks_supported():
            raise NotImplementedError(
                "UUID() needs host-callback support, which this backend "
                "does not provide"
            )
        interner = scope.interner
        valid_key = (scope.default_ref, None, VALID_ATTR)

        def fn(env: Env) -> jnp.ndarray:
            ts = env.read(scope.ts_key())
            try:
                valid = env.read(valid_key)
            except KeyError:
                valid = jnp.ones(jnp.shape(ts), dtype=jnp.bool_)

            def mint(v):
                import uuid as _uuid

                import numpy as _np

                flat = _np.asarray(v).reshape(-1)
                out = _np.zeros(flat.shape, dtype=_np.int32)  # padding: null id
                for i in _np.nonzero(flat)[0]:
                    out[i] = interner.intern(str(_uuid.uuid4()))
                return out.reshape(_np.shape(v))

            import jax
            from jax.experimental import io_callback

            return io_callback(
                mint,
                jax.ShapeDtypeStruct(jnp.shape(valid), jnp.int32),
                valid,
            )

        return CompiledExpr(AttrType.STRING, fn)

    if name == "default":
        src = compile_expression(params[0], scope)
        dflt = compile_expression(params[1], scope)
        if src.type != dflt.type and not (
            src.type in NUMERIC_TYPES and dflt.type in NUMERIC_TYPES
        ):
            raise TypeError(f"default({src.type!r}, {dflt.type!r}) type mismatch")
        t = src.type
        isnull = _is_null_fn(src)
        return CompiledExpr(
            t, lambda env: jnp.where(isnull(env), _cast(dflt(env), t), src(env))
        )

    from siddhi_tpu.core.extension import lookup_function  # cycle-free at call time

    ext = lookup_function(name)
    if ext is not None:
        return ext([compile_expression(p, scope) for p in params], scope)

    raise NotImplementedError(f"unknown function '{name}'")
