"""Pattern / sequence NFA engine over token matrices.

The reference implements temporal patterns as a per-event interpreter over linked
Pre/Post state-processor chains, each holding a `pendingStateEventList` of partial
matches (reference: query/input/stream/state/StreamPreStateProcessor.java:43-359,
StreamPostStateProcessor.java:29-140, CountPreStateProcessor.java:34-150,
LogicalPreStateProcessor.java:35, AbsentStreamPreStateProcessor.java:37-140).

Here the whole NFA lives in one fixed-capacity **token table** on device: every
partial match is a row holding (current slot, capture columns for every state
ref, occurrence counts, timestamps). Processing a micro-batch is a `lax.scan`
over event rows; each scan step runs a static, vectorized pass per NFA slot —
eligibility mask -> compiled condition over the token table -> capture/advance
scatter. `every` is modelled as *persistent* slots whose tokens fork into free
rows instead of being consumed (reference semantics: `every` re-arms via
nextEveryStatePreProcessor, StreamPostStateProcessor.java:100-120).

Count states `<m:n>` follow the reference's shared-token model exactly
(CountPatternTestCase 1-15 are golden tests): one token is simultaneously
absorbing at the count slot and pending at the next slot once min is reached
(`_eligible` count-skip), the next state is checked before absorption for the
same event (descending slot order, matching
PatternMultiProcessStreamReceiver's reversed eventSequence), a trailing count
emits at exactly min and is consumed, and min-0 counts forward/emit at arrival.

Deliberate deviations from the reference interpreter (documented, test-covered):
- token/capture capacity is static (`@app:patternCapacity`, `@app:countCapacity`)
  with overflow surfaced via aux flags, where the reference grows lists unboundedly;
- `every` over a count state arms a fresh virgin token when a token's count
  reaches min. The reference's addEveryState clone at that point shares its
  capture chains with the parent (StateEventCloner.copyStateEvent is shallow)
  and is never re-forwarded — a structural dead end no reference test covers —
  so the clean generation-chain semantics is used instead;
- emission order among tokens completing on the SAME event is lane order, not
  pending-list age order;
- counts absorb past the capture capacity on both execution paths (the
  occurrence counter keeps counting while capture writes drop), so `<m:>`
  with m above `@app:countCapacity` still fires — only the first `cap`
  occurrences are retrievable;
- absent states with a waiting time are supported standalone (`A -> not B for 5
  sec`) and inside logical elements (`A and not B for t` completes at the
  deadline once every present side arrived; `A or not B for t` completes via
  the present side immediately or at the deadline with the absent ref null —
  reference: AbsentLogicalPreStateProcessor, LogicalAbsentPatternTestCase
  testQueryAbsent11-16). Logical elements whose BOTH sides are absent
  (`not A for t1 and/or not B for t2`) complete on timers: AND at the later
  deadline iff neither side arrived inside its window; OR at each side's own
  deadline iff that side never arrived (an `every` generator fires once per
  clean side; non-every completes once at the earliest —
  LogicalAbsentPatternTestCase testQueryAbsent25-40, 46-50).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.lax as lax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.executor import (
    TS_ATTR,
    Env,
    Scope,
    compile_expression,
)
from siddhi_tpu.core.types import AttrType, InternTable, PHYSICAL_DTYPE, null_value
from siddhi_tpu.query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    Filter,
    LogicalStateElement,
    LogicalType,
    NextStateElement,
    StateElement,
    StateInputStream,
    StateStreamType,
    StreamStateElement,
)
from siddhi_tpu.ops.prefix import first_indices
from siddhi_tpu.ops.scatter import set_at as _set_at
from siddhi_tpu.query_api.expression import Expression

NO_TIMER = np.int64(np.iinfo(np.int64).max)

DEFAULT_TOKEN_CAPACITY = 128
DEFAULT_COUNT_CAPACITY = 8

# Test hook: force every pattern onto the per-event scan path (the batch
# kernels' differential oracle). Read at step-build time.
FORCE_SCAN = False


def _min_within(slot_ms, global_ms):
    """Effective within bound: a token dies when EITHER the slot's or the
    pattern-global within is exceeded (matching the scan path's kill list)."""
    if slot_ms is None:
        return global_ms
    if global_ms is None:
        return slot_ms
    return min(slot_ms, global_ms)


@dataclasses.dataclass
class Atom:
    """One stream obligation inside a slot (reference: a single
    Stream/AbsentStream state element)."""

    ref: str
    ref_idx: int
    stream_id: str
    filters: list  # raw Expression list, compiled in PatternProgram
    absent: bool = False
    waiting_ms: Optional[int] = None
    cap: int = 1  # occurrence capture capacity K


@dataclasses.dataclass
class Slot:
    """One linearized NFA state (reference: one Pre/Post state-processor pair)."""

    index: int
    atoms: list  # [Atom] — two entries for logical elements
    logical: Optional[LogicalType] = None
    min_count: int = 1
    max_count: int = 1  # -1 == unbounded
    persistent: bool = False  # `every` entry: matches fork, token stays
    within_ms: Optional[int] = None

    @property
    def is_count(self) -> bool:
        return not (self.min_count == 1 and self.max_count == 1)

    @property
    def is_absent(self) -> bool:
        return len(self.atoms) == 1 and self.atoms[0].absent


def _flatten_state(
    elem: StateElement,
    slots: list,
    refs: list,
    schemas: dict,
    count_cap: int,
    every_blocks: list,
) -> None:
    """Linearize the state-element tree into the slot chain (reference:
    StateInputStreamParser.parseInputStream recursive walk,
    util/parser/StateInputStreamParser.java:134-430)."""

    def new_atom(stream, absent=False, waiting=None, cap=1) -> Atom:
        sid = stream.stream_id
        if sid not in schemas:
            raise SiddhiAppCreationError(f"stream '{sid}' is not defined")
        ref = stream.alias
        if ref is None:
            # unaliased: referenceable by stream name when that stream appears
            # exactly once in the pattern; otherwise synthetic
            uses = sum(1 for r in refs if r.stream_id == sid)
            ref = sid if uses == 0 else f"__p{len(refs)}"
        if any(r.ref == ref for r in refs):
            raise SiddhiAppCreationError(f"duplicate pattern event reference '{ref}'")
        filters = [
            h.expression for h in stream.handlers if isinstance(h, Filter)
        ]
        if len(filters) != len(stream.handlers):
            raise SiddhiAppCreationError(
                "pattern sources support only filters (no windows/stream functions)"
            )
        a = Atom(ref, len(refs), sid, filters, absent=absent, waiting_ms=waiting, cap=cap)
        refs.append(a)
        return a

    if isinstance(elem, NextStateElement):
        first = len(slots)
        _flatten_state(elem.state, slots, refs, schemas, count_cap, every_blocks)
        _flatten_state(elem.next, slots, refs, schemas, count_cap, every_blocks)
        if elem.within_ms is not None:
            for s in slots[first:]:
                s.within_ms = s.within_ms or elem.within_ms
    elif isinstance(elem, EveryStateElement):
        first = len(slots)
        _flatten_state(elem.state, slots, refs, schemas, count_cap, every_blocks)
        if len(slots) == first + 1:
            # single-slot every: persistent generator slot (forks per match)
            slots[first].persistent = True
        elif len(slots) > first + 1:
            # multi-slot every BLOCK: re-arms when the block COMPLETES
            # (reference: EveryInnerStateRuntime wires the block's last post
            # processor's nextEveryStatePreProcessor back to the block's
            # first pre — matches are strictly serial, EveryPatternTestCase
            # testQuery5/7)
            every_blocks.append((first, len(slots) - 1))
        if elem.within_ms is not None:
            for s in slots[first:]:
                s.within_ms = s.within_ms or elem.within_ms
    elif isinstance(elem, CountStateElement):
        mx = elem.max_count
        cap = mx if 0 < mx <= count_cap else count_cap
        atom = new_atom(elem.stream.stream, cap=cap)
        slots.append(
            Slot(
                len(slots),
                [atom],
                min_count=elem.min_count,
                max_count=mx,
                within_ms=elem.within_ms,
            )
        )
    elif isinstance(elem, LogicalStateElement):
        atoms = []
        for side in (elem.left, elem.right):
            if isinstance(side, AbsentStreamStateElement):
                atoms.append(
                    new_atom(
                        side.stream, absent=True,
                        waiting=side.waiting_time_ms,
                    )
                )
            elif isinstance(side, StreamStateElement):
                atoms.append(new_atom(side.stream))
            else:
                raise SiddhiAppCreationError(
                    "'and'/'or' sides must be plain or absent streams"
                )
        if all(a.absent for a in atoms) and any(
            a.waiting_ms is None for a in atoms
        ):
            raise SiddhiAppCreationError(
                "a logical element with both sides absent needs "
                "'for <time>' on each side "
                "(reference: AbsentLogicalPreStateProcessor waiting times)"
            )
        slots.append(
            Slot(len(slots), atoms, logical=elem.type, within_ms=elem.within_ms)
        )
    elif isinstance(elem, AbsentStreamStateElement):
        if elem.waiting_time_ms is None:
            raise SiddhiAppCreationError(
                "a standalone absent stream needs 'for <time>' "
                "(reference: AbsentStreamPreStateProcessor waiting time)"
            )
        atom = new_atom(elem.stream, absent=True, waiting=elem.waiting_time_ms)
        slots.append(Slot(len(slots), [atom], within_ms=elem.within_ms))
    elif isinstance(elem, StreamStateElement):
        atom = new_atom(elem.stream)
        slots.append(Slot(len(slots), [atom], within_ms=elem.within_ms))
    else:
        raise SiddhiAppCreationError(f"unsupported state element {type(elem).__name__}")


class PatternProgram:
    """Compiled NFA: slot chain + per-atom conditions + token-table layout."""

    def __init__(
        self,
        state_stream: StateInputStream,
        schemas: dict[str, StreamSchema],
        interner: InternTable,
        token_capacity: int = DEFAULT_TOKEN_CAPACITY,
        count_capacity: int = DEFAULT_COUNT_CAPACITY,
    ):
        self.sequence = state_stream.type is StateStreamType.SEQUENCE
        self.within_ms = state_stream.within_ms
        self.T = token_capacity
        self.schemas = schemas
        self.interner = interner

        self.slots: list[Slot] = []
        self.refs: list[Atom] = []
        self.every_blocks: list[tuple[int, int]] = []
        _flatten_state(
            state_stream.state, self.slots, self.refs, schemas, count_capacity,
            self.every_blocks,
        )
        if not self.slots:
            raise SiddhiAppCreationError("empty pattern")

        # name-resolution scope over every ref (reference: each state's
        # MatchingMetaInfoHolder exposes all earlier stream events)
        self.scope = Scope(interner)
        for a in self.refs:
            self.scope.add_stream(a.ref, schemas[a.stream_id].attr_types)
        self.scope.default_ref = self.refs[0].ref

        # compiled per-atom condition: AND of the atom's filters, evaluated over
        # the token table with the current event broadcast as the atom's own ref.
        # _cond_keys records which VarKeys each slot's conditions read — the
        # count fast path gates on conditions being row-only (no token-table
        # dependence) for slots 0 and 1.
        self._conds = {}
        self._cond_keys: dict[tuple, set] = {}
        for slot in self.slots:
            for atom in slot.atoms:
                conds = []
                keys: set = set()
                for f in atom.filters:
                    s = self.scope.child()
                    s.default_ref = atom.ref
                    s.prefer_default = True
                    c = compile_expression(f, s)
                    if c.type is not AttrType.BOOL:
                        raise SiddhiAppCreationError("pattern filter must be boolean")
                    conds.append(c)
                    keys |= s.used_keys
                self._conds[(slot.index, atom.ref_idx)] = conds
                self._cond_keys[(slot.index, atom.ref_idx)] = keys

        self.stream_ids = sorted({a.stream_id for a in self.refs})
        self.needs_scheduler = any(
            a.waiting_ms is not None for a in self.refs
        )
        # keys read from the EMISSION buffer (selector/having/order-by) —
        # set by the owning runtime from the selector's child scope; None
        # means unknown, which disables projection (keep everything).
        # capture_keep() combines these with indexed keys and cross-ref
        # condition reads to project the token capture lanes (TPU gathers
        # and scatters run near one element per scalar-core cycle, so every
        # unread [T, cap] lane is pure wall-clock)
        self._capture_readers: Optional[frozenset] = None
        self._keep_cache = None
        # sequences with count slots carry an explicit per-token forwarding
        # lane (reference: SEQUENCE addState accepts ONE new state per event,
        # so next-slot pending membership is a contended, per-event win —
        # SequenceTestCase testQuery6/11). Patterns keep implicit count-skip.
        self._use_fwd = self.sequence and any(s.is_count for s in self.slots)

    # ---- capture projection ---------------------------------------------

    def set_capture_readers(self, keys: frozenset) -> None:
        """Declare the emission-buffer reader keys (selector/having/order-by).

        Must run before any state/kernel builder calls capture_keep(): a
        keep-set memoized earlier is left in place (state shapes must stay
        consistent across traces) and the missed projection is logged loudly
        instead of silently vanishing."""
        if self._keep_cache is not None:
            import logging

            logging.getLogger(__name__).warning(
                "pattern capture projection disabled: capture_keep() was "
                "memoized before set_capture_readers() — a state or kernel "
                "builder ran too early; all capture lanes stay materialized"
            )
            return
        self._capture_readers = frozenset(keys)

    def capture_keep(self):
        """Per-ref projection of the capture lanes: (keep_cols, ts_used).

        keep_cols[ref_idx] — attribute names whose captured values some
        compiled expression actually reads; every other attribute lane is
        never materialized in the token table or the emission buffer.
        ts_used[ref_idx] — whether the ref's captured-timestamp lane is read
        (selector/conditions) or structurally required (absent deadlines,
        next_timer reads caps ts[:, 0]).

        A key counts as a CAPTURE read when it is indexed (e1[2].price /
        e1[last]), recorded after pattern construction (selector / having /
        order-by resolve against the emission buffer), or recorded by a
        condition of a DIFFERENT ref (cross-ref reads see the partner's
        captures); an atom's own un-indexed keys read the live event, which
        the env builders substitute directly. Reference analog: every
        StateEvent carries all captured StreamEvents
        (event/state/StateEvent.java) — here only the read subset exists.
        """
        if self._keep_cache is not None:
            return self._keep_cache
        used = set(self.scope.root_used_keys())
        by_ref = {a.ref: a for a in self.refs}
        if self._capture_readers is None:
            needed = used  # owner never told us — keep everything
        else:
            cross = set()
            for (_slot_idx, ref_idx), keys in self._cond_keys.items():
                me = self.refs[ref_idx].ref
                cross |= {k for k in keys if k[0] != me}
            needed = (
                {k for k in used if k[1] is not None}
                | set(self._capture_readers)
                | cross
            )
        keep_cols = {a.ref_idx: set() for a in self.refs}
        ts_used = {
            a.ref_idx: bool(a.absent and a.waiting_ms is not None)
            for a in self.refs
        }
        for ref, _k, attr in needed:
            a = by_ref.get(ref)
            if a is None:
                continue
            if attr == TS_ATTR:
                ts_used[a.ref_idx] = True
            elif attr in self.schemas[a.stream_id].attr_types:
                keep_cols[a.ref_idx].add(attr)
        self._keep_cache = (keep_cols, ts_used)
        return self._keep_cache

    # ---- token table ----------------------------------------------------

    def init_state(self, now: int = 0):
        T = self.T
        keep_cols, _ts_used = self.capture_keep()
        caps = []
        for a in self.refs:
            schema = self.schemas[a.stream_id]
            cols = {
                name: jnp.full(
                    (T, a.cap), null_value(t), dtype=PHYSICAL_DTYPE[t]
                )
                for name, t in schema.attrs
                if name in keep_cols[a.ref_idx]
            }
            caps.append(
                {
                    "n": jnp.zeros((T,), dtype=jnp.int32),
                    "ts": jnp.zeros((T, a.cap), dtype=jnp.int64),
                    "cols": cols,
                }
            )
        tok = {
            "active": jnp.zeros((T,), dtype=jnp.bool_).at[0].set(True),
            "slot": jnp.zeros((T,), dtype=jnp.int32),
            # -1 == virgin (no event captured yet); 0 is a legitimate epoch ts
            "start_ts": jnp.full((T,), -1, dtype=jnp.int64),
            "entry_ts": jnp.full((T,), now, dtype=jnp.int64).at[1:].set(0),
            "caps": caps,
        }
        if self._use_fwd:
            # a min-0 count start state forwards its virgin immediately
            # (reference: CountPreStateProcessor.addState minCount==0 branch)
            fwd0 = self.slots[0].is_count and self.slots[0].min_count == 0
            tok["fwd"] = jnp.zeros((T,), dtype=jnp.bool_).at[0].set(fwd0)
        return tok

    # ---- environments ----------------------------------------------------

    def _synth_capture_cols(self, cols, col_of, ts_of, n_of, expand=None):
        """Synthesize columns for used capture keys outside the stored range:
        e1[k] with k >= cap reads null, e1[last]/e1[last-i] gather by the live
        occurrence count (reference: StateEvent.getStreamEvent(position) walks
        the chain and returns null past the end; `last` indexes the tail).

        col_of(a, attr) -> [N, cap], ts_of(a) -> [N, cap], n_of(a) -> [N].
        """
        by_ref = {a.ref: a for a in self.refs}
        for key in self.scope.root_used_keys():
            ref, k, attr = key
            a = by_ref.get(ref)
            if a is None or k is None or key in cols:
                continue
            n = n_of(a)
            if attr == "__arrived__":
                col = (n > k) if k >= 0 else (n >= -k)
            else:
                if attr == TS_ATTR:
                    arr = ts_of(a)
                    nv = np.asarray(null_value(AttrType.LONG), dtype=arr.dtype)
                else:
                    t = self.schemas[a.stream_id].attr_types.get(attr)
                    if t is None:
                        continue
                    arr = col_of(a, attr)
                    nv = np.asarray(null_value(t), dtype=arr.dtype)
                if k >= a.cap:
                    col = jnp.full(arr.shape[:1], nv, dtype=arr.dtype)
                elif k >= 0:
                    col = arr[:, k]
                else:
                    idx = n + k  # last == -1 -> n-1, last-i -> n-1-i
                    col = jnp.full(arr.shape[:1], nv, dtype=arr.dtype)
                    for i in range(a.cap):
                        col = jnp.where(idx == i, arr[:, i], col)
            cols[key] = expand(col) if expand else col

    def _token_env(self, tok, now, override_ref: Optional[int] = None,
                   event_cols: Optional[dict] = None, event_ts=None) -> Env:
        """Column view of the token table; `override_ref` substitutes the
        current event (broadcast scalars) for that ref's columns."""
        T = self.T
        cols = {}
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            for name in c["cols"]:
                cols[(a.ref, None, name)] = c["cols"][name][:, 0]
                for k in range(a.cap):
                    cols[(a.ref, k, name)] = c["cols"][name][:, k]
            cols[(a.ref, None, TS_ATTR)] = c["ts"][:, 0]
            for k in range(a.cap):
                cols[(a.ref, k, TS_ATTR)] = c["ts"][:, k]
            cols[(a.ref, None, "__arrived__")] = c["n"] > 0
        self._synth_capture_cols(
            cols,
            lambda a, attr: tok["caps"][a.ref_idx]["cols"][attr],
            lambda a: tok["caps"][a.ref_idx]["ts"],
            lambda a: tok["caps"][a.ref_idx]["n"],
        )
        if override_ref is not None:
            a = self.refs[override_ref]
            for name, v in event_cols.items():
                cols[(a.ref, None, name)] = jnp.broadcast_to(v, (T,))
            cols[(a.ref, None, TS_ATTR)] = jnp.broadcast_to(event_ts, (T,))
            cols[(a.ref, None, "__arrived__")] = jnp.ones((T,), dtype=jnp.bool_)
        return Env(cols, now=now)

    # ---- per-event application -------------------------------------------

    def _eligible(self, tok, p: int) -> jnp.ndarray:
        """Tokens that may match slot p: at p, or parked at preceding count
        slots whose min is satisfied (count-skip, reference:
        CountPreStateProcessor min-count forwarding).

        SEQUENCE type keeps only the OLDEST forwarded token: the reference's
        addState accepts a single new state per event for sequences
        (StreamPreStateProcessor.addState SEQUENCE branch), so a contended
        forward is won by the earlier chain — SequenceTestCase testQuery11."""
        active, slot = tok["active"], tok["slot"]
        elig = active & (slot == p)
        skip = jnp.zeros_like(elig)
        q = p - 1
        while q >= 0 and self.slots[q].is_count:
            sat = tok["caps"][self.slots[q].atoms[0].ref_idx]["n"] >= max(
                self.slots[q].min_count, 0
            )
            skip = skip | (active & (slot == q) & sat)
            if self.slots[q].min_count > 0:
                break
            q -= 1
        if self._use_fwd:
            # sequence forwarding is explicit: a token reaches the next
            # slot's pending only by winning its forward event's contention
            # (the fwd lane, updated at each event's end)
            skip = skip & tok["fwd"]
        return elig | skip

    def _capture(self, caps_r, atom: Atom, match, ts, event_cols):
        """Write the current event into ref r's next occurrence slot."""
        T = self.T
        n = caps_r["n"]
        pos = jnp.clip(n, 0, atom.cap - 1)
        write = match & (n < atom.cap)
        rowi = jnp.arange(T)
        new_cols = {}
        for name, arr in caps_r["cols"].items():
            upd = arr.at[rowi, pos].set(
                jnp.broadcast_to(event_cols[name], (T,)).astype(arr.dtype)
            )
            new_cols[name] = jnp.where(write[:, None], upd, arr)
        upd_ts = caps_r["ts"].at[rowi, pos].set(jnp.broadcast_to(ts, (T,)))
        return {
            "n": jnp.where(match, n + 1, n),
            "ts": jnp.where(write[:, None], upd_ts, caps_r["ts"]),
            "cols": new_cols,
        }

    def apply_event(
        self, tok, ts, kind, valid, stream_cols: dict[str, dict], out, out_n,
        overflow, timer_seen=None,
    ):
        """One scan step: apply a single event row to the token table.

        stream_cols: {stream_id: {attr: scalar}} — the row's columns, keyed by
        the stream this step function serves (one entry).

        timer_seen: max TIMER timestamp already processed. Deadline blocks
        fire on any valid row whose effective time max(ts, timer_seen)
        passes the deadline — redundant when timers arrive in order (the
        scheduler fires first), but it rescues tokens whose deadlines fall at
        or before an already-processed timer (late/out-of-order event
        timestamps), which next_timer's `after` filter would otherwise
        silently drop.
        """
        is_cur = valid & (kind == KIND_CURRENT)
        is_timer = valid & (kind == KIND_TIMER)
        if timer_seen is None:
            timer_seen = np.int64(-(1 << 62))
        eff_now = jnp.maximum(ts, timer_seen)
        can_fire = is_timer | is_cur

        # within expiry (reference: StreamPreStateProcessor.isExpired :102-121)
        active = tok["active"]
        kills = []
        started = tok["start_ts"] >= 0
        if self.within_ms is not None:
            kills.append(started & (ts - tok["start_ts"] > self.within_ms))
        for slot in self.slots:
            if slot.within_ms is not None:
                kills.append(
                    (tok["slot"] == slot.index)
                    & started
                    & (ts - tok["start_ts"] > slot.within_ms)
                )
        if kills:
            dead = kills[0]
            for k in kills[1:]:
                dead = dead | k
            active = tok["active"] & ~(dead & valid)
        tok = {**tok, "active": active}

        touched = jnp.zeros((self.T,), dtype=jnp.bool_)
        last = len(self.slots) - 1

        # ---- sequence start-state re-init: the reference clears every
        # pending list per event and re-inits the start state when its
        # pending empties (SequenceSingleProcessStreamReceiver.stabilizeStates
        # -> resetAndUpdate -> StreamPreStateProcessor.init). For an
        # every-rooted sequence that means a fresh virgin must exist whenever
        # no slot-0 token is still pending there (virgin, or a count still
        # absorbing below max) — SequenceTestCase testQuery6.
        if self.sequence and self.slots[0].persistent:
            s0 = self.slots[0]
            n0 = tok["caps"][s0.atoms[0].ref_idx]["n"]
            pend = tok["active"] & (tok["slot"] == 0) & (tok["start_ts"] < 0)
            if s0.is_count:
                mx0 = s0.max_count if s0.max_count > 0 else (1 << 30)
                pend = pend | (
                    tok["active"] & (tok["slot"] == 0) & (n0 < mx0)
                )
            need = is_cur & ~pend.any()
            mask0 = jnp.zeros((self.T,), dtype=jnp.bool_).at[0].set(True) & need
            tok, overflow = self._arm_virgins(tok, mask0, 0, ts, overflow)

        # ---- timer handling: absent deadlines emit/advance
        for slot in self.slots:
            atom = slot.atoms[0]
            p = slot.index
            if slot.is_absent and atom.waiting_ms is not None:
                at_p = tok["active"] & (tok["slot"] == p)
                deadline = tok["entry_ts"] + atom.waiting_ms
                fire = at_p & can_fire & (eff_now >= deadline)
                # a token completed by an absence has no captured event to
                # start its within clock: the deadline starts it (so `within`
                # can expire absent-first patterns — AbsentPatternTestCase
                # testQueryAbsent42)
                started = jnp.where(
                    fire & (tok["start_ts"] < 0), deadline, tok["start_ts"]
                )
                tok = {**tok, "start_ts": started}
                if p == last:
                    # emit with this ref not arrived; output ts = deadline
                    out, out_n, overflow = self._write_emits(
                        out, out_n, overflow, fire, tok, deadline
                    )
                    if slot.persistent:
                        # `every not X for t`: the generator re-arms with a
                        # fresh window starting at the fired deadline
                        # (EveryAbsentPatternTestCase testQueryAbsent1)
                        tok = self._clear_slot_caps(
                            tok, fire, slot, ts=deadline
                        )
                    else:
                        tok = self._consume(tok, fire, slot)
                elif slot.persistent:
                    # fork the completion downstream; generator re-arms
                    tok, overflow, _dest = self._fork(
                        tok, tok, fire, p + 1, deadline, overflow
                    )
                    tok = self._clear_slot_caps(tok, fire, slot, ts=deadline)
                else:
                    tok = self._advance_rows(tok, fire, slot, deadline)
                touched = touched | fire
            elif slot.logical is not None and all(
                a.absent and a.waiting_ms is not None for a in slot.atoms
            ):
                # both sides absent (`not A for t1 and/or not B for t2`) —
                # reference: AbsentLogicalPreStateProcessor with two absent
                # partners (LogicalAbsentPatternTestCase 25-40, 46-50).
                # AND completes at the LATER deadline iff neither side
                # arrived inside its window; OR completes at each side's own
                # deadline iff that side never arrived (an `every` generator
                # fires once per side — two pendings when both are clean;
                # a non-every element completes once, at the earliest).
                a1, a2 = slot.atoms[0], slot.atoms[1]
                at_p = tok["active"] & (tok["slot"] == p)
                arr1 = tok["caps"][a1.ref_idx]["n"] > 0
                arr2 = tok["caps"][a2.ref_idx]["n"] > 0
                if p == 0:
                    # start-of-pattern: an arrival re-arms that side's
                    # window from the arrival (marker ts lane), it does not
                    # block completion forever
                    last1 = tok["caps"][a1.ref_idx]["ts"][:, 0]
                    last2 = tok["caps"][a2.ref_idx]["ts"][:, 0]
                    dl1 = jnp.maximum(tok["entry_ts"], last1) + a1.waiting_ms
                    dl2 = jnp.maximum(tok["entry_ts"], last2) + a2.waiting_ms
                    arr1 = jnp.zeros_like(arr1)
                    arr2 = jnp.zeros_like(arr2)
                else:
                    dl1 = tok["entry_ts"] + a1.waiting_ms
                    dl2 = tok["entry_ts"] + a2.waiting_ms
                if slot.logical is LogicalType.AND:
                    both_dl = jnp.maximum(dl1, dl2)
                    fires = [
                        (
                            at_p & can_fire & ~arr1 & ~arr2 & (eff_now >= both_dl),
                            both_dl,
                        )
                    ]
                else:
                    f1 = at_p & can_fire & ~arr1 & (eff_now >= dl1)
                    f2 = at_p & can_fire & ~arr2 & (eff_now >= dl2)
                    if slot.persistent:
                        fires = [(f1, dl1), (f2, dl2)]
                    else:
                        fires = [(f1 | f2, jnp.where(f1, dl1, dl2))]
                for fire, dts in fires:
                    if p == last:
                        out, out_n, overflow = self._write_emits(
                            out, out_n, overflow, fire, tok, dts
                        )
                        if slot.persistent:
                            # every-generator: window restarts at the fired
                            # deadline
                            tok = self._clear_slot_caps(
                                tok, fire, slot, ts=dts
                            )
                        else:
                            tok = self._consume(tok, fire, slot)
                    elif slot.persistent:
                        # fork the pending completion; the generator stays
                        # armed with its window restarted at the deadline
                        tok, overflow, _dest = self._fork(
                            tok, tok, fire, p + 1, dts, overflow
                        )
                        tok = self._clear_slot_caps(tok, fire, slot, ts=dts)
                    else:
                        tok = self._advance_rows(tok, fire, slot, dts)
                    touched = touched | fire
                continue
            elif slot.logical is not None:
                # `A and not B for t`: completes at the deadline once every
                # present side has arrived. `A or not B for t`: completes at
                # the deadline iff B never arrived inside the window (an A
                # arrival would have advanced the token immediately).
                # (reference: AbsentLogicalPreStateProcessor waiting-time
                # scheduling for both logical types)
                ab = next(
                    (
                        a for a in slot.atoms
                        if a.absent and a.waiting_ms is not None
                    ),
                    None,
                )
                if ab is None:
                    continue
                at_p = tok["active"] & (tok["slot"] == p)
                deadline = tok["entry_ts"] + ab.waiting_ms
                if slot.logical is LogicalType.OR:
                    # B's arrival was recorded as a capture marker (it must
                    # not kill the token — A can still complete the or)
                    b_arrived = tok["caps"][ab.ref_idx]["n"] > 0
                    fire = at_p & can_fire & ~b_arrived & (eff_now >= deadline)
                else:
                    arrived = jnp.ones((self.T,), dtype=jnp.bool_)
                    for a2 in slot.atoms:
                        if not a2.absent:
                            arrived = arrived & (
                                tok["caps"][a2.ref_idx]["n"] > 0
                            )
                    fire = at_p & can_fire & arrived & (eff_now >= deadline)
                if p == last:
                    out, out_n, overflow = self._write_emits(
                        out, out_n, overflow, fire, tok, deadline
                    )
                    tok = self._consume(tok, fire, slot)
                    if slot.persistent:
                        # surviving every-generator re-arms fresh, window
                        # restarting at the deadline — NOT the row's raw
                        # timestamp: a late row firing through the eff_now
                        # rescue (ts < deadline <= timer_seen) would re-arm
                        # the generator in the past, leaving its next
                        # deadline already expired so every subsequent row
                        # re-fires it (the resurrected-deadline hazard)
                        tok = self._clear_slot_caps(
                            tok, fire, slot, ts=deadline
                        )
                elif slot.persistent:
                    # `every` generator: fork the completion downstream and
                    # keep the generator armed with a fresh window
                    tok, overflow, _dest = self._fork(
                        tok, tok, fire, p + 1, deadline, overflow
                    )
                    tok = self._clear_slot_caps(tok, fire, slot, ts=deadline)
                else:
                    tok = self._advance_rows(tok, fire, slot, deadline)
                touched = touched | fire

        # ---- event matching, descending slot order so one event moves a
        # token at most one hop (reference: next-event semantics)
        for slot in reversed(self.slots):
            p = slot.index
            # touched accumulates per SLOT: both sides of a logical element
            # may consume the same event (reference: LogicalPatternTestCase
            # testQuery5 — one event satisfies both sides of an `and`)
            slot_touch = jnp.zeros((self.T,), dtype=jnp.bool_)
            for atom in slot.atoms:
                if atom.stream_id not in stream_cols:
                    continue
                ev = stream_cols[atom.stream_id]
                elig = self._eligible(tok, p) & ~touched & is_cur
                if slot.is_count and atom.cap:
                    mx = slot.max_count
                    if mx > 0:
                        # cannot absorb beyond max (only tokens AT p absorb)
                        n_here = tok["caps"][atom.ref_idx]["n"]
                        elig = elig & ~((tok["slot"] == p) & (n_here >= mx))
                env = self._token_env(
                    tok, None, override_ref=atom.ref_idx,
                    event_cols=ev, event_ts=ts,
                )
                match = elig
                for c in self._conds[(p, atom.ref_idx)]:
                    match = match & c(env)
                if atom.absent:
                    both_absent = slot.logical is not None and all(
                        a2.absent for a2 in slot.atoms
                    )
                    if atom.waiting_ms is not None and (
                        slot.logical is LogicalType.OR or both_absent
                    ):
                        # `A or not B for t` / `not A for t1 and not B for
                        # t2`: an arrival inside the window must not kill the
                        # token (the other side may still satisfy the element,
                        # and an `every` generator must survive) — record it
                        # as a capture marker so the TIMER path knows this
                        # absent side can never fire
                        # (reference: AbsentLogicalPreStateProcessor —
                        # the partner processor keeps waiting)
                        mark = match & (
                            ts <= tok["entry_ts"] + atom.waiting_ms
                        )
                        if p == 0 and both_absent:
                            # start-of-pattern both-absent: an arrival
                            # re-arms THAT SIDE's window from the arrival
                            # (reference: the initial state always re-waits;
                            # LogicalAbsentPatternTestCase 46, 34/35) — track
                            # the latest arrival in the marker's ts lane
                            c = dict(tok["caps"][atom.ref_idx])
                            c["n"] = jnp.where(mark, 1, c["n"]).astype(
                                c["n"].dtype
                            )
                            c["ts"] = c["ts"].at[:, 0].set(
                                jnp.where(
                                    mark,
                                    jnp.maximum(c["ts"][:, 0], ts),
                                    c["ts"][:, 0],
                                )
                            )
                            new_caps = list(tok["caps"])
                            new_caps[atom.ref_idx] = c
                            tok = {**tok, "caps": new_caps}
                        else:
                            new_caps = list(tok["caps"])
                            new_caps[atom.ref_idx] = self._capture(
                                tok["caps"][atom.ref_idx], atom, mark, ts, ev
                            )
                            tok = {**tok, "caps": new_caps}
                        slot_touch = slot_touch | mark
                        continue
                    # arrival on an absent stream kills the token
                    # (reference: AbsentStreamPreStateProcessor.process kill);
                    # with a waiting time, only arrivals INSIDE the window
                    if atom.waiting_ms is not None:
                        match = match & (
                            ts <= tok["entry_ts"] + atom.waiting_ms
                        )
                    if p == 0 and atom.waiting_ms is not None:
                        # start-of-pattern absent: the initial/generator
                        # token RE-ARMS instead of dying — the reference's
                        # init state always re-waits from the violating
                        # arrival, captures cleared
                        # (LogicalAbsentPatternTestCase testQueryAbsent10)
                        rearm = match & (tok["start_ts"] < 0)
                        kill = match & ~rearm
                        tok = {**tok, "active": tok["active"] & ~kill}
                        tok = self._clear_slot_caps(tok, rearm, slot, ts=ts)
                    else:
                        tok = {**tok, "active": tok["active"] & ~match}
                    slot_touch = slot_touch | match
                    continue

                # capture the event into the atom's ref
                new_caps = list(tok["caps"])
                new_caps[atom.ref_idx] = self._capture(
                    tok["caps"][atom.ref_idx], atom, match, ts, ev
                )
                adv_tok = {
                    **tok,
                    "caps": new_caps,
                    "slot": jnp.where(match, p, tok["slot"]),
                    "start_ts": jnp.where(
                        match & (tok["start_ts"] < 0), ts, tok["start_ts"]
                    ),
                }

                if slot.logical is not None:
                    arrived = [
                        new_caps[a2.ref_idx]["n"] > 0
                        for a2 in slot.atoms
                        if not a2.absent
                    ]
                    if slot.logical is LogicalType.OR:
                        complete = match
                    else:
                        allv = arrived[0]
                        for v in arrived[1:]:
                            allv = allv & v
                        complete = match & allv
                        wait_ab = next(
                            (
                                a for a in slot.atoms
                                if a.absent and a.waiting_ms is not None
                            ),
                            None,
                        )
                        if wait_ab is not None:
                            # completion defers to the absent deadline; an
                            # early present arrival stays captured and the
                            # TIMER path completes it
                            complete = complete & (
                                eff_now
                                >= tok["entry_ts"] + wait_ab.waiting_ms
                            )
                    advance = complete
                elif slot.is_count:
                    # absorb in place; a trailing count emits (and dies) at
                    # exactly min occurrences (reference:
                    # CountPostStateProcessor.process -> processMinCountReached
                    # when streamEvents == minCount, stateChanged consumes)
                    n_after = new_caps[atom.ref_idx]["n"]
                    if slot.min_count >= 1:
                        count_armed = match & (n_after == slot.min_count)
                    else:
                        count_armed = jnp.zeros_like(match)
                    if p == last and slot.min_count >= 1:
                        advance = count_armed
                    else:
                        advance = jnp.zeros_like(match)
                else:
                    advance = match

                stay = match & ~advance
                blk = next(
                    (b for b in self.every_blocks if b[1] == p), None
                )
                if p == last:
                    out, out_n, overflow = self._write_emits(
                        out, out_n, overflow, advance, adv_tok, ts
                    )
                    new_tok = self._merge(tok, adv_tok, stay)
                    new_tok = self._consume(
                        new_tok, advance, slot, force=slot.is_count
                    )
                    tok = new_tok
                    if blk is not None:
                        tok, overflow, rearmed = self._rearm_block(
                            tok, adv_tok, advance, blk, ts, overflow
                        )
                        touched = touched | rearmed
                elif slot.persistent and not slot.is_count:
                    # fork: advanced copy goes to a free row; the source
                    # (virgin/generator) stays armed
                    tok, overflow, dest_mask = self._fork(
                        tok, adv_tok, advance, p + 1, ts, overflow
                    )
                    tok = self._merge(tok, adv_tok, stay)
                    touched = touched | dest_mask
                    tok, out, out_n, overflow = self._arrival_effects(
                        tok, dest_mask, p + 1, ts, out, out_n, overflow
                    )
                else:
                    moved = self._merge(tok, adv_tok, match)
                    moved = {
                        **moved,
                        "slot": jnp.where(advance, p + 1, moved["slot"]),
                        "entry_ts": jnp.where(advance, ts, moved["entry_ts"]),
                    }
                    tok = moved
                    tok, out, out_n, overflow = self._arrival_effects(
                        tok, advance, p + 1, ts, out, out_n, overflow
                    )
                    if blk is not None:
                        tok, overflow, rearmed = self._rearm_block(
                            tok, tok, advance, blk, ts, overflow
                        )
                        touched = touched | rearmed
                slot_touch = slot_touch | match

                if slot.persistent and slot.logical is not None:
                    # the surviving generator re-arms FRESH: a completed
                    # logical pair's partial captures clear and its absence
                    # window restarts (reference: the every re-arm is a clean
                    # addEveryState virgin — LogicalPatternTestCase
                    # testQuery15/19)
                    tok = self._clear_slot_caps(tok, advance, slot, ts=ts)

                if (
                    slot.persistent and slot.is_count
                    and slot.min_count >= 1 and not self.sequence
                ):
                    # (sequences never call processMinCountReached — the token
                    # is shared via the SEQUENCE re-add branch instead)
                    # `every` over a count: a fresh virgin is armed exactly
                    # when a token's occurrence count reaches min (reference:
                    # CountPostStateProcessor.processMinCountReached ->
                    # nextEveryStatePreProcessor.addEveryState; the reference's
                    # shallow clone is replaced by a clean virgin — PARITY.md)
                    tok, overflow = self._arm_virgins(
                        tok, count_armed, p, ts, overflow
                    )
            touched = touched | slot_touch

        # ---- sequence strictness: any unconsumed CURRENT event kills
        # non-virgin, non-generator tokens (reference: sequence
        # StreamPreStateProcessor resetState on mismatch)
        if self.sequence:
            # (non-virgin tokens at persistent slots are NOT exempt: the
            # reference drops a full count tail that fails to re-add —
            # SequenceTestCase testQuery6)
            virgin = tok["start_ts"] < 0
            kill = is_cur & tok["active"] & ~touched & ~virgin
            tok = {**tok, "active": tok["active"] & ~kill}

        if self._use_fwd:
            # end-of-event forwarding: each count slot's absorbers with min
            # satisfied contend for the ONE pending spot at the next slot;
            # the oldest chain wins (reference: SEQUENCE addState drops all
            # but the first add per event). Min-0 virgins keep their
            # arm-time forward.
            T = self.T
            lanes64 = jnp.arange(T, dtype=jnp.int64)
            new_fwd = tok["fwd"] & tok["active"] & (tok["start_ts"] < 0)
            for q, cslot in enumerate(self.slots):
                if not cslot.is_count:
                    continue
                n_q = tok["caps"][cslot.atoms[0].ref_idx]["n"]
                cand = (
                    tok["active"] & (tok["slot"] == q) & touched
                    & (n_q >= max(cslot.min_count, 0))
                    & (tok["start_ts"] >= 0)
                )
                key = jnp.where(
                    cand, tok["start_ts"] * T + lanes64, np.int64(1) << 62
                )
                winner = cand & (jnp.arange(T) == jnp.argmin(key))
                new_fwd = new_fwd | winner
            # padding/timer rows are no-ops, not forward contests
            tok = {**tok, "fwd": jnp.where(is_cur, new_fwd, tok["fwd"])}

        return tok, out, out_n, overflow

    # ---- token-table update helpers --------------------------------------

    @staticmethod
    def _merge(old, new, mask):
        """Per-row select between two token tables."""

        def sel(a, b):
            if a.ndim == 1:
                return jnp.where(mask, b, a)
            return jnp.where(mask[:, None], b, a)

        caps = [
            {
                "n": sel(o["n"], n_["n"]),
                "ts": sel(o["ts"], n_["ts"]),
                "cols": {k: sel(o["cols"][k], n_["cols"][k]) for k in o["cols"]},
            }
            for o, n_ in zip(old["caps"], new["caps"])
        ]
        merged = {
            "active": sel(old["active"], new["active"]),
            "slot": sel(old["slot"], new["slot"]),
            "start_ts": sel(old["start_ts"], new["start_ts"]),
            "entry_ts": sel(old["entry_ts"], new["entry_ts"]),
            "caps": caps,
        }
        if "fwd" in old:
            merged["fwd"] = sel(old["fwd"], new["fwd"])
        return merged

    def _consume(self, tok, mask, slot: Slot, force: bool = False):
        """Tokens that emitted: die, unless at a persistent slot (the `every`
        generator stays armed). Trailing count slots force-consume: their
        re-arm is the virgin forked at min, not the emitting token."""
        if slot.persistent and not force:
            return tok
        return {**tok, "active": tok["active"] & ~mask}

    def _arrival_effects(self, tok, arrived, q: int, ts, out, out_n, overflow):
        """Effects of tokens arriving AT slot q: a trailing min-0 count emits
        immediately with empty captures and is consumed (reference:
        CountPreStateProcessor.addState minCount==0 ->
        processMinCountReached at add time)."""
        if q >= len(self.slots):
            return tok, out, out_n, overflow
        nxt = self.slots[q]
        if not (nxt.is_count and nxt.min_count == 0 and q == len(self.slots) - 1):
            return tok, out, out_n, overflow
        out, out_n, overflow = self._write_emits(
            out, out_n, overflow, arrived, tok, ts
        )
        return (
            {**tok, "active": tok["active"] & ~arrived},
            out, out_n, overflow,
        )

    def _clear_slot_caps(self, tok, mask, slot: Slot, ts=None):
        """Reset a slot's atom captures on `mask` rows (the re-arming
        generator of a persistent logical slot becomes virgin again). `ts`
        restarts the slot clock — a fresh absence window measures from the
        re-arm, not the original arm."""
        caps = list(tok["caps"])
        for a in slot.atoms:
            c = caps[a.ref_idx]
            schema = self.schemas[a.stream_id]
            caps[a.ref_idx] = {
                "n": jnp.where(mask, 0, c["n"]),
                "ts": jnp.where(mask[:, None], np.int64(0), c["ts"]),
                "cols": {
                    name: jnp.where(
                        mask[:, None],
                        np.asarray(
                            null_value(schema.attr_types[name]), arr.dtype
                        ),
                        arr,
                    )
                    for name, arr in c["cols"].items()
                },
            }
        out = {**tok, "caps": caps}
        if ts is not None:
            out["entry_ts"] = jnp.where(mask, ts, out["entry_ts"])
        if slot.index == 0:
            out["start_ts"] = jnp.where(
                mask, np.int64(-1), out["start_ts"]
            )
        return out

    def _rearm_block(self, tok, src_tok, mask, block, ts, overflow):
        """Fork re-armed copies at a completed every block's first slot:
        captures of slots OUTSIDE the block are retained, block captures are
        cleared (reference: addEveryState clones the completing StateEvent
        back into the block's first pre-state; block recaptures overwrite).
        Matches are strictly serial — EveryPatternTestCase testQuery5/7."""
        first, last = block
        T = self.T
        dest, overflow = self._alloc_lanes(tok, mask, overflow)
        block_refs = {
            a.ref_idx for s in self.slots[first:last + 1] for a in s.atoms
        }
        caps = []
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            if a.ref_idx in block_refs:
                schema = self.schemas[a.stream_id]
                cols = {
                    name: arr.at[dest].set(
                        np.asarray(
                            null_value(schema.attr_types[name]), arr.dtype
                        ),
                        mode="drop",
                    )
                    for name, arr in c["cols"].items()
                }
                caps.append(
                    {
                        "n": c["n"].at[dest].set(0, mode="drop"),
                        "ts": c["ts"].at[dest].set(np.int64(0), mode="drop"),
                        "cols": cols,
                    }
                )
            else:
                s = src_tok["caps"][a.ref_idx]
                caps.append(
                    {
                        "n": c["n"].at[dest].set(s["n"], mode="drop"),
                        "ts": c["ts"].at[dest].set(s["ts"], mode="drop"),
                        "cols": {
                            name: arr.at[dest].set(s["cols"][name], mode="drop")
                            for name, arr in c["cols"].items()
                        },
                    }
                )
        # a re-armed whole-pattern block is virgin again; a mid-pattern block
        # keeps the match start (within measures from the first capture)
        start = (
            src_tok["start_ts"]
            if first > 0
            else jnp.full((T,), -1, jnp.int64)
        )
        dest_mask = jnp.zeros((T,), jnp.bool_).at[dest].set(True, mode="drop")
        res = {
            "active": tok["active"].at[dest].set(True, mode="drop"),
            "slot": tok["slot"].at[dest].set(first, mode="drop"),
            "start_ts": tok["start_ts"].at[dest].set(start, mode="drop"),
            "entry_ts": tok["entry_ts"].at[dest].set(
                jnp.broadcast_to(ts, (T,)).astype(jnp.int64), mode="drop"
            ),
            "caps": caps,
        }
        if "fwd" in tok:
            res["fwd"] = tok["fwd"].at[dest].set(False, mode="drop")
        return res, overflow, dest_mask

    def _arm_virgins(self, tok, mask, p: int, ts, overflow):
        """Scatter fresh virgin tokens (slot p, no captures) into free rows."""
        T = self.T
        dest, overflow = self._alloc_lanes(tok, mask, overflow)
        caps = []
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            schema = self.schemas[a.stream_id]
            cols = {
                name: arr.at[dest].set(
                    np.asarray(null_value(schema.attr_types[name]), arr.dtype),
                    mode="drop",
                )
                for name, arr in c["cols"].items()
            }
            caps.append(
                {
                    "n": c["n"].at[dest].set(0, mode="drop"),
                    "ts": c["ts"].at[dest].set(np.int64(0), mode="drop"),
                    "cols": cols,
                }
            )
        res = {
            "active": tok["active"].at[dest].set(True, mode="drop"),
            "slot": tok["slot"].at[dest].set(p, mode="drop"),
            "start_ts": tok["start_ts"].at[dest].set(np.int64(-1), mode="drop"),
            "entry_ts": tok["entry_ts"].at[dest].set(
                jnp.broadcast_to(ts, (T,)).astype(jnp.int64), mode="drop"
            ),
            "caps": caps,
        }
        if "fwd" in tok:
            fwd0 = self.slots[p].is_count and self.slots[p].min_count == 0
            res["fwd"] = tok["fwd"].at[dest].set(fwd0, mode="drop")
        return res, overflow

    def _advance_rows(self, tok, mask, slot: Slot, ts):
        p = slot.index
        return {
            **tok,
            "slot": jnp.where(mask, p + 1, tok["slot"]),
            "entry_ts": jnp.where(mask, ts, tok["entry_ts"]),
        }

    def _alloc_lanes(self, tok, mask, overflow):
        """Allocate one free token lane per set row of `mask`; rows that don't
        fit scatter to index T (dropped by mode='drop') and raise overflow."""
        T = self.T
        free = ~tok["active"]
        order = jnp.argsort(~free)  # free row indices first (stable)
        nfree = jnp.sum(free)
        rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
        ok = mask & (rank < nfree)
        dest = jnp.where(ok, order[jnp.clip(rank, 0, T - 1)], T)
        return dest, overflow | jnp.any(mask & ~ok)

    def _fork(self, tok, adv_tok, mask, next_slot: int, ts, overflow):
        """Scatter advanced copies of `mask` rows into free rows
        (reference: every re-arm keeps the pre-state armed while the matched
        StateEvent moves on)."""
        T = self.T
        dest, overflow = self._alloc_lanes(tok, mask, overflow)

        def scat(lane, adv_lane, fill=None):
            return lane.at[dest].set(adv_lane, mode="drop")

        caps = [
            {
                "n": scat(o["n"], a["n"]),
                "ts": scat(o["ts"], a["ts"]),
                "cols": {k: scat(o["cols"][k], a["cols"][k]) for k in o["cols"]},
            }
            for o, a in zip(tok["caps"], adv_tok["caps"])
        ]
        dest_mask = jnp.zeros((T,), dtype=jnp.bool_).at[dest].set(True, mode="drop")
        res = {
            "active": tok["active"].at[dest].set(True, mode="drop"),
            "slot": tok["slot"].at[dest].set(
                jnp.full((T,), next_slot, dtype=jnp.int32), mode="drop"
            ),
            "start_ts": scat(tok["start_ts"], adv_tok["start_ts"]),
            "entry_ts": tok["entry_ts"].at[dest].set(
                jnp.broadcast_to(ts, (T,)), mode="drop"
            ),
            "caps": caps,
        }
        if "fwd" in tok:
            res["fwd"] = tok["fwd"].at[dest].set(False, mode="drop")
        return res, overflow, dest_mask

    # ---- emission --------------------------------------------------------

    def out_capacity(self, batch_capacity: int) -> int:
        return max(batch_capacity, 64)

    # ---- vectorized batch fast path --------------------------------------
    #
    # Simple chains (single-atom slots, no counts/absent/logical, `every`
    # only as the arming slot) admit a fully vectorized batch kernel: per NFA
    # state one [T, B] match matrix (tokens x rows), tokens advancing to
    # their FIRST matching row — the dense "token-matrix x batch" form of
    # SURVEY §3.3's north star. One device program per batch instead of a
    # B-step scan; multi-hop within a batch falls out of the ascending state
    # loop (a token advancing at state p on row j can only use rows > j at
    # state p+1).

    @property
    def fast_path_ok(self) -> bool:
        if self.every_blocks:
            return False
        for i, s in enumerate(self.slots):
            if len(s.atoms) != 1 or s.is_count or s.is_absent or s.logical:
                return False
            if s.persistent and i != 0:
                return False
            if s.atoms[0].cap != 1:
                return False
        if self.sequence and len({a.stream_id for a in self.refs}) > 1:
            # multi-stream sequence strictness (an unconsumed event of ANY
            # participating stream kills waiting tokens) needs the scan path
            return False
        return True

    @property
    def count_fast_ok(self) -> bool:
        """Closed-form count kernel applies to: PATTERN type, slot 0 a count
        state (min >= 1, optionally `every`), simple single-atom tail slots,
        no within bounds, and row-only conditions for slots 0 and 1 (slot-1
        matching is folded into slot-0's closed form, so neither may read the
        token table). The key insight making this O(1) device passes instead
        of a per-event scan: all absorbing tokens absorb every matching event
        (reference: CountPreStateProcessor.processAndReturn iterates every
        pending state), so capture sets are pure rank arithmetic over the
        batch's match sequence."""
        if self.sequence or len(self.slots) < 2 or self.within_ms is not None:
            return False
        if self.every_blocks:
            return False
        s0 = self.slots[0]
        if not s0.is_count or s0.min_count < 1 or s0.is_absent or s0.logical:
            return False
        for s in self.slots:
            if s.within_ms is not None:
                return False
        for s in self.slots[1:]:
            if (
                len(s.atoms) != 1 or s.is_count or s.is_absent
                or s.logical or s.persistent or s.atoms[0].cap != 1
            ):
                return False
        for p in (0, 1):
            ref = self.slots[p].atoms[0].ref
            keys = self._cond_keys[(p, self.slots[p].atoms[0].ref_idx)]
            if any(k[0] != ref or k[1] is not None for k in keys):
                return False
        return True

    def _row_env(self, ev: dict, batch_ts, now, atom: Atom) -> Env:
        """[B]-shaped env exposing only the current event as the atom's ref."""
        cols = {(atom.ref, None, name): v for name, v in ev.items()}
        cols[(atom.ref, None, TS_ATTR)] = batch_ts
        cols[(atom.ref, None, "__arrived__")] = jnp.ones(
            batch_ts.shape, dtype=jnp.bool_
        )
        return Env(cols, now=now)

    def apply_batch_count(
        self, tok, batch_ts, batch_kind, batch_valid, stream_cols: dict,
        out, out_n, overflow, now,
    ):
        """Whole-batch count-pattern kernel (see count_fast_ok).

        Per chunk: enumerate slot-0's condition matches as a rank sequence
        (midx), derive every token's absorption span and slot-1 advance row in
        closed form, materialize the `every` generation chain armed at each
        min-count crossing, then run the remaining simple slots with the
        ordinary [T, B] token-matrix passes.
        """
        T = self.T
        B = batch_ts.shape[0]
        S = len(self.slots)
        slot0, slot1 = self.slots[0], self.slots[1]
        atom0, atom1 = slot0.atoms[0], slot1.atoms[0]
        _keep_cols, _ts_used = self.capture_keep()
        K = atom0.cap
        m = slot0.min_count
        # occurrence COUNTING runs to the true max (unbounded -> huge), while
        # capture WRITES stop at the capture capacity K — matching the scan
        # path, whose n keeps counting as writes drop (module docstring)
        M = slot0.max_count if slot0.max_count > 0 else (1 << 30)

        rows = jnp.arange(B, dtype=jnp.int32)
        toks = jnp.arange(T, dtype=jnp.int32)
        qpos = jnp.arange(K, dtype=jnp.int32)
        v = batch_valid & (batch_kind == KIND_CURRENT)
        at0 = tok["active"] & (tok["slot"] == 0)
        n0 = tok["caps"][atom0.ref_idx]["n"]

        # ---- slot-0 match sequence over the batch ----
        ev0 = stream_cols.get(atom0.stream_id)
        if ev0 is not None:
            env0 = self._row_env(ev0, batch_ts, now, atom0)
            Mc = v
            for c in self._conds[(0, atom0.ref_idx)]:
                Mc = Mc & jnp.broadcast_to(c(env0), (B,))
        else:
            Mc = jnp.zeros((B,), dtype=jnp.bool_)
        midx_excl = jnp.cumsum(Mc.astype(jnp.int32)) - Mc.astype(jnp.int32)
        k_total = midx_excl[-1] + Mc[-1].astype(jnp.int32)
        mrow = first_indices(Mc, B, fill=B)
        mrow_c = jnp.clip(mrow, 0, B - 1)
        mts = batch_ts[mrow_c]

        # ---- slot-1 advance row per row (row-only by gate) ----
        ev1 = stream_cols.get(atom1.stream_id)
        if ev1 is not None:
            env1 = self._row_env(ev1, batch_ts, now, atom1)
            Madv = v
            for c in self._conds[(1, atom1.ref_idx)]:
                Madv = Madv & jnp.broadcast_to(c(env1), (B,))
        else:
            Madv = jnp.zeros((B,), dtype=jnp.bool_)

        # token t advances at the first row b with Madv[b] and enter-count
        # n0 + min(midx_excl[b], room) >= m — equivalently midx_excl[b] >=
        # m - n0, since room = M - n0 with M >= m never blocks reaching m
        # (midx_excl: the reference forwards at min via newAndEvery, pending
        # only from the NEXT event, and checks the next state first — so the
        # row that reaches min is itself not advance-eligible).
        # midx_excl is NON-DECREASING, so "first b with Madv[b] and
        # midx_excl[b] >= v" factors into two [B]/[T] primitives: a suffix-min
        # scan (madv_next[b] = first advance row at or after b) and a
        # searchsorted for the threshold crossing. This replaces the r4 dense
        # [T, B] pred compare + argmax, whose HLO materialized ~750 MB of
        # [T, B] s32/u32/pred per chunk (the whole kernel's wall — 7.7 ms vs
        # ~0.6 ms of everything else). method='sort' keeps searchsorted
        # vectorized (one bitonic sort of T+B keys); the default 'scan'
        # serializes into scalar-space gathers.
        room = (M - jnp.clip(n0, 0, M)).astype(jnp.int32)
        thresh = (m - jnp.clip(n0, 0, m)).astype(midx_excl.dtype)
        madv_next = lax.cummin(
            jnp.where(Madv, rows, B).astype(jnp.int32), reverse=True
        )
        b0_t = jnp.searchsorted(
            midx_excl, thresh, side="left", method="sort"
        ).astype(jnp.int32)
        jt = jnp.where(b0_t < B, madv_next[jnp.clip(b0_t, 0, B - 1)], B)
        has_adv = at0 & (jt < B)
        j = jt.astype(jnp.int32)
        jc = jnp.clip(j, 0, B - 1)

        # absorption span: stops at the advance row (reference:
        # removeIfNextStateProcessed drops the token from the count pending
        # once the next state captured)
        A = jnp.clip(jnp.where(has_adv, midx_excl[jc], k_total), 0, room)
        A = jnp.where(at0, A, 0)

        # ---- capture writes for existing slot-0 tokens ----
        caps = [dict(c) for c in tok["caps"]]
        src = qpos[None, :] - n0[:, None]
        wmask = at0[:, None] & (src >= 0) & (src < A[:, None])
        srcc = jnp.clip(src, 0, B - 1)
        cr = dict(caps[atom0.ref_idx])
        cr["n"] = jnp.where(at0, n0 + A, n0).astype(cr["n"].dtype)
        if _ts_used[atom0.ref_idx]:
            cr["ts"] = jnp.where(wmask, mts[srcc], cr["ts"])
        if ev0 is not None:
            cr["cols"] = {
                name: jnp.where(wmask, ev0[name][mrow_c].astype(arr.dtype)[srcc], arr)
                for name, arr in cr["cols"].items()
            }
        caps[atom0.ref_idx] = cr
        start_ts = jnp.where(
            at0 & (tok["start_ts"] < 0) & (A > 0), mts[0], tok["start_ts"]
        )

        # ---- slot-1 capture + transition for advancing tokens ----
        advD = at0 & has_adv
        if ev1 is not None:
            c1 = dict(caps[atom1.ref_idx])
            c1["n"] = jnp.where(advD, 1, c1["n"]).astype(c1["n"].dtype)
            # column-0 writes via static slice update, not arange scatter
            if _ts_used[atom1.ref_idx]:
                c1["ts"] = c1["ts"].at[:, 0].set(
                    jnp.where(advD, batch_ts[jc], c1["ts"][:, 0])
                )
            c1["cols"] = {
                name: arr.at[:, 0].set(
                    jnp.where(advD, ev1[name][jc].astype(arr.dtype), arr[:, 0])
                )
                for name, arr in c1["cols"].items()
            }
            caps[atom1.ref_idx] = c1
        entry_row = jnp.where(advD, j, -1)
        tok = {
            "active": tok["active"],
            "slot": jnp.where(advD, 2, tok["slot"]),
            "start_ts": start_ts,
            "entry_ts": jnp.where(advD, batch_ts[jc], tok["entry_ts"]),
            "caps": caps,
        }

        # ---- `every` generation chain (armed at each min crossing) ----
        if slot0.persistent:
            tail = at0 & (n0 < m)
            tail_exists = tail.any()
            ny = jnp.min(jnp.where(tail, n0, m)).astype(jnp.int32)
            # generations beyond the token-lane count T can never be armed
            # (they overflow either way), so the generation axis is capped at
            # T — [G]-shaped gathers/scatters cost ~1 element/cycle on the
            # TPU scalar core, and modeling unarmable generations is pure
            # waste; the cap's dropped generations raise the same overflow
            # flag lane exhaustion would have
            Gmax = min(B // max(m, 1) + 1, T)
            g = jnp.arange(Gmax, dtype=jnp.int32)
            s_g = (m - ny) + g * m
            valid_g = tail_exists & (s_g <= k_total)
            overflow = overflow | (
                tail_exists & ((m - ny) + Gmax * m <= k_total)
            )
            # generation g advances at the first row b with Madv[b] and
            # midx_excl[b] >= s_g + m (room never blocks, see above). Same
            # suffix-min + sorted-searchsorted factoring as the per-token
            # advance: s_g is increasing and midx_excl non-decreasing, so
            # this is a sorted-sorted merge — no [G, B] matrix.
            b0_g = jnp.searchsorted(
                midx_excl, (s_g + m).astype(midx_excl.dtype),
                side="left", method="sort",
            ).astype(jnp.int32)
            jg_row = jnp.where(
                b0_g < B, madv_next[jnp.clip(b0_g, 0, B - 1)], B
            )
            has_advg = valid_g & (jg_row < B)
            jg = jg_row.astype(jnp.int32)
            jgc = jnp.clip(jg, 0, B - 1)
            Ag = jnp.clip(
                jnp.where(has_advg, midx_excl[jgc], k_total) - s_g, 0, M
            )
            Ag = jnp.where(valid_g, Ag, 0)

            # scatter generations into free lanes
            free = ~tok["active"]
            nfree = jnp.sum(free)
            free_idx = first_indices(free, Gmax)
            grank = (jnp.cumsum(valid_g.astype(jnp.int32)) - 1).astype(jnp.int32)
            okg = valid_g & (grank < nfree) & (free_idx[jnp.clip(grank, 0, Gmax - 1)] >= 0)
            overflow = overflow | jnp.any(valid_g & ~okg)
            dst = jnp.where(okg, free_idx[jnp.clip(grank, 0, Gmax - 1)], T)

            src_g = s_g[:, None] + qpos[None, :]
            wm_g = (qpos[None, :] < Ag[:, None])
            src_gc = jnp.clip(src_g, 0, B - 1)
            caps = [dict(c) for c in tok["caps"]]
            cr = dict(caps[atom0.ref_idx])
            cr["n"] = cr["n"].at[dst].set(Ag, mode="drop")
            if _ts_used[atom0.ref_idx]:
                cr["ts"] = _set_at(
                    cr["ts"], dst, jnp.where(wm_g, mts[src_gc], np.int64(0))
                )
            if ev0 is not None:
                new_cols = {}
                for name, arr in cr["cols"].items():
                    t = self.schemas[atom0.stream_id].attr_types[name]
                    nv = np.asarray(null_value(t), dtype=arr.dtype)
                    genv = jnp.where(wm_g, ev0[name][mrow_c][src_gc].astype(arr.dtype), nv)
                    new_cols[name] = arr.at[dst].set(genv, mode="drop")
                cr["cols"] = new_cols
            caps[atom0.ref_idx] = cr
            if ev1 is not None:
                c1 = dict(caps[atom1.ref_idx])
                c1["n"] = c1["n"].at[dst].set(
                    has_advg.astype(c1["n"].dtype), mode="drop"
                )
                if _ts_used[atom1.ref_idx]:
                    c1["ts"] = c1["ts"].at[:, 0].set(
                        _set_at(
                            c1["ts"][:, 0], dst,
                            jnp.where(has_advg, batch_ts[jgc], np.int64(0)),
                        )
                    )
                new_cols = {}
                for name, arr in c1["cols"].items():
                    t = self.schemas[atom1.stream_id].attr_types[name]
                    nv = np.asarray(null_value(t), dtype=arr.dtype)
                    gv = jnp.where(has_advg, ev1[name][jgc].astype(arr.dtype), nv)
                    new_cols[name] = arr.at[:, 0].set(_set_at(arr[:, 0], dst, gv))
                c1["cols"] = new_cols
                caps[atom1.ref_idx] = c1
            # untouched refs: clear stale lane contents
            written = {atom0.ref_idx} | (
                {atom1.ref_idx} if ev1 is not None else set()
            )
            for ridx, a in enumerate(self.refs):
                if ridx in written:
                    continue
                c = dict(caps[ridx])
                c["n"] = c["n"].at[dst].set(0, mode="drop")
                if _ts_used[ridx]:
                    c["ts"] = _set_at(
                        c["ts"], dst,
                        jnp.zeros(dst.shape + c["ts"].shape[1:], c["ts"].dtype),
                    )
                c["cols"] = {
                    name: _set_at(
                        arr, dst,
                        jnp.full(
                            dst.shape + arr.shape[1:],
                            np.asarray(
                                null_value(self.schemas[a.stream_id].attr_types[name]),
                                arr.dtype,
                            ),
                            arr.dtype,
                        ),
                    )
                    for name, arr in c["cols"].items()
                }
                caps[ridx] = c
            g_start = jnp.where(Ag > 0, mts[jnp.clip(s_g, 0, B - 1)], np.int64(-1))
            tok = {
                "active": tok["active"].at[dst].set(True, mode="drop"),
                "slot": tok["slot"].at[dst].set(
                    jnp.where(has_advg, 2, 0), mode="drop"
                ),
                "start_ts": _set_at(tok["start_ts"], dst, g_start),
                "entry_ts": _set_at(
                    tok["entry_ts"], dst, mts[jnp.clip(s_g - 1, 0, B - 1)]
                ),
                "caps": caps,
            }
            entry_row = entry_row.at[dst].set(
                jnp.where(has_advg, jg, -1), mode="drop"
            )

        # ---- remaining simple slots (ordinary token-matrix passes) ----
        for p in range(2, S):
            slot = self.slots[p]
            atom = slot.atoms[0]
            if atom.stream_id not in stream_cols:
                continue
            ev = stream_cols[atom.stream_id]
            elig = tok["active"] & (tok["slot"] == p)
            env = self._matrix_env(tok, ev, batch_ts, now, atom.ref_idx)
            cond = jnp.ones((T, B), dtype=jnp.bool_)
            for c in self._conds[(p, atom.ref_idx)]:
                cond = cond & jnp.broadcast_to(c(env), (T, B))
            Mm = elig[:, None] & v[None, :] & (rows[None, :] > entry_row[:, None]) & cond
            has = Mm.any(axis=1)
            jj = jnp.argmax(Mm, axis=1).astype(jnp.int32)
            jjc = jnp.clip(jj, 0, B - 1)
            caps = [dict(c) for c in tok["caps"]]
            crp = dict(caps[atom.ref_idx])
            crp["n"] = jnp.where(has, 1, crp["n"]).astype(crp["n"].dtype)
            if _ts_used[atom.ref_idx]:
                crp["ts"] = crp["ts"].at[:, 0].set(
                    jnp.where(has, batch_ts[jjc], crp["ts"][:, 0])
                )
            crp["cols"] = {
                name: arr.at[:, 0].set(
                    jnp.where(has, ev[name][jjc].astype(arr.dtype), arr[:, 0])
                )
                for name, arr in crp["cols"].items()
            }
            caps[atom.ref_idx] = crp
            tok = {
                "active": tok["active"],
                "slot": jnp.where(has, p + 1, tok["slot"]),
                "start_ts": tok["start_ts"],
                "entry_ts": jnp.where(has, batch_ts[jjc], tok["entry_ts"]),
                "caps": caps,
            }
            entry_row = jnp.where(has, jj, entry_row)

        # ---- completions (ordered by completion row, then lane) ----
        done = tok["active"] & (tok["slot"] == S)
        cap = out["valid"].shape[0]
        key = jnp.where(
            done, entry_row.astype(jnp.int64) * T + toks, np.int64(1) << 60
        )
        order = jnp.argsort(key).astype(jnp.int32)
        d_sorted = done[order]
        rank = (jnp.cumsum(d_sorted.astype(jnp.int32)) - d_sorted).astype(jnp.int32)
        dest = jnp.where(d_sorted & (out_n + rank < cap), out_n + rank, cap)
        overflow = overflow | (d_sorted & (out_n + rank >= cap)).any()
        src_t = order
        out = dict(out)
        emit_ts = jnp.where(
            entry_row[src_t] >= 0,
            batch_ts[jnp.clip(entry_row[src_t], 0, B - 1)],
            now,
        )
        out["ts"] = _set_at(out["ts"], dest, emit_ts)
        out["valid"] = out["valid"].at[dest].set(True, mode="drop")
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            out[f"n{a.ref_idx}"] = out[f"n{a.ref_idx}"].at[dest].set(
                c["n"][src_t], mode="drop"
            )
            if f"ts{a.ref_idx}" in out:
                out[f"ts{a.ref_idx}"] = _set_at(
                    out[f"ts{a.ref_idx}"], dest, c["ts"][src_t]
                )
            for name in c["cols"]:
                out[f"c{a.ref_idx}.{name}"] = _set_at(
                    out[f"c{a.ref_idx}.{name}"], dest, c["cols"][name][src_t]
                )
        out_n = jnp.minimum(
            out_n + done.sum(dtype=jnp.int32), cap
        ).astype(jnp.int32)
        tok = {**tok, "active": tok["active"] & ~done}
        return tok, out, out_n, overflow

    def _matrix_env(self, tok, row_cols: dict, row_ts, now, override_ref: int) -> Env:
        """[T, 1] token columns vs [1, B] event columns -> [T, B] broadcasts."""
        T = self.T
        cols = {}
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            cols[(a.ref, None, TS_ATTR)] = c["ts"][:, 0][:, None]
            cols[(a.ref, 0, TS_ATTR)] = c["ts"][:, 0][:, None]
            for name in c["cols"]:
                cols[(a.ref, None, name)] = c["cols"][name][:, 0][:, None]
                cols[(a.ref, 0, name)] = c["cols"][name][:, 0][:, None]
            cols[(a.ref, None, "__arrived__")] = (c["n"] > 0)[:, None]
        self._synth_capture_cols(
            cols,
            lambda a, attr: tok["caps"][a.ref_idx]["cols"][attr],
            lambda a: tok["caps"][a.ref_idx]["ts"],
            lambda a: tok["caps"][a.ref_idx]["n"],
            expand=lambda col: col[:, None],
        )
        a = self.refs[override_ref]
        for name, v in row_cols.items():
            cols[(a.ref, None, name)] = v[None, :]
            cols[(a.ref, 0, name)] = v[None, :]
        cols[(a.ref, None, TS_ATTR)] = row_ts[None, :]
        cols[(a.ref, 0, TS_ATTR)] = row_ts[None, :]
        cols[(a.ref, None, "__arrived__")] = jnp.ones((1, 1), dtype=jnp.bool_)
        return Env(cols, now=now)

    def apply_batch_fast(
        self, tok, batch_ts, batch_kind, batch_valid, stream_cols: dict,
        out, out_n, overflow, now,
    ):
        """One vectorized pass over a whole batch of one stream's rows."""
        T = self.T
        B = batch_ts.shape[0]
        S = len(self.slots)
        _keep_cols, _ts_used = self.capture_keep()
        rows = jnp.arange(B, dtype=jnp.int32)
        toks = jnp.arange(T, dtype=jnp.int32)
        v = batch_valid & (batch_kind == KIND_CURRENT)
        entry_row = jnp.full((T,), -1, jnp.int32)  # batch-local hop cursor

        for p, slot in enumerate(self.slots):
            atom = slot.atoms[0]
            if atom.stream_id not in stream_cols:
                continue
            ev = stream_cols[atom.stream_id]
            elig = tok["active"] & (tok["slot"] == p)
            env = self._matrix_env(tok, ev, batch_ts, now, atom.ref_idx)
            cond = jnp.ones((T, B), dtype=jnp.bool_)
            for c in self._conds[(p, atom.ref_idx)]:
                cond = cond & jnp.broadcast_to(c(env), (T, B))
            M = elig[:, None] & v[None, :] & (rows[None, :] > entry_row[:, None]) & cond
            win = _min_within(slot.within_ms, self.within_ms)
            if win is not None:
                started = tok["start_ts"] >= 0
                M = M & ~(
                    started[:, None]
                    & (batch_ts[None, :] - tok["start_ts"][:, None] > win)
                )
            if self.sequence and not slot.persistent and p > 0:
                # strict continuity: the match must be the FIRST valid row
                # after the token's entry; a non-matching next row kills it
                nxt_ok = v[None, :] & (rows[None, :] > entry_row[:, None])
                has_next = nxt_ok.any(axis=1)
                jnext = jnp.argmax(nxt_ok, axis=1).astype(jnp.int32)
                M = M & (rows[None, :] == jnext[:, None])
                die = elig & has_next & ~M.any(axis=1)
                tok = {**tok, "active": tok["active"] & ~die}

            if p == 0 and slot.persistent:
                # `every`: each matching row forks a fresh token one state on
                fork = M.any(axis=0) & v  # [B]
                frank = (jnp.cumsum(fork.astype(jnp.int32)) - fork).astype(jnp.int32)
                free = ~tok["active"]
                free_idx = first_indices(free, B)
                dest = jnp.where(fork, free_idx[jnp.clip(frank, 0, B - 1)], -1)
                okf = fork & (dest >= 0)
                overflow = overflow | (fork & (dest < 0)).any()
                dstc = jnp.where(okf, dest, T)  # T = dropped lane
                active2 = tok["active"].at[dstc].set(True, mode="drop")
                slot2 = tok["slot"].at[dstc].set(1, mode="drop")
                # set_at / column-slice forms: raw 64-bit scatters serialize
                # on TPU (ops/scatter.py) — these run once per batch at [B]
                start2 = _set_at(tok["start_ts"], dstc, batch_ts)
                entry2 = _set_at(tok["entry_ts"], dstc, batch_ts)
                entry_row = entry_row.at[dstc].set(rows, mode="drop")
                caps = [dict(c) for c in tok["caps"]]
                cr = dict(caps[atom.ref_idx])
                cr["n"] = cr["n"].at[dstc].set(1, mode="drop")
                if _ts_used[atom.ref_idx]:
                    cr["ts"] = cr["ts"].at[:, 0].set(
                        _set_at(cr["ts"][:, 0], dstc, batch_ts)
                    )
                cr["cols"] = {
                    name: arr.at[:, 0].set(
                        _set_at(arr[:, 0], dstc, ev[name].astype(arr.dtype))
                    )
                    for name, arr in cr["cols"].items()
                }
                caps[atom.ref_idx] = cr
                tok = {
                    "active": active2, "slot": slot2, "start_ts": start2,
                    "entry_ts": entry2, "caps": caps,
                }
            else:
                has = M.any(axis=1)
                j = jnp.argmax(M, axis=1).astype(jnp.int32)  # first match row
                jc = jnp.clip(j, 0, B - 1)
                adv = has
                mts = batch_ts[jc]
                caps = [dict(c) for c in tok["caps"]]
                cr = dict(caps[atom.ref_idx])
                cr["n"] = jnp.where(adv, 1, cr["n"])
                # column-0 writes via static slice update, not arange scatter
                if _ts_used[atom.ref_idx]:
                    cr["ts"] = cr["ts"].at[:, 0].set(
                        jnp.where(adv, mts, cr["ts"][:, 0])
                    )
                cr["cols"] = {
                    name: arr.at[:, 0].set(
                        jnp.where(adv, ev[name][jc].astype(arr.dtype), arr[:, 0])
                    )
                    for name, arr in cr["cols"].items()
                }
                caps[atom.ref_idx] = cr
                tok = {
                    "active": tok["active"],
                    "slot": jnp.where(adv, p + 1, tok["slot"]),
                    "start_ts": jnp.where(
                        adv & (tok["start_ts"] < 0), mts, tok["start_ts"]
                    ),
                    "entry_ts": jnp.where(adv, mts, tok["entry_ts"]),
                    "caps": caps,
                }
                entry_row = jnp.where(adv, j, entry_row)

        # completions: tokens past the last slot emit, ordered by their
        # completion row (then token index for same-row ties)
        done = tok["active"] & (tok["slot"] == S)
        cap = out["valid"].shape[0]
        key = jnp.where(done, entry_row.astype(jnp.int64) * T + toks, np.int64(1) << 60)
        order = jnp.argsort(key).astype(jnp.int32)  # done tokens first, row order
        d_sorted = done[order]
        rank = (jnp.cumsum(d_sorted.astype(jnp.int32)) - d_sorted).astype(jnp.int32)
        dest = jnp.where(d_sorted & (out_n + rank < cap), out_n + rank, cap)
        overflow = overflow | (d_sorted & (out_n + rank >= cap)).any()
        src = order  # token index per sorted position
        out = dict(out)
        emit_ts = jnp.where(
            entry_row[src] >= 0, batch_ts[jnp.clip(entry_row[src], 0, B - 1)], now
        )
        out["ts"] = _set_at(out["ts"], dest, emit_ts)
        out["valid"] = out["valid"].at[dest].set(True, mode="drop")
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            out[f"n{a.ref_idx}"] = out[f"n{a.ref_idx}"].at[dest].set(c["n"][src], mode="drop")
            if f"ts{a.ref_idx}" in out:
                out[f"ts{a.ref_idx}"] = _set_at(out[f"ts{a.ref_idx}"], dest, c["ts"][src])
            for name in c["cols"]:
                out[f"c{a.ref_idx}.{name}"] = _set_at(
                    out[f"c{a.ref_idx}.{name}"], dest, c["cols"][name][src]
                )
        out_n = jnp.minimum(out_n + done.sum(dtype=jnp.int32), cap).astype(jnp.int32)
        tok = {**tok, "active": tok["active"] & ~done}

        # purge tokens whose within expired by the end of the batch (the scan
        # path kills them on the next arrival; purging bounds table growth)
        last_ts = jnp.max(jnp.where(v, batch_ts, np.int64(0)))
        win_by_slot = np.full((S + 1,), np.iinfo(np.int64).max, dtype=np.int64)
        for p, slot in enumerate(self.slots):
            w = _min_within(slot.within_ms, self.within_ms)
            if w is not None:
                win_by_slot[p] = w
        # select-chain over the (tiny) slot count: keeps the per-slot window
        # durations as scalar literals instead of a device-array const
        slot_c = jnp.clip(tok["slot"], 0, S)
        win_t = jnp.full(slot_c.shape, win_by_slot[S], dtype=jnp.int64)
        for p in range(S):
            win_t = jnp.where(slot_c == p, win_by_slot[p], win_t)
        started = tok["start_ts"] >= 0
        expired = started & (last_ts - tok["start_ts"] > win_t)
        keep0 = jnp.arange(T) == 0  # the arming token never dies
        is_armer = keep0 & np.asarray(self.slots[0].persistent)
        tok = {**tok, "active": tok["active"] & ~(expired & ~is_armer)}
        return tok, out, out_n, overflow

    def init_out(self, cap: int):
        keep_cols, ts_used = self.capture_keep()
        out = {
            "ts": jnp.zeros((cap,), dtype=jnp.int64),
            "valid": jnp.zeros((cap,), dtype=jnp.bool_),
        }
        for a in self.refs:
            schema = self.schemas[a.stream_id]
            out[f"n{a.ref_idx}"] = jnp.zeros((cap,), dtype=jnp.int32)
            if ts_used[a.ref_idx]:
                out[f"ts{a.ref_idx}"] = jnp.zeros(
                    (cap, a.cap), dtype=jnp.int64
                )
            for name, t in schema.attrs:
                if name in keep_cols[a.ref_idx]:
                    out[f"c{a.ref_idx}.{name}"] = jnp.full(
                        (cap, a.cap), null_value(t), dtype=PHYSICAL_DTYPE[t]
                    )
        return out

    def _write_emits(self, out, out_n, overflow, emit, tok, ts):
        cap = out["valid"].shape[0]
        rank = jnp.cumsum(emit.astype(jnp.int32)) - 1
        dest_raw = out_n + rank
        ok = emit & (dest_raw < cap)
        dest = jnp.where(ok, dest_raw, cap)
        overflow = overflow | jnp.any(emit & ~ok)
        out = dict(out)
        out["ts"] = out["ts"].at[dest].set(jnp.broadcast_to(ts, (self.T,)), mode="drop")
        out["valid"] = out["valid"].at[dest].set(True, mode="drop")
        for a in self.refs:
            c = tok["caps"][a.ref_idx]
            out[f"n{a.ref_idx}"] = out[f"n{a.ref_idx}"].at[dest].set(c["n"], mode="drop")
            if f"ts{a.ref_idx}" in out:
                out[f"ts{a.ref_idx}"] = out[f"ts{a.ref_idx}"].at[dest].set(c["ts"], mode="drop")
            for name in c["cols"]:
                key = f"c{a.ref_idx}.{name}"
                out[key] = out[key].at[dest].set(c["cols"][name], mode="drop")
        return (
            out,
            jnp.minimum(out_n + jnp.sum(emit).astype(jnp.int32), cap).astype(jnp.int32),
            overflow,
        )

    def out_env_cols(self, out) -> dict:
        """VarKeys for the selector over the emission buffer (projected: only
        lanes capture_keep() retained exist — every key the selector resolves
        is in the kept set by construction)."""
        cols = {}
        for a in self.refs:
            for name in self.schemas[a.stream_id].attr_names:
                arr = out.get(f"c{a.ref_idx}.{name}")
                if arr is None:
                    continue
                cols[(a.ref, None, name)] = arr[:, 0]
                for k in range(a.cap):
                    cols[(a.ref, k, name)] = arr[:, k]
            tsr = out.get(f"ts{a.ref_idx}")
            if tsr is not None:
                cols[(a.ref, None, TS_ATTR)] = tsr[:, 0]
                for k in range(a.cap):
                    cols[(a.ref, k, TS_ATTR)] = tsr[:, k]
            cols[(a.ref, None, "__arrived__")] = out[f"n{a.ref_idx}"] > 0
        self._synth_capture_cols(
            cols,
            lambda a, attr: out[f"c{a.ref_idx}.{attr}"],
            lambda a: out[f"ts{a.ref_idx}"],
            lambda a: out[f"n{a.ref_idx}"],
        )
        return cols

    def next_timer(self, tok, after=None) -> jnp.ndarray:
        """Earliest absent-slot deadline over active tokens, NO_TIMER if none.

        `after`: deadlines at or before this (the max timer timestamp already
        processed) are excluded — they were handled by that timer pass, and
        re-arming them would loop forever on a logical element whose absent
        deadline passed while its present side is still pending."""
        t = NO_TIMER
        for slot in self.slots:
            absents = [
                a
                for a in slot.atoms
                if a.absent and a.waiting_ms is not None
            ]
            if not absents or (len(slot.atoms) == 1 and not slot.is_absent):
                continue
            both_absent = len(absents) == len(slot.atoms) >= 2
            at_p = tok["active"] & (tok["slot"] == slot.index)
            for a in absents:  # both-absent elements wait per side
                base = tok["entry_ts"]
                if slot.index == 0 and both_absent:
                    # arrivals re-arm that side's window (see apply_event)
                    base = jnp.maximum(
                        base, tok["caps"][a.ref_idx]["ts"][:, 0]
                    )
                dl = jnp.where(at_p, base + a.waiting_ms, NO_TIMER)
                if after is not None:
                    dl = jnp.where(dl > after, dl, NO_TIMER)
                t = jnp.minimum(t, jnp.min(dl))
        return t
