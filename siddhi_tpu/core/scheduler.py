"""Timer scheduling: TIMER-event injection for time-based windows and rates.

Reference: util/Scheduler.java:41-115 + util/SystemTimeBasedScheduler.java — a
dedicated thread injects TIMER events into the processor chain at notified
times. Here each target keeps at most one outstanding fire time (window steps
re-report their next deadline via the step's aux output, so the schedule is
self-sustaining).
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable


class SystemTimeScheduler:
    def __init__(self) -> None:
        self._heap: list[tuple[int, int, Callable[[int], None]]] = []
        self._times: dict[int, int] = {}  # id(target) -> scheduled time
        self._cv = threading.Condition()
        self._serial = 0
        self._stop = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        with self._cv:
            if self._thread is None:
                self._thread = threading.Thread(target=self._run, daemon=True)
                self._thread.start()

    def notify_at(self, t_ms: int, target: Callable[[int], None]) -> None:
        with self._cv:
            key = id(target)
            prev = self._times.get(key)
            if prev is not None and prev <= t_ms:
                return  # an earlier-or-equal fire is already pending
            self._times[key] = t_ms
            self._serial += 1
            heapq.heappush(self._heap, (t_ms, self._serial, target))
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                    not self._heap or self._heap[0][0] > time.time() * 1000
                ):
                    if self._heap:
                        delay = max(self._heap[0][0] / 1000 - time.time(), 0.0)
                        self._cv.wait(timeout=min(delay, 0.25))
                    else:
                        self._cv.wait(timeout=0.25)
                if self._stop:
                    return
                t_ms, _, target = heapq.heappop(self._heap)
                if self._times.get(id(target)) == t_ms:
                    del self._times[id(target)]
                else:
                    continue  # superseded entry
            try:
                target(t_ms)
            except Exception:  # pragma: no cover - target errors must not kill timing
                import traceback

                traceback.print_exc()

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        # join so no timer target is mid-flight (e.g. inside a device call)
        # when the interpreter tears down — that aborts the process
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
