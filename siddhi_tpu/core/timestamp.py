"""Playback (event-time) clock + event-time scheduler.

Reference: util/timestamp/ — TimestampGenerator SPI with system-time and
event-time impls; `@app:playback(idle.time='100 millisec', increment='2 sec')`
(SiddhiAppParser.java:166-212) drives the app clock from event timestamps with
an idle heartbeat; util/EventTimeBasedScheduler.java:28 fires timers on the
virtual clock instead of wall time.
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable, Optional


class EventTimeClock:
    """Virtual clock advanced by event timestamps; optional idle heartbeat
    bumps it by `increment_ms` after `idle_ms` without events."""

    def __init__(
        self,
        idle_ms: Optional[int] = None,
        increment_ms: Optional[int] = None,
    ):
        self._t = 0
        self._lock = threading.Lock()
        self._listeners: list[Callable[[int], None]] = []
        self.idle_ms = idle_ms
        self.increment_ms = increment_ms
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_advance = None

    def now(self) -> int:
        with self._lock:
            return self._t

    def on_advance(self, fn: Callable[[int], None]) -> None:
        self._listeners.append(fn)

    def advance(self, t_ms: int) -> None:
        import time as _time

        with self._lock:
            if t_ms <= self._t:
                return
            self._t = t_ms
            self._last_advance = _time.monotonic()
        for fn in self._listeners:
            fn(t_ms)

    def start_heartbeat(self) -> None:
        if self.idle_ms is None or self.increment_ms is None or self._thread:
            return
        self._stop.clear()

        def run():
            import time as _time

            while not self._stop.wait(self.idle_ms / 1000.0):
                with self._lock:
                    idle = (
                        self._last_advance is not None
                        and (_time.monotonic() - self._last_advance) * 1000
                        >= self.idle_ms
                    )
                    t = self._t + self.increment_ms if idle else None
                if t is not None:
                    self.advance(t)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None


class EventTimeScheduler:
    """Same notify_at contract as SystemTimeScheduler, but fires when the
    playback clock passes the scheduled time (reference:
    util/EventTimeBasedScheduler.java)."""

    def __init__(self, clock: EventTimeClock):
        self.clock = clock
        self._heap: list[tuple[int, int, Callable[[int], None]]] = []
        self._times: dict[int, int] = {}
        self._lock = threading.Lock()
        self._serial = 0
        self._tls = threading.local()  # re-entrancy guard for notify_at
        clock.on_advance(self._on_advance)

    def start(self) -> None:  # same surface as SystemTimeScheduler
        pass

    def notify_at(self, t_ms: int, target: Callable[[int], None]) -> None:
        with self._lock:
            key = id(target)
            prev = self._times.get(key)
            if prev is not None and prev <= t_ms:
                return
            self._times[key] = t_ms
            self._serial += 1
            heapq.heappush(self._heap, (t_ms, self._serial, target))
        # already due? (no-op when called from inside a dispatch: the outer
        # _on_advance loop re-checks the heap, so periodic targets that
        # re-arm themselves from their own callback cannot recurse)
        if not getattr(self._tls, "dispatching", False):
            self._on_advance(self.clock.now())

    def _on_advance(self, now_ms: int) -> None:
        if getattr(self._tls, "dispatching", False):
            return  # the outer loop will pick up anything newly due
        self._tls.dispatching = True
        try:
            while True:
                with self._lock:
                    if not self._heap or self._heap[0][0] > now_ms:
                        return
                    t_ms, _, target = heapq.heappop(self._heap)
                    if self._times.get(id(target)) == t_ms:
                        del self._times[id(target)]
                    else:
                        continue
                try:
                    target(t_ms)
                except Exception:  # pragma: no cover
                    import traceback

                    traceback.print_exc()
        finally:
            self._tls.dispatching = False

    def shutdown(self) -> None:
        self.clock.stop()
