"""Sources, sinks, mappers, the in-memory broker, and distributed sinks.

Reference: stream/input/source/Source.java:42-126 (connect-with-retry,
pause/resume), SourceMapper.java, InMemorySource.java; stream/output/sink/
Sink.java:47-177 (publish with reconnect), SinkMapper.java, distributed
strategies stream/output/sink/distributed/* + util/transport/
{Single,Multi}ClientDistributedSink.java; util/transport/InMemoryBroker.java:29-53
(static topic pub/sub) and BackoffRetryCounter.java.

Host-side subsystem: transports feed the junction ingest path (which packs
columnar device batches); egress drains decoded events through mappers.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Callable, Optional

from siddhi_tpu.core.errors import (
    ConnectionUnavailableError,
    SiddhiAppCreationError,
)
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.extension import lookup
from siddhi_tpu.testing import faults as _faults


# ---------------------------------------------------------------------------
# in-memory broker (reference: util/transport/InMemoryBroker.java)
# ---------------------------------------------------------------------------


class InMemoryBroker:
    _lock = threading.RLock()
    _topics: dict[str, list] = {}

    @classmethod
    def subscribe(cls, subscriber) -> None:
        """subscriber: object with .topic and .on_message(payload)."""
        with cls._lock:
            cls._topics.setdefault(subscriber.topic, []).append(subscriber)

    @classmethod
    def unsubscribe(cls, subscriber) -> None:
        with cls._lock:
            subs = cls._topics.get(subscriber.topic, [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, payload) -> None:
        with cls._lock:
            subs = list(cls._topics.get(topic, []))
        for s in subs:
            s.on_message(payload)


class _BrokerSubscriber:
    def __init__(self, topic: str, fn: Callable):
        self.topic = topic
        self.on_message = fn


# ---------------------------------------------------------------------------
# retry/backoff (reference: util/transport/BackoffRetryCounter.java)
# ---------------------------------------------------------------------------


class BackoffRetryCounter:
    """Exponential backoff ladder with an optional interval cap and bounded
    jitter. Jitter de-synchronizes mass reconnects after a broker blip (every
    disconnected transport would otherwise retry at the exact same instants —
    a thundering herd against the recovering endpoint)."""

    INTERVALS_MS = [50, 100, 500, 1000, 5000, 10000, 30000, 60000]

    def __init__(
        self,
        max_interval_ms: int | None = None,
        jitter: float = 0.0,
        rand: random.Random | None = None,
    ) -> None:
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self._i = 0
        self.max_interval_ms = max_interval_ms
        self.jitter = float(jitter)
        self._rand = rand if rand is not None else random.Random()

    def reset(self) -> None:
        self._i = 0

    @property
    def attempts(self) -> int:
        return self._i

    def next_interval_ms(self) -> int:
        iv = self.INTERVALS_MS[min(self._i, len(self.INTERVALS_MS) - 1)]
        self._i += 1
        if self.jitter:
            # additive bounded jitter: [iv, iv * (1 + jitter)] — never earlier
            # than the base ladder, so backoff guarantees still hold
            iv += int(self._rand.uniform(0.0, self.jitter * iv))
        if self.max_interval_ms is not None:
            # the cap is a HARD ceiling: jitter never pushes past it
            iv = min(iv, int(self.max_interval_ms))
        return iv


def _make_retry_counter(options: dict) -> BackoffRetryCounter:
    """Per-transport counter from @source/@sink options:
    retry.max.interval.ms caps the ladder, retry.jitter in [0,1] spreads it."""
    try:
        cap = options.get("retry.max.interval.ms")
        return BackoffRetryCounter(
            max_interval_ms=int(cap) if cap is not None else None,
            jitter=float(options.get("retry.jitter", 0.0)),
        )
    except ValueError as e:
        # annotation problems surface as app-creation errors like every
        # other option-validation path
        raise SiddhiAppCreationError(
            f"invalid retry options (retry.max.interval.ms / retry.jitter): {e}"
        ) from e


def _connect_with_retry(transport) -> None:
    """Shared source/sink reconnect loop: exponential backoff on a single
    daemon chain — concurrent publish failures do NOT spawn parallel chains
    (reference: Source.connectWithRetry:126 / Sink.java:128-160)."""
    with transport._conn_lock:
        if transport._stopped or transport._reconnecting:
            return
        transport._reconnecting = True
    retry_scheduled = False
    try:
        # _conn_lock serializes every connect() on this transport — including
        # a sink's in-line on.error='RETRY' loop racing this background chain;
        # skip connect() when that loop already restored the link (a second
        # connect would leak a connection on socket-style transports)
        with transport._conn_lock:
            if not transport.connected:
                transport.connect()
                transport.connected = True
            transport._retry.reset()
    except ConnectionUnavailableError:
        iv = transport._retry.next_interval_ms()
        retry_scheduled = True

        def retry():
            time.sleep(iv / 1000.0)
            with transport._conn_lock:
                transport._reconnecting = False
            if not transport._stopped:
                _connect_with_retry(transport)

        threading.Thread(target=retry, daemon=True).start()
    finally:
        if not retry_scheduled:
            # any other connect() failure must not wedge future reconnects
            with transport._conn_lock:
                transport._reconnecting = False


# ---------------------------------------------------------------------------
# source mappers (wire payload -> event rows)
# ---------------------------------------------------------------------------


class SourceMapper:
    """reference: stream/input/source/SourceMapper.java."""

    def init(self, schema, options: dict) -> None:
        self.schema = schema
        self.options = options

    def map(self, payload) -> list[tuple]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    """Payload is a row tuple, an Event, or a list of either."""

    def map(self, payload) -> list[tuple]:
        if isinstance(payload, Event):
            return [tuple(payload.data)]
        if isinstance(payload, (list,)) and payload and isinstance(
            payload[0], (tuple, list, Event)
        ):
            return [
                tuple(p.data) if isinstance(p, Event) else tuple(p) for p in payload
            ]
        return [tuple(payload)]


class JsonSourceMapper(SourceMapper):
    """JSON object (or list) keyed by attribute name; reference ecosystem:
    siddhi-map-json's default mapping."""

    def map(self, payload) -> list[tuple]:
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) else payload
        objs = obj if isinstance(obj, list) else [obj]
        out = []
        for o in objs:
            if "event" in o:  # siddhi-map-json envelope {"event": {...}}
                o = o["event"]
            out.append(tuple(o.get(n) for n in self.schema.attr_names))
        return out


class KeyValueSourceMapper(SourceMapper):
    def map(self, payload) -> list[tuple]:
        objs = payload if isinstance(payload, list) else [payload]
        return [tuple(o.get(n) for n in self.schema.attr_names) for o in objs]


class TextSourceMapper(SourceMapper):
    """`attr:value` lines (reference ecosystem: siddhi-map-text default)."""

    def map(self, payload) -> list[tuple]:
        fields: dict[str, str] = {}
        for line in str(payload).splitlines():
            if ":" in line:
                k, _, v = line.partition(":")
                fields[k.strip()] = v.strip().strip('"')
        from siddhi_tpu.core.types import AttrType

        row = []
        for name, t in self.schema.attrs:
            v: Any = fields.get(name)
            if v is None:
                row.append(None)
            elif t in (AttrType.INT, AttrType.LONG):
                row.append(int(v))
            elif t in (AttrType.FLOAT, AttrType.DOUBLE):
                row.append(float(v))
            elif t is AttrType.BOOL:
                row.append(v.lower() == "true")
            else:
                row.append(v)
        return [tuple(row)]


SOURCE_MAPPERS = {
    "passthrough": PassThroughSourceMapper,
    "json": JsonSourceMapper,
    "keyvalue": KeyValueSourceMapper,
    "text": TextSourceMapper,
}


# ---------------------------------------------------------------------------
# sink mappers (events -> wire payload)
# ---------------------------------------------------------------------------


class SinkMapper:
    def init(self, schema, options: dict) -> None:
        self.schema = schema
        self.options = options

    def map(self, events: list[Event]):
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, events: list[Event]):
        return events


class JsonSinkMapper(SinkMapper):
    def map(self, events: list[Event]):
        return json.dumps(
            [
                {"event": dict(zip(self.schema.attr_names, e.data))}
                for e in events
            ]
        )


class KeyValueSinkMapper(SinkMapper):
    def map(self, events: list[Event]):
        return [dict(zip(self.schema.attr_names, e.data)) for e in events]


class TextSinkMapper(SinkMapper):
    def map(self, events: list[Event]):
        return "\n\n".join(
            "\n".join(f"{n}:{v}" for n, v in zip(self.schema.attr_names, e.data))
            for e in events
        )


SINK_MAPPERS = {
    "passthrough": PassThroughSinkMapper,
    "json": JsonSinkMapper,
    "keyvalue": KeyValueSinkMapper,
    "text": TextSinkMapper,
}


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------


SOURCE_ON_ERROR_ACTIONS = ("LOG", "STREAM", "STORE")


class Source:
    """Transport SPI (reference: Source.java:42-126). Subclasses implement
    connect/disconnect; arriving payloads go through self.mapper into
    self.input_handler.

    `on.error` gives ingress the same failure policies sinks and junctions
    have — a payload the mapper cannot decode or the handler rejects:

    LOG     log + drop the payload
    STREAM  route the mapped rows (plus the error) to the stream's fault
            stream `!S` — requires the stream to declare
            @OnError(action='STREAM'); an UNMAPPABLE payload has no typed
            rows to publish and falls back to STORE (store wired) or LOG
    STORE   spill the raw wire payload to the manager's ErrorStore; replay
            re-delivers it through the mapper

    Without the option, failures propagate to the delivering thread —
    the pre-policy behavior transports already rely on.
    """

    def init(self, stream_id: str, options: dict, mapper: SourceMapper, input_handler) -> None:
        self.stream_id = stream_id
        self.options = options
        self.mapper = mapper
        self.input_handler = input_handler
        self.paused = False
        self._retry = _make_retry_counter(options)
        self.connected = False
        self._stopped = False
        self._reconnecting = False
        self._conn_lock = threading.Lock()
        oe = options.get("on.error")
        self.on_error = str(oe).upper() if oe is not None else None
        if self.on_error is not None and self.on_error not in SOURCE_ON_ERROR_ACTIONS:
            raise SiddhiAppCreationError(
                f"@source on stream '{stream_id}': unknown on.error "
                f"'{self.on_error}' (expected one of "
                f"{SOURCE_ON_ERROR_ACTIONS})"
            )
        # wired by the app runtime after build_source
        self.error_store_fn: Optional[Callable[[], object]] = None
        self.app_name = ""
        self.fault_sender: Optional[Callable] = None  # rows+error -> '!S'
        self.on_error_stats: Optional[Callable[[int], None]] = None

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def stop(self) -> None:
        """Cancel pending reconnects and disconnect."""
        self._stopped = True
        self.disconnect()

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def connect_with_retry(self) -> None:
        """reference: Source.connectWithRetry:126 — exponential backoff in a
        daemon thread until the transport comes up (or disconnect() cancels)."""
        _connect_with_retry(self)

    def deliver(self, payload, handler=None) -> bool:
        """Map + inject one wire payload; True when it reached the stream.
        With no `on.error` policy, failures propagate to the delivering
        thread (pre-policy behavior). `handler` overrides the wired input
        handler for ONE delivery — the error-replay path passes a raw
        (admission-free) handler, because a replayed payload was admitted
        once already and an over-quota gate would silently shed it while
        the replay caller purges the entry."""
        h = handler if handler is not None else self.input_handler
        if self.paused:
            return False
        if self.on_error is None:
            rows = self.mapper.map(payload)
            if rows:
                h.send_many(rows)
            return True
        try:
            rows = self.mapper.map(payload)
        except Exception as e:
            return self._on_deliver_failure(payload, None, e)
        try:
            # failure_ownership: a downstream dispatch failure is caught and
            # handled RIGHT HERE by this source's on.error policy — it must
            # not double as a crash signal that restarts a supervised app
            # over a payload the policy already captured
            from siddhi_tpu.core.supervision import failure_ownership

            with failure_ownership():
                if rows:
                    h.send_many(rows)
            return True
        except Exception as e:
            return self._on_deliver_failure(payload, rows, e)

    def _on_deliver_failure(self, payload, rows, exc: Exception) -> bool:
        import logging

        log = logging.getLogger(f"siddhi_tpu.source.{self.stream_id}")
        if self.on_error_stats is not None:
            self.on_error_stats(1)
        mode = self.on_error
        if mode == "STREAM" and rows and self.fault_sender is not None:
            try:
                self.fault_sender(rows, f"{type(exc).__name__}: {exc}")
                return True
            except Exception:
                log.exception(
                    "source '%s': fault-stream routing failed; falling "
                    "back to the error store / log", self.stream_id,
                )
            mode = "STORE"
        elif mode == "STREAM":
            # no typed rows (the mapper itself failed) or no fault stream
            mode = "STORE"
        if mode == "STORE":
            from siddhi_tpu.core.error_store import ORIGIN_SOURCE, make_entry

            store = (
                self.error_store_fn() if self.error_store_fn is not None
                else None
            )
            if store is not None:
                store.store(make_entry(
                    self.app_name, ORIGIN_SOURCE, self.stream_id, exc,
                    payload=payload,
                ))
                return False
            log.error(
                "source '%s': on.error needs an error store but none is "
                "available; the payload was dropped", self.stream_id,
            )
            return False
        log.error(
            "source '%s': payload could not be mapped/delivered (%s); it "
            "was dropped (on.error='LOG')", self.stream_id, exc,
        )
        return False


class InMemorySource(Source):
    """reference: stream/input/source/InMemorySource.java — broker topic."""

    def connect(self) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise SiddhiAppCreationError("@source(type='inMemory') needs a topic")
        self._sub = _BrokerSubscriber(topic, self.deliver)
        InMemoryBroker.subscribe(self._sub)

    def disconnect(self) -> None:
        sub = getattr(self, "_sub", None)
        if sub is not None:
            InMemoryBroker.unsubscribe(sub)


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


ON_ERROR_ACTIONS = ("LOG", "RETRY", "WAIT", "STORE")


class Sink:
    """reference: Sink.java:47-177 — publish with reconnect on
    ConnectionUnavailableError, failure policy from `on.error`:

    LOG    log + drop the payload, reconnect in the background (default)
    RETRY  re-attempt connect+publish in the calling thread with backoff
           (retry.count attempts, default 3); exhausted -> log + drop,
           background reconnect
    WAIT   block the calling thread until the transport reconnects, then
           publish — back-pressures the sender; on shutdown the held payload
           spills to the error store instead of silently dropping
    STORE  spill the payload to the manager's ErrorStore for later replay
    """

    def init(self, stream_id: str, options: dict, mapper: Optional[SinkMapper]) -> None:
        self.stream_id = stream_id
        self.options = options
        self.mapper = mapper
        self.connected = False
        self._retry = _make_retry_counter(options)
        self._stopped = False
        self._reconnecting = False
        self._conn_lock = threading.Lock()
        self.on_error = str(options.get("on.error", "LOG")).upper()
        if self.on_error not in ON_ERROR_ACTIONS:
            raise SiddhiAppCreationError(
                f"@sink on stream '{stream_id}': unknown on.error "
                f"'{self.on_error}' (expected one of {ON_ERROR_ACTIONS})"
            )
        try:
            # default bounded at 3 (650 ms worst case): the caller may hold
            # the app-wide process lock, so a dead transport must not stall
            # every stream for the full 106 s ladder
            self._retry_count = int(options.get("retry.count", 3))
        except ValueError as e:
            raise SiddhiAppCreationError(
                f"@sink on stream '{stream_id}': invalid retry.count "
                f"'{options.get('retry.count')}'"
            ) from e
        # wired by the app runtime after build_sink
        self.error_store_fn: Optional[Callable[[], object]] = None
        self.app_name = ""
        self.sink_ref = ""
        self.on_error_stats: Optional[Callable[[int], None]] = None
        self.on_publish_stats: Optional[Callable[[int], None]] = None
        self.latency_tracker = None  # map+publish latency histogram

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def connect_with_retry(self) -> None:
        _connect_with_retry(self)

    def publish(self, payload) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        self._stopped = True
        self.disconnect()

    def on_events(self, events: list[Event]) -> None:
        from siddhi_tpu.observability.metrics import timed

        with timed(self.latency_tracker):
            payload = self.mapper.map(events) if self.mapper else events
            ok = self.publish_guarded(payload)
            # count only DELIVERED events: a down transport must not report
            # healthy egress throughput while dropping/spilling payloads
            if ok and self.on_publish_stats is not None:
                self.on_publish_stats(len(events))

    def publish_guarded(self, payload) -> bool:
        """Publish under the sink's on.error policy; True when the payload was
        delivered (reference: Sink.java:128-160 onError/connectAndPublish)."""
        try:
            # fault-injection site `sink_publish` (testing/faults.py):
            # defaults to ConnectionUnavailableError so the sink's on.error
            # policy engages exactly like a real transport outage
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.check(
                    "sink_publish", f"{self.app_name}:{self.stream_id}"
                )
            self.publish(payload)
            return True
        except ConnectionUnavailableError as e:
            self.connected = False
            if self.on_error_stats is not None:
                self.on_error_stats(1)
            return self._on_publish_failure(payload, e)

    def _on_publish_failure(self, payload, exc: ConnectionUnavailableError) -> bool:
        import logging

        log = logging.getLogger(f"siddhi_tpu.sink.{self.stream_id}")
        mode = self.on_error
        if mode == "RETRY":
            retry = _make_retry_counter(self.options)
            # bounded in-line retries, in the CALLING thread: transient blips
            # resolve in-line (and in-order); a dead transport falls back to
            # LOG semantics with a background reconnect chain
            while retry.attempts < self._retry_count:
                if self._stopped:
                    return False
                time.sleep(retry.next_interval_ms() / 1000.0)
                try:
                    with self._conn_lock:
                        # serialized with other in-line retriers; a transport
                        # must never see two concurrent connect() calls
                        if not self.connected:
                            self.connect()
                            self.connected = True
                    self.publish(payload)
                    return True
                except ConnectionUnavailableError:
                    self.connected = False
            log.error(
                "sink '%s': on.error='RETRY' exhausted its backoff ladder; "
                "the payload was dropped", self.stream_id,
            )
            self.connect_with_retry()
            return False
        if mode == "WAIT":
            # block the sender until the background reconnect chain lands
            # (reference: Sink connectWithRetry + isTryingToConnect spin)
            self.connect_with_retry()
            retry = _make_retry_counter(self.options)
            while not self._stopped:
                if self.connected:
                    try:
                        self.publish(payload)
                        return True
                    except ConnectionUnavailableError:
                        self.connected = False
                        self.connect_with_retry()
                        # a half-up endpoint (connects fine, rejects publishes)
                        # must see ladder-paced attempts, not a 2 ms hot spin
                        time.sleep(retry.next_interval_ms() / 1000.0)
                        continue
                time.sleep(0.002)
            # shutdown while blocked: WAIT promises no silent drops — spill
            # to the error store when one is wired, and always say so
            from siddhi_tpu.core.error_store import ORIGIN_SINK, make_entry

            store = self.error_store_fn() if self.error_store_fn is not None else None
            if store is not None:
                store.store(make_entry(
                    self.app_name, ORIGIN_SINK, self.stream_id, exc,
                    payload=payload, sink_ref=self.sink_ref,
                ))
                log.error(
                    "sink '%s': shut down while on.error='WAIT' was holding a "
                    "payload; it was spilled to the error store", self.stream_id,
                )
            else:
                log.error(
                    "sink '%s': shut down while on.error='WAIT' was holding a "
                    "payload and no error store is wired; it was dropped",
                    self.stream_id,
                )
            return False
        if mode == "STORE":
            from siddhi_tpu.core.error_store import ORIGIN_SINK, make_entry

            store = self.error_store_fn() if self.error_store_fn is not None else None
            if store is None:
                log.error(
                    "sink '%s': on.error='STORE' but no error store is "
                    "available; the payload was dropped", self.stream_id,
                )
            else:
                store.store(make_entry(
                    self.app_name, ORIGIN_SINK, self.stream_id, exc,
                    payload=payload, sink_ref=self.sink_ref,
                ))
            self.connect_with_retry()
            return False
        # LOG (default; previous behavior + an explicit error line)
        log.error(
            "sink '%s': publish failed (%s); the payload was dropped and a "
            "background reconnect was started", self.stream_id, exc,
        )
        self.connect_with_retry()
        return False


class InMemorySink(Sink):
    def connect(self) -> None:
        self.topic = self.options.get("topic")
        if self.topic is None:
            raise SiddhiAppCreationError("@sink(type='inMemory') needs a topic")

    def publish(self, payload) -> None:
        InMemoryBroker.publish(self.topic, payload)


class LogSink(Sink):
    """reference: LogSink — event-level tracing egress."""

    def connect(self) -> None:
        import logging

        self._log = logging.getLogger(f"siddhi_tpu.sink.{self.stream_id}")

    def publish(self, payload) -> None:
        self._log.info("%s : %s", self.stream_id, payload)


SOURCES = {"inmemory": InMemorySource}
SINKS = {"inmemory": InMemorySink, "log": LogSink}


# ---------------------------------------------------------------------------
# distributed sinks (reference: stream/output/sink/distributed/*)
# ---------------------------------------------------------------------------


class DistributedSink:
    """Egress fan-out over N destination sinks with a distribution strategy
    (reference: RoundRobin/Partitioned/Broadcast DistributionStrategy)."""

    def __init__(self, sinks: list[Sink], strategy: str, partition_key: Optional[str], schema):
        self.sinks = sinks
        self.strategy = strategy.lower()
        self.partition_key = partition_key
        self.schema = schema
        self._rr = 0
        if self.strategy not in ("roundrobin", "partitioned", "broadcast"):
            raise SiddhiAppCreationError(
                f"unknown distribution strategy '{strategy}'"
            )
        if self.strategy == "partitioned" and partition_key is None:
            raise SiddhiAppCreationError(
                "partitioned distribution needs partitionKey"
            )

    def connect_with_retry(self) -> None:
        for s in self.sinks:
            s.connect_with_retry()

    def disconnect(self) -> None:
        for s in self.sinks:
            s.disconnect()

    def stop(self) -> None:
        for s in self.sinks:
            s.stop()

    def on_events(self, events: list[Event]) -> None:
        n = len(self.sinks)
        if self.strategy == "broadcast":
            for s in self.sinks:
                s.on_events(events)
        elif self.strategy == "roundrobin":
            for e in events:
                self.sinks[self._rr % n].on_events([e])
                self._rr += 1
        else:  # partitioned
            import zlib

            idx = self.schema.index_of(self.partition_key)
            buckets: dict[int, list[Event]] = {}
            for e in events:
                # stable across processes (Python's hash() is salted)
                h = zlib.crc32(repr(e.data[idx]).encode())
                buckets.setdefault(h % n, []).append(e)
            for i, evs in buckets.items():
                self.sinks[i].on_events(evs)


def wire_source_error_handling(
    source: Source, error_store_fn: Callable[[], object], app_name: str,
    fault_sender: Optional[Callable] = None,
    on_error_stats: Optional[Callable[[int], None]] = None,
) -> None:
    """Attach app-level error plumbing to a source. `fault_sender(rows,
    error)` publishes typed rows to the stream's `!S` fault junction —
    required for `on.error='STREAM'` (the app runtime passes None when the
    stream declares no @OnError(action='STREAM'), which is a creation
    error for a STREAM-policy source)."""
    if source.on_error == "STREAM" and fault_sender is None:
        raise SiddhiAppCreationError(
            f"@source on stream '{source.stream_id}': on.error='STREAM' "
            f"needs the stream to declare @OnError(action='STREAM') so the "
            f"fault stream '!{source.stream_id}' exists"
        )
    source.error_store_fn = error_store_fn
    source.app_name = app_name
    source.fault_sender = fault_sender
    source.on_error_stats = on_error_stats


def wire_sink_error_handling(
    sink, error_store_fn: Callable[[], object], app_name: str,
    sink_ref: str, on_error_stats: Optional[Callable[[int], None]] = None,
    on_publish_stats: Optional[Callable[[int], None]] = None,
    latency_tracker=None,
) -> None:
    """Attach app-level error/metrics plumbing to a (possibly distributed)
    sink. `sink_ref` uniquely names this @sink within the app; distributed
    destinations get `.0`, `.1`, ... suffixes so STORE entries identify the
    exact failing destination for replay. Throughput/latency trackers are
    shared across a distributed sink's destinations (one egress component)."""
    if isinstance(sink, DistributedSink):
        targets = [(s, f"{sink_ref}.{i}") for i, s in enumerate(sink.sinks)]
    else:
        targets = [(sink, sink_ref)]
    for s, ref in targets:
        s.error_store_fn = error_store_fn
        s.app_name = app_name
        s.sink_ref = ref
        s.on_error_stats = on_error_stats
        s.on_publish_stats = on_publish_stats
        s.latency_tracker = latency_tracker


# ---------------------------------------------------------------------------
# assembly from @source/@sink annotations
# ---------------------------------------------------------------------------


def _options(ann) -> dict:
    return {k: v for k, v in ann.elements if k is not None}


def _make_source_mapper(map_ann, schema) -> SourceMapper:
    mtype = (map_ann.element("type") if map_ann else None) or "passThrough"
    cls = SOURCE_MAPPERS.get(mtype.lower()) or lookup("source_mapper", mtype)
    if cls is None:
        raise SiddhiAppCreationError(f"unknown source mapper '{mtype}'")
    m = cls()
    m.init(schema, _options(map_ann) if map_ann else {})
    return m


def _make_sink_mapper(map_ann, schema) -> SinkMapper:
    mtype = (map_ann.element("type") if map_ann else None) or "passThrough"
    cls = SINK_MAPPERS.get(mtype.lower()) or lookup("sink_mapper", mtype)
    if cls is None:
        raise SiddhiAppCreationError(f"unknown sink mapper '{mtype}'")
    m = cls()
    m.init(schema, _options(map_ann) if map_ann else {})
    return m


def build_source(ann, stream_id: str, schema, input_handler) -> Source:
    from siddhi_tpu.query_api.annotation import find_annotation

    stype = ann.element("type")
    if stype is None:
        raise SiddhiAppCreationError("@source needs a type")
    cls = SOURCES.get(stype.lower()) or lookup("source", stype)
    if cls is None:
        raise SiddhiAppCreationError(f"unknown source type '{stype}'")
    mapper = _make_source_mapper(find_annotation(ann.annotations, "map"), schema)
    src = cls()
    src.init(stream_id, _options(ann), mapper, input_handler)
    return src


def build_sink(ann, stream_id: str, schema) -> object:
    from siddhi_tpu.query_api.annotation import find_annotation, find_all

    stype = ann.element("type")
    if stype is None:
        raise SiddhiAppCreationError("@sink needs a type")
    cls = SINKS.get(stype.lower()) or lookup("sink", stype)
    if cls is None:
        raise SiddhiAppCreationError(f"unknown sink type '{stype}'")
    map_ann = find_annotation(ann.annotations, "map")
    dist = find_annotation(ann.annotations, "distribution")
    if dist is None:
        mapper = _make_sink_mapper(map_ann, schema)
        sink = cls()
        sink.init(stream_id, _options(ann), mapper)
        return sink
    # distributed: one destination sink per @destination, base options shared
    dests = find_all(dist.annotations, "destination")
    if not dests:
        raise SiddhiAppCreationError("@distribution needs @destination entries")
    sinks = []
    for d in dests:
        mapper = _make_sink_mapper(map_ann, schema)
        s = cls()
        s.init(stream_id, {**_options(ann), **_options(d)}, mapper)
        sinks.append(s)
    return DistributedSink(
        sinks,
        dist.element("strategy", "roundRobin"),
        dist.element("partitionKey"),
        schema,
    )
