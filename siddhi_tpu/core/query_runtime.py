"""Per-query compilation and runtime container.

Reference: query/QueryRuntime.java:45-200 wires receiver -> processor chain ->
selector -> rate limiter -> callback as runtime objects. Here the whole chain is
compiled once into a single pure jax step function
`(state, in_batch, now) -> (state', out_batch)` and jitted; the runtime object
owns the device state and the host-side output routing.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import (
    EventBatch,
    KIND_CURRENT,
    KIND_EXPIRED,
    KIND_TIMER,
    StreamSchema,
)
from siddhi_tpu.core.executor import Scope, compile_expression
from siddhi_tpu.core.flow import Flow
from siddhi_tpu.core.selector import CompiledSelector
from siddhi_tpu.core.types import AttrType, InternTable
from siddhi_tpu.query_api.annotation import find_annotation
from siddhi_tpu.query_api.execution import (
    Filter,
    InsertIntoStream,
    OutputEventsFor,
    Query,
    ReturnStream,
    SingleInputStream,
    StreamFunctionHandler,
    WindowHandler,
)


class CompiledSingleChain:
    """Ordered filter / stream-function / window stages over one input stream
    (reference: SingleInputStreamParser.generateProcessor chain assembly).
    Stream functions append attribute columns; the chain's effective output
    schema is `out_attrs`."""

    def __init__(
        self,
        stream: SingleInputStream,
        schema: StreamSchema,
        scope: Scope,
        window_factory: Optional[Callable] = None,
    ):
        from siddhi_tpu.core.stream_function import make_stream_function

        self.schema = schema
        self.ref = stream.alias or stream.stream_id
        self.window = None
        # lineage probe (observability/lineage.py): called on the post-
        # filter/fn, pre-window flow during tracing to emit the admit mask
        # (+ group key / window-time) as `__lin.*` aux lanes; None (no
        # call) when @app:lineage is off
        self.lineage_probe = None
        self.stages: list[tuple[str, object]] = []
        attrs = dict(schema.attr_types)
        for h in stream.handlers:
            if isinstance(h, Filter):
                cond = compile_expression(h.expression, scope)
                if cond.type is not AttrType.BOOL:
                    raise SiddhiAppCreationError("filter must be a boolean expression")
                self.stages.append(("filter", cond))
            elif isinstance(h, WindowHandler):
                if self.window is not None:
                    raise SiddhiAppCreationError("only one window per stream")
                if window_factory is None:
                    raise SiddhiAppCreationError(
                        "windows are not available at this site"
                    )
                win_schema = StreamSchema(schema.stream_id, list(attrs.items()))
                self.window = window_factory(h.window, win_schema, self.ref)
                self.stages.append(("window", self.window))
            elif isinstance(h, StreamFunctionHandler):
                stage = make_stream_function(
                    h, attrs, self.ref, scope, schema.stream_id
                )
                for name, t in stage.new_attrs:
                    if name in attrs:
                        raise SiddhiAppCreationError(
                            f"stream function '#{h.name}' output '{name}' "
                            "collides with an existing attribute"
                        )
                    attrs[name] = t
                    # later filters/selectors resolve the appended attrs
                    scope.add_stream(self.ref, attrs)
                self.stages.append(("fn", stage))
        self.out_attrs: list[tuple[str, AttrType]] = list(attrs.items())

    def init_state(self):
        return self.window.init_state() if self.window is not None else ()

    def apply(self, state, flow: Flow):
        probe = self.lineage_probe
        for kind, stage in self.stages:
            if kind == "filter":
                flow = self._filter(flow, [stage])
            elif kind == "fn":
                flow = stage.apply(flow)
            else:  # window
                if probe is not None:
                    probe(flow)  # admit mask = post-filter, pre-window
                    probe = None
                state, flow = stage.apply(state, flow)
        if probe is not None:
            probe(flow)  # windowless chain: probe the final flow
        return state, flow

    @staticmethod
    def _filter(flow: Flow, conds) -> Flow:
        if not conds:
            return flow
        env = flow.env()
        mask = None
        for c in conds:
            m = c(env)
            mask = m if mask is None else (mask & m)
        is_timer = flow.batch.kind == KIND_TIMER  # timers bypass filters
        valid = flow.batch.valid & (is_timer | mask)
        batch = EventBatch(flow.batch.ts, flow.batch.kind, valid, flow.batch.cols)
        import dataclasses

        return dataclasses.replace(flow, batch=batch)


class _AuxWarnPool:
    """Deferred aux-flag checks with NO background thread.

    The hot dispatch path never blocks on device scalars (and does no device
    work at all — even eager coalesce ops cost seconds through a degraded
    relay): submitted flags accumulate in a bounded host-side backlog, and
    the one blocking device->host read happens only (a) in `flush()` and
    (b) at most once per `drain_every_s` from a main-thread submit.
    Transfers are pinned to the main thread on purpose: on some tunneled PJRT backends a
    device->host read issued from a helper thread permanently degrades every
    subsequent dispatch in the process (measured ~2.5 ms/call), so a daemon
    drain thread would un-do the engine's own fast path.

    Backlog entries hold weakrefs to the query runtime, so a shut-down app is
    collectable even if nobody flushes."""

    COALESCE_AT = 32

    def __init__(self):
        import os
        import time as _time
        import weakref

        self._weakref = weakref
        self._lock = threading.Lock()
        # id(qr) -> [qr_weakref, {flag_kind: [device bools]}]
        self._pending: dict = {}
        self._last_drain = _time.monotonic()
        # periodic-drain cadence; 0 or negative disables automatic drains
        # (flush()/shutdown still drain) — benches that must keep the relay
        # in its fast mode set SIDDHI_TPU_AUX_DRAIN_S=0
        try:
            self.drain_every_s = float(
                os.environ.get("SIDDHI_TPU_AUX_DRAIN_S", "5.0")
            )
        except ValueError:
            self.drain_every_s = 5.0

    def _may_autodrain(self) -> bool:
        if self.drain_every_s <= 0:
            return False
        if threading.current_thread() is threading.main_thread():
            return True
        # helper threads may drain only on backends where a non-main-thread
        # transfer does not degrade dispatch (see class docstring)
        from siddhi_tpu.utils.backend import transfer_degrades_dispatch

        return not transfer_degrades_dispatch()

    def submit(self, qr, flags: dict) -> None:
        with self._lock:
            ent = self._pending.get(id(qr))
            # id() values are reused after GC: a stale dead entry at this
            # address must not swallow a live runtime's flags
            if ent is not None and ent[0]() is not qr:
                ent = None
            if ent is None:
                ent = [self._weakref.ref(qr), {k: [] for k in flags}]
                self._pending[id(qr)] = ent
            acc = ent[1]
            for k, v in flags.items():
                vs = acc.setdefault(k, [])
                vs.append(v)
                # bound the backlog with NO device work (eager coalesce ops
                # through a degraded relay cost seconds): keep the first
                # COALESCE_AT flags (overflows usually start early) plus a
                # ring of the most recent ones
                if len(vs) > 2 * self.COALESCE_AT:
                    del vs[self.COALESCE_AT]
        import time as _time

        if (
            _time.monotonic() - self._last_drain > self.drain_every_s
            and self._may_autodrain()
        ):
            self.flush()

    def flush(self) -> None:
        """Drain everything with ONE blocking device read for the whole
        backlog (all runtimes, all flag kinds stacked into one vector).
        Call from the main thread on transfer-sensitive backends."""
        import time as _time

        import numpy as np

        with self._lock:
            pending, self._pending = self._pending, {}
            self._last_drain = _time.monotonic()
        plan = []  # (qr, [keys]) aligned with scalars
        scalars = []
        for _qid, (qr_ref, acc) in pending.items():
            qr = qr_ref()
            if qr is None:
                continue  # app GC'd un-flushed: drop its backlog
            keys = sorted(acc)
            try:
                qr_scalars = [
                    jnp.stack(
                        [jnp.asarray(v).astype(bool) for v in acc[k]]
                    ).any()
                    for k in keys
                ]
            except Exception:
                import logging

                logging.getLogger(__name__).debug(
                    "aux flag coalesce failed", exc_info=True
                )
                continue  # drop this runtime whole: keeps plan/scalars aligned
            scalars.extend(qr_scalars)
            plan.append((qr, keys))
        if not scalars:
            return
        try:
            vals = np.asarray(jnp.stack(scalars))  # the cycle's single block
        except Exception:
            import logging

            logging.getLogger(__name__).debug("aux flag drain failed", exc_info=True)
            return
        i = 0
        for qr, keys in plan:
            try:
                qr._check_aux_flags(
                    {k: bool(vals[i + j]) for j, k in enumerate(keys)}
                )
            except Exception:  # never let a warning path kill the app
                import logging

                logging.getLogger(__name__).debug(
                    "aux flag check failed", exc_info=True
                )
            i += len(keys)


_AUX_WORKER = _AuxWarnPool()


class BaseQueryRuntime:
    """Shared host-side half of a compiled query: output schema inference,
    callback/junction routing, state container (reference: QueryRuntime.java:45
    + OutputParser callback construction)."""

    @property
    def used_attrs(self):
        """Input attribute names this query can ever read (from the compile
        scope's resolved keys), or None when unknown/everything (select *).
        Fused ingest drops un-read columns from the wire."""
        scope = getattr(self, "_scope", None)
        if scope is None or getattr(self.query.selector, "select_all", False):
            return None
        return {k[2] for k in scope.used_keys}

    def _setup_output(self, query: "Query", query_id: str) -> None:
        out = query.output_stream
        if isinstance(out, InsertIntoStream):
            target = out.target
        else:
            target = f"__ret_{query_id}"
        self.out_schema = StreamSchema(target, self.selector.out_attrs)
        self.output_events = out.output_events
        # ungrouped batch-mode collapse needs the kind filter at selector level
        # (reference: QuerySelector currentOn/expiredOn gate lastEvent)
        self.selector.output_events_for_batch = out.output_events
        self.query_callbacks: list[Callable] = []
        self.publish_fn: Optional[Callable] = None
        self._receive_lock = threading.RLock()
        # armed by a fused group engine for cross-query shared-window members
        # (core/ingest.py): called before every donated-state per-batch step
        # to split chain buffers a fused dispatch aliased across queries
        self._unshare_guard: Optional[Callable] = None
        # armed by parallel/keyshard.py (@app:shard axis='keys'): the
        # KeyShardedGroupExec that replaced self._step and owns the [D]
        # state layout, occupancy gauges and the snapshot canonical form
        self._keyshard = None
        # device-budget trackers (wired by the app runtime when statistics
        # are on): jitted-step dispatch time and host-blocking decode stalls
        self.device_step_tracker = None
        self.sync_stall_tracker = None
        # continuous profiler (observability/profiler.py): compile ledger
        # for the jitted step + waterfall sub-stage attribution; both None
        # (one check) when statistics are off
        self.compile_telemetry = None
        self.profiler = None
        # lineage recorder (observability/lineage.py QueryLineage), armed by
        # arm_lineage() when @app:lineage is on; None = one attribute check
        # per receive (same contract as the trackers above)
        self.lineage = None
        self.state = None
        self.tables = {}
        self.table_op = None
        self._warned_overflow = False
        self._warned_join_overflow = False
        self._warned_table_overflow = False

        from siddhi_tpu.core.ratelimit import (
            EventAllLimiter,
            TimeAllLimiter,
            build_rate_limiter,
        )

        grouped = bool(query.selector.group_by)
        self.rate_limiter = build_rate_limiter(query.output_rate, grouped)
        if (
            self.rate_limiter is not None
            and grouped
            and not isinstance(self.rate_limiter, (EventAllLimiter, TimeAllLimiter))
        ):
            # per-group limiters need the group key beside each output row
            self.selector.emit_group_key = True

    def _attach_tables(self, tables: dict, interner) -> None:
        """Compile this query's table-output op and attach ONLY the tables the
        query actually reads (in-conditions, join sides) or writes (output
        target) — table-free queries skip table-state plumbing entirely
        (reference: OutputParser constructing Insert/Update/Delete/
        UpdateOrInsertIntoTableCallback, query/output/callback/*)."""
        from siddhi_tpu.core.table import collect_used_tables, compile_table_output

        self._interner = interner
        tables = dict(tables or {})
        self.table_op = compile_table_output(
            self.query.output_stream, self.out_schema, tables, interner
        )
        if self.table_op is not None and self.rate_limiter is not None:
            raise SiddhiAppCreationError(
                "output rate limiting into a table is not supported yet"
            )
        used = collect_used_tables(self.query, tables)
        self.tables = {tid: tables[tid] for tid in sorted(used)}
        target = getattr(self.query.output_stream, "target", None)
        self._mutates_table = target if self.table_op is not None else None

    def _collect_table_states(self) -> dict:
        st = {tid: t.state for tid, t in self.tables.items()}
        # join sides backed by other findables (named windows) are read-only
        for fid, f in getattr(self, "join_findables", {}).items():
            st[fid] = f.state
        return st

    def _writeback_table_states(self, tstates: dict) -> None:
        mutated = getattr(self, "_mutates_table", None)
        for tid, t in self.tables.items():
            t.state = tstates[tid]
            if tid == mutated:
                t.notify_change()  # record-store write-through

    def init_state(self):
        raise NotImplementedError

    def describe_state(self) -> dict:
        """Introspection snapshot (pull-only; see observability/introspect).
        Subclasses add their stateful internals (window fill, NFA instance
        counts, join-side buffers)."""
        d = {
            "kind": type(self).__name__,
            "callbacks": len(self.query_callbacks),
            "rate_limited": self.rate_limiter is not None,
            "tables": sorted(self.tables),
        }
        # cross-query state sharing (core/fusion_exec.py): this query's
        # window ring is one refcounted buffer serving every query in the set
        shared = getattr(self, "shared_ring", None)
        if shared is not None:
            d["shared_ring"] = dict(shared)
        lin = getattr(self, "lineage", None)
        if lin is not None:
            d["lineage"] = lin.describe()
        return d

    def _published_kinds(self):
        """Event kinds this query's insert-into actually publishes (the
        insert transform re-kinds them all CURRENT on the target) — maps a
        downstream junction's lineage seq back to this query's records."""
        from siddhi_tpu.core.event import KIND_CURRENT, KIND_EXPIRED
        from siddhi_tpu.query_api.execution import OutputEventsFor

        if self.output_events is OutputEventsFor.CURRENT:
            return frozenset((KIND_CURRENT,))
        if self.output_events is OutputEventsFor.EXPIRED:
            return frozenset((KIND_EXPIRED,))
        return frozenset((KIND_CURRENT, KIND_EXPIRED))

    def _lin_observe(self, lin, aux: dict, now: int, tag=None) -> dict:
        """Pull the step's `__lin.*` lanes to host, feed the recorder, and
        return aux with the lanes stripped (callers downstream only ever
        see the ordinary flag keys). Runs under the receive lock so
        observation order matches dispatch order."""
        import numpy as np

        lanes = {}
        rest = {}
        for k, v in aux.items():
            if k.startswith("__lin"):
                lanes[k] = np.asarray(v)
            else:
                rest[k] = v
        if lanes:
            try:
                lin.observe(lanes, now, tag)
            except Exception:  # provenance must never break dispatch
                import logging

                logging.getLogger(__name__).debug(
                    "lineage observe failed for query '%s'",
                    self.query_id, exc_info=True,
                )
        return rest

    @staticmethod
    def _fresh(state):
        """Deep-copy an initial state pytree: jnp constant caching can alias
        identical zero leaves, which breaks buffer donation (the same buffer
        must not be donated twice in one call)."""
        import jax.numpy as _jnp

        return jax.tree_util.tree_map(lambda x: _jnp.array(x, copy=True), state)

    def _warn_aux(self, aux: dict) -> None:
        """Surface overflow flags WITHOUT stalling the dispatch pipeline:
        flags accumulate (and periodically coalesce on-device) in the
        process-wide `_AuxWarnPool`; the one blocking device read happens in
        its periodic main-thread drain or in `flush_aux_warnings`. No helper
        thread is involved — on some tunneled PJRT backends any device->host
        read from a non-main thread permanently degrades every subsequent
        dispatch in the process."""
        flags = {
            k: v
            for k, v in aux.items()
            if k != "next_timer" and not k.startswith("__lin")
        }
        if flags:
            _AUX_WORKER.submit(self, flags)

    def flush_aux_warnings(self) -> None:
        _AUX_WORKER.flush()

    def _check_aux_flags(self, aux: dict) -> None:
        if (
            not self._warned_overflow
            and "groupby_overflow" in aux
            and bool(aux["groupby_overflow"])
        ):
            self._warned_overflow = True
            import logging

            logging.getLogger(__name__).error(
                "query '%s': group-by slot table overflowed (capacity %d); "
                "overflowed keys lose their cross-batch carry — raise it "
                "with @app:groupCapacity(size='N')",
                self.query_id,
                self.selector.group.capacity if self.selector.group else -1,
            )
        if (
            not getattr(self, "_warned_pattern_overflow", False)
            and "pattern_overflow" in aux
            and bool(aux["pattern_overflow"])
        ):
            self._warned_pattern_overflow = True
            import logging

            logging.getLogger(__name__).warning(
                "query '%s': pattern token table or emission buffer "
                "overflowed; partial matches or emissions were dropped — "
                "raise @app:patternCapacity(size='N') (sizes both)",
                self.query_id,
            )
        if (
            not getattr(self, "_warned_partition_overflow", False)
            and "partition_overflow" in aux
            and bool(aux["partition_overflow"])
        ):
            self._warned_partition_overflow = True
            import logging

            logging.getLogger(__name__).error(
                "query '%s': partition key table overflowed; events of "
                "overflowed keys were dropped — raise it with "
                "@app:partitionCapacity(size='N')",
                self.query_id,
            )
        if (
            not getattr(self, "_warned_window_overflow", False)
            and "window_overflow" in aux
            and bool(aux["window_overflow"])
        ):
            self._warned_window_overflow = True
            import logging

            logging.getLogger(__name__).warning(
                "query '%s': window emission/key buffer overflowed; events "
                "were dropped — reduce batch size or raise window capacity",
                self.query_id,
            )
        if (
            not self._warned_table_overflow
            and "table_overflow" in aux
            and bool(aux["table_overflow"])
        ):
            self._warned_table_overflow = True
            import logging

            logging.getLogger(__name__).error(
                "query '%s': table ran out of capacity; inserts were dropped — "
                "raise it with @capacity(size='N') on the table definition",
                self.query_id,
            )
        if (
            not self._warned_join_overflow
            and "join_overflow" in aux
            and bool(aux["join_overflow"])
        ):
            self._warned_join_overflow = True
            import logging

            logging.getLogger(__name__).warning(
                "query '%s': join output overflowed its capacity; matches were "
                "dropped — raise it with @app:joinCapacity(size='N')",
                self.query_id,
            )
        if (
            not getattr(self, "_warned_pk_duplicate", False)
            and "table_pk_duplicate_dropped" in aux
            and bool(aux["table_pk_duplicate_dropped"])
        ):
            self._warned_pk_duplicate = True
            import logging

            logging.getLogger(__name__).error(
                "query '%s': dropping inserted event(s) — an event with the "
                "same primary key is already stored (use `update or insert "
                "into` to overwrite)",
                self.query_id,
            )
        if (
            not getattr(self, "_warned_pk_conflict", False)
            and "table_pk_conflict" in aux
            and bool(aux["table_pk_conflict"])
        ):
            self._warned_pk_conflict = True
            import logging

            logging.getLogger(__name__).error(
                "query '%s': update failed — rekeying matched rows would "
                "collide with an existing primary key; the update event was "
                "skipped",
                self.query_id,
            )

    def _need_step_clock(self) -> bool:
        """One check deciding whether a receive path should time its jitted
        step (device-budget tracker or compile telemetry wired)."""
        return (
            self.device_step_tracker is not None
            or self.compile_telemetry is not None
        )

    def _observe_step(self, prog, signature, wall_ns: int) -> None:
        """Shared step-call accounting for every receive path (single/
        pattern/join): device-time histogram, waterfall 'device' sub-stage
        (thread-local, set by send_columns' per-batch chunk), and compile
        telemetry for `prog` under `query.<id>[signature]`-scoped ledgers.

        `signature` must identify the PROGRAM as well as the call shape
        when the runtime jits several (pattern per-stream steps, join
        sides): telemetry tracks one jit cache per component, so the
        component key embeds everything up to the batch capacity."""
        dt = self.device_step_tracker
        if dt is not None:
            dt.record_ns(wall_ns)
            prof = self.profiler
            if prof is not None:
                prof.tls_stage("device", wall_ns)
        ct = self.compile_telemetry
        if ct is not None:
            prog_key, shape = signature
            comp = f"query.{self.query_id}"
            if prog_key:
                comp += f"[{prog_key}]"
            ct.observe(comp, prog, shape, wall_ns)

    def _timed_decode(self, decode, schema, out):
        """Host decode with the d2h truth-sync stall recorded: decoding a
        device batch is the blocking read that forces real completion of the
        dependent chain (the live version of bench.py's truth sync)."""
        st = self.sync_stall_tracker
        if st is None:
            return decode(schema, out)
        import time as _time

        t0 = _time.perf_counter_ns()
        try:
            return decode(schema, out)
        finally:
            dns = _time.perf_counter_ns() - t0
            st.record_ns(dns)
            prof = self.profiler
            if prof is not None:
                # waterfall: the blocking decode is the 'readback' sub-stage
                # of send_columns' active per-batch chunk (if any)
                prof.tls_stage("readback", dns)

    def route_output(self, out: EventBatch, now: int, decode) -> None:
        """Dispatch a step's output to query callbacks / downstream junction.

        `decode` = app-runtime host decoder (batch -> event triples).
        """
        if self.rate_limiter is not None:
            rows = self._timed_decode(decode, self.out_schema, out)
            keys = None
            if "__group_key__" in out.cols:
                import numpy as np

                idx = np.nonzero(np.asarray(out.valid))[0]
                keys = np.asarray(out.cols["__group_key__"])[idx]
            rows4 = [
                (ts, kind, data, int(keys[i]) if keys is not None else None)
                for i, (ts, kind, data) in enumerate(rows)
            ]
            # only the kinds this query OUTPUTS enter the limiter — an
            # un-requested EXPIRED row must not consume a chunk slot or
            # shadow a group's held row (reference: the selector's
            # currentOn/expiredOn gate sits before OutputRateLimiter)
            want = self.output_events
            if want is OutputEventsFor.CURRENT:
                rows4 = [r for r in rows4 if r[1] == KIND_CURRENT]
            elif want is OutputEventsFor.EXPIRED:
                rows4 = [r for r in rows4 if r[1] == KIND_EXPIRED]
            else:
                rows4 = [
                    r for r in rows4 if r[1] in (KIND_CURRENT, KIND_EXPIRED)
                ]
            released = self.rate_limiter.process(rows4, now)
            self._deliver(released, now)
            return
        if self.query_callbacks:
            events = self._timed_decode(decode, self.out_schema, out)
            if events:
                ins = [e for e in events if e[1] == KIND_CURRENT]
                removed = [e for e in events if e[1] == KIND_EXPIRED]
                want = self.output_events
                if want is OutputEventsFor.CURRENT:
                    removed = []
                elif want is OutputEventsFor.EXPIRED:
                    ins = []
                if ins or removed:
                    ts = events[-1][0]
                    for cb in self.query_callbacks:
                        cb(ts, ins or None, removed or None)
        if self.publish_fn is not None:
            self.publish_fn(out, now)

    def _deliver(self, rows4: list, now: int) -> None:
        """Route rate-limiter-released rows to callbacks and the downstream
        junction (re-encoded into a device batch)."""
        if not rows4:
            return
        if self.query_callbacks:
            ins = [(ts, kind, data) for ts, kind, data, _k in rows4 if kind == KIND_CURRENT]
            removed = [(ts, kind, data) for ts, kind, data, _k in rows4 if kind == KIND_EXPIRED]
            want = self.output_events
            if want is OutputEventsFor.CURRENT:
                removed = []
            elif want is OutputEventsFor.EXPIRED:
                ins = []
            if ins or removed:
                ts = rows4[-1][0]
                for cb in self.query_callbacks:
                    cb(ts, ins or None, removed or None)
        if self.publish_fn is not None:
            # pad to a fixed capacity so downstream jitted steps keep one
            # stable shape (variable sizes would each trigger a recompile)
            cap = 64
            for ofs in range(0, len(rows4), cap):
                chunk = rows4[ofs : ofs + cap]
                batch = self.out_schema.to_batch(
                    [r[0] for r in chunk],
                    [r[2] for r in chunk],
                    self._interner,
                    capacity=cap,
                    kinds=[r[1] for r in chunk],
                )
                self.publish_fn(batch, now)


class QueryRuntime(BaseQueryRuntime):
    """Compiled query + device state + host output routing."""

    def __init__(
        self,
        query: Query,
        query_id: str,
        in_schema: StreamSchema,
        interner: InternTable,
        window_factory: Optional[Callable] = None,
        group_capacity: Optional[int] = None,
        tables: Optional[dict] = None,
    ):
        self.query = query
        self.query_id = query_id
        self.in_schema = in_schema
        stream = query.input_stream
        assert isinstance(stream, SingleInputStream)
        self.ref = stream.alias or stream.stream_id

        scope = Scope(interner)
        scope.add_stream(self.ref, in_schema.attr_types)
        if self.ref != in_schema.stream_id:
            scope.add_stream(in_schema.stream_id, in_schema.attr_types)
        scope.default_ref = self.ref
        for t in (tables or {}).values():
            scope.add_table(t)

        if window_factory is None:
            from siddhi_tpu.core.windows import make_window

            def window_factory(spec, schema, ref, _scope=scope):
                return make_window(spec, schema, ref, _scope)

        self.chain = CompiledSingleChain(stream, in_schema, scope, window_factory)
        self._scope = scope
        self.selector = CompiledSelector(
            query.selector,
            scope,
            self.chain.out_attrs,  # includes stream-function appended attrs
            batch_mode=self.chain.window is not None and self.chain.window.is_batch,
            group_capacity=group_capacity,
        )

        self._setup_output(query, query_id)
        self._attach_tables(tables, interner)
        # batch windows skip their EXPIRED candidate lanes when nothing can
        # observe them: `insert [current] into` output, no rate limiter, and
        # no membership-consuming aggregator (min/max/distinctCount). Halves
        # the flow length every selector op runs over.
        win = self.chain.window
        if win is not None and win.is_batch and hasattr(win, "emit_expired"):
            from siddhi_tpu.core.aggregators import (
                DistinctCountAggregator,
                ExtremeAggregator,
            )
            from siddhi_tpu.query_api.execution import OutputEventsFor

            needs_member = any(
                isinstance(a, DistinctCountAggregator)
                or (isinstance(a, ExtremeAggregator) and not a.forever)
                for a in self.selector.aggregators
            )
            if (
                self.output_events is OutputEventsFor.CURRENT
                and self.rate_limiter is None
                and not needs_member
            ):
                win.emit_expired = False
        self.needs_scheduler = (
            self.chain.window is not None and self.chain.window.needs_scheduler
        )
        # cron-driven windows compute their next fire host-side
        cron = getattr(self.chain.window, "cron_schedule", None)
        self.host_next_timer = cron.next_fire_ms if cron is not None else None
        # the state pytree is exclusively this query's: donate it so XLA
        # reuses the buffers in place instead of allocating fresh ones
        self._step = jax.jit(self._step_impl, donate_argnums=(0,))

    # ---- device program --------------------------------------------------

    @property
    def stateless_chain(self) -> bool:
        """True when this query carries NO cross-batch state — no window, no
        aggregator, no group-by slot table, no table reads/writes — and no
        host-side ordering state (rate limiter): its output for a micro-batch
        depends only on that micro-batch. The batch shard router
        (parallel/shard.py) relies on this to route micro-batches of one
        send to different devices and merge the outputs back in batch order
        with byte-identical results."""
        sel = self.selector
        return (
            self.chain.window is None
            and not sel.aggregators
            and sel.group is None
            and self.rate_limiter is None
            and self.table_op is None
            and not self.tables
            and not getattr(self, "join_findables", None)
        )

    def init_state(self):
        return {"chain": self.chain.init_state(), "sel": self.selector.init_state()}

    def describe_state(self) -> dict:
        d = super().describe_state()
        if self._keyshard is not None:
            d["keyshard"] = self._keyshard.describe_state()
        win = self.chain.window
        if win is not None:
            # under the receive lock: the step donates the old state buffers,
            # so an unlocked read could touch already-deleted device arrays
            with self._receive_lock:
                d["window"] = (
                    win.describe_state(self.state["chain"])
                    if self.state is not None
                    else {"type": type(win).__name__, "fill": 0}
                )
        return d

    def arm_lineage(self, cfg) -> None:
        """Enable provenance recording for this query (@app:lineage): the
        chain probe + `__lin.*` step lanes feed a SingleQueryLineage.
        Must run before the first dispatch traces the step (lane structure
        is part of the traced program). Emissions are untouched — lineage
        on/off is byte-parity-safe."""
        from siddhi_tpu.observability.lineage import LIN, SingleQueryLineage

        sel = self.selector
        grouped = sel.group is not None
        if grouped:
            # out rows carry their group key beside them (the rate-limiter
            # mechanism); the key col is NOT part of the out schema, so
            # downstream decode/publish/deliver are unaffected
            sel.emit_group_key = True
        win = self.chain.window
        time_attr = getattr(win, "time_attr", None)

        def probe(flow, _sel=sel, _grouped=grouped, _ta=time_attr):
            b = flow.batch
            flow.aux[LIN + "admit"] = b.valid & (b.kind == KIND_CURRENT)
            if _grouped:
                flow.aux[LIN + "key"] = _sel.group.key_of(flow.env())
            if _ta is not None:
                flow.aux[LIN + "wts"] = b.cols[_ta].astype(jnp.int64)

        self.chain.lineage_probe = probe
        self.lineage = SingleQueryLineage(
            cfg, self.query_id, self._published_kinds(),
            input_stream=self.in_schema.stream_id,
            window=win,
            grouped=grouped,
            aggregated=bool(sel.aggregators),
            order_limited=bool(
                sel.order_by or sel.limit is not None
                or sel.offset is not None
            ),
        )

    def _step_impl(self, state, tstates, batch: EventBatch, now):
        flow = Flow(batch=batch, ref=self.ref, now=now, tables=tstates)
        chain_state, flow = self.chain.apply(state["chain"], flow)
        sel_state, out = self.selector.apply(state["sel"], flow)
        if self.table_op is not None:
            tstates = self.table_op(tstates, out, now, flow.aux)
        if self.lineage is not None:
            # provenance lanes (observability/lineage.py): extra program
            # OUTPUTS only — the emission lanes above are untouched
            from siddhi_tpu.observability.lineage import LIN

            aux = flow.aux
            aux[LIN + "in"] = batch.valid & (batch.kind == KIND_CURRENT)
            aux[LIN + "in_ts"] = batch.ts
            aux[LIN + "w_valid"] = flow.batch.valid
            aux[LIN + "w_kind"] = flow.batch.kind
            aux[LIN + "w_ts"] = flow.batch.ts
            aux[LIN + "out_valid"] = out.valid
            aux[LIN + "out_kind"] = out.kind
            if "__group_key__" in out.cols:
                aux[LIN + "gkey"] = out.cols["__group_key__"]
        return {"chain": chain_state, "sel": sel_state}, tstates, out, flow.aux

    # ---- host side -------------------------------------------------------

    def receive(self, batch: EventBatch, now: int) -> tuple[EventBatch, dict]:
        # shared-window member (core/ingest.py share sets): split any chain
        # buffers a fused dispatch aliased across queries BEFORE this step
        # donates them. Callers hold the app process lock (the lock the
        # fused writeback runs under), so the split cannot race an in-flight
        # fused send. None — one attribute check — for every other query.
        if self._unshare_guard is not None:
            self._unshare_guard()
        with self._receive_lock:
            if self.state is None:
                ks = self._keyshard
                self.state = self._fresh(
                    ks.init_state() if ks is not None else self.init_state()
                )
            tstates = self._collect_table_states()
            timed = self._need_step_clock()
            if timed:
                import time as _time

                t0 = _time.perf_counter_ns()
            self.state, tstates, out, aux = self._step(
                self.state, tstates, batch, jnp.asarray(now, dtype=jnp.int64)
            )
            if timed:
                # compile telemetry: the jit retraces per batch capacity
                # (timer batches, downstream cap-64 re-publishes); a
                # recompile at a seen capacity means the carried state
                # pytree drifted (donation_mismatch)
                self._observe_step(
                    self._step, ("", int(batch.ts.shape[0])),
                    _time.perf_counter_ns() - t0,
                )
            self._writeback_table_states(tstates)
            lin = self.lineage
            if lin is not None:
                # observe under the receive lock: recorder order must
                # match dispatch order (the lanes are stripped from aux)
                aux = self._lin_observe(lin, aux, now)
        self._warn_aux(aux)
        return out, aux
