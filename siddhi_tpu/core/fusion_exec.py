"""Fusion executor planning: turn the static FusionPlan into per-junction
fused-group configurations at runtime creation.

PR 7 shipped the static decision layer (`analysis/fusion.py` — a versioned
FusionPlan with per-stream fusable groups, SA124 hazards, and shared-state
candidates). This module is the runtime half of the contract: at `start()`
the app runtime calls `junction_fusion_configs(runtime)` and, for every
junction the plan formed a group on, builds ONE `FusedJunctionIngest` over
exactly the group's endpoints:

* **group members** run inside one XLA chunk program (one donated-state
  dispatch per K-batch chunk instead of `n * K` per-batch dispatches);
* **blocked queries** (the plan's SA124 hazards: rate limiters, schedulers,
  partitions, observed insert targets, ...) stay on the unfused per-batch
  path — the group engine re-dispatches every micro-batch to them after the
  fused chunk commits (`FusedJunctionIngest._residual_dispatch`), so their
  outputs are byte-identical to a fully per-batch run;
* **shared-state candidates** whose queries all landed in the same group
  and whose runtime chains are provably compatible (`_chain_share_key`)
  reference ONE window ring: the chunk program carries the canonical chain
  state once and every member reads it (core/ingest.py share sets).

Safety guards applied here, beyond the plan's own hazards:

* `_insert_reach`: a residual (blocked) query whose output can reach the
  fused stream — directly or through a chain of insert-into queries — would
  feed events back into the group AFTER the whole chunk instead of
  interleaved per batch, changing the group's window contents. Such
  junctions fall back to the legacy all-or-nothing fused path.
* subscriber-name accounting: the group engages only when every junction
  subscriber is either a group endpoint or a mapped residual consumer
  (query / aggregation); anything unrecognized vetoes the partial config.

Escape hatch: `@app:fuse(disable='true')` on the app, overridden
process-wide by SIDDHI_TPU_FUSE=1 (force on) / SIDDHI_TPU_FUSE=0 (force
off — no fused ingest engines are built at all, every junction runs the
per-batch path). The annotation is validated here (the runtime analog of
the analyzer's SA125, same rule set).
"""

from __future__ import annotations

import os
from typing import Optional

FUSE_ENV = "SIDDHI_TPU_FUSE"

_TRUE = ("1", "on", "true", "force")
_FALSE = ("0", "off", "false")


def fuse_env_override() -> Optional[bool]:
    """Process-wide fusion toggle: True (forced on), False (forced off), or
    None (defer to the app's @app:fuse annotation)."""
    v = os.environ.get(FUSE_ENV, "").strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    return None


def iter_fuse_annotation_problems(ann):
    """Yield one message per malformed `@app:fuse` element — THE validation
    rules, shared by the runtime resolver (raises on the first) and the
    analyzer's SA125 diagnostics (reports them all), so the two can never
    drift."""
    for k, v in ann.elements:
        if k == "disable":
            if str(v).strip().lower() not in ("true", "false"):
                yield f"@app:fuse disable '{v}' must be true or false"
        else:
            yield (
                f"unknown @app:fuse option '{k if k is not None else v}' "
                "(expected disable)"
            )


def resolve_fuse_annotation(ann) -> bool:
    """Whether whole-graph fusion is enabled for one app, from its
    `@app:fuse` annotation (or None) plus the SIDDHI_TPU_FUSE env override.
    Raises SiddhiAppCreationError on malformed options — the runtime analog
    of the analyzer's SA125 diagnostic."""
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    enabled = True
    if ann is not None:
        for problem in iter_fuse_annotation_problems(ann):
            raise SiddhiAppCreationError(problem)
        enabled = (
            str(ann.element("disable", "false")).strip().lower() != "true"
        )
    env = fuse_env_override()
    if env is not None:
        enabled = env
    return enabled


# ---------------------------------------------------------------------------
# plan -> junction configuration
# ---------------------------------------------------------------------------


def _insert_reach(app) -> dict:
    """stream id -> set of stream ids its events can reach through chains of
    insert-into queries (the stream itself excluded unless a cycle feeds it
    back). Used to veto partial fusion when a BLOCKED query's output can
    re-enter the fused stream: per-batch it interleaves, post-chunk it
    would not."""
    from siddhi_tpu.analysis.cost import iter_query_entries

    edges: dict[str, set] = {}
    for _qid, q, _in_part in iter_query_entries(app):
        target = getattr(q.output_stream, "target", None)
        if target is None:
            continue
        for sid in _consumed_stream_ids(q):
            edges.setdefault(sid, set()).add(target)

    reach: dict[str, set] = {}

    def closure(sid: str) -> set:
        got = reach.get(sid)
        if got is not None:
            return got
        reach[sid] = seen = set()
        frontier = [sid]
        while frontier:
            nxt = frontier.pop()
            for t in edges.get(nxt, ()):
                if t not in seen:
                    seen.add(t)
                    frontier.append(t)
        return seen

    for sid in list(edges):
        closure(sid)
    return reach


def _consumed_stream_ids(q) -> list:
    from siddhi_tpu.query_api.execution import (
        JoinInputStream,
        SingleInputStream,
        StateInputStream,
        iter_state_streams,
    )

    stream = q.input_stream
    if isinstance(stream, SingleInputStream):
        return [stream.stream_id]
    if isinstance(stream, JoinInputStream):
        return [stream.left.stream_id, stream.right.stream_id]
    if isinstance(stream, StateInputStream):
        return [s.stream_id for s in iter_state_streams(stream.state)]
    return []


def _chain_share_key(qr):
    """Runtime-level compatibility key for cross-query window-state sharing,
    or None when this runtime cannot share. Defense in depth over the plan's
    AST signature (which already proved the filter+window chains textually
    identical): only plain single-stream QueryRuntimes with a pure
    filter+window chain (no stream functions — their appended columns feed
    the ring) whose live window stage opted into sharing
    (`WindowStage.share_signature`, core/windows.py — plain ring/bucket
    shapes only, never timer-armed) hold provably byte-identical chain
    state."""
    from siddhi_tpu.core.query_runtime import QueryRuntime

    if type(qr) is not QueryRuntime:
        return None
    chain = getattr(qr, "chain", None)
    win = getattr(chain, "window", None)
    if win is None:
        return None
    if any(kind == "fn" for kind, _stage in chain.stages):
        return None
    return win.share_signature()


def junction_fusion_configs(runtime) -> dict:
    """stream id -> config dict for junctions where the FusionPlan formed a
    fusable group that can engage against the live wiring. Config keys:

    * ``endpoints`` — the group's FuseEndpoints (subscription order);
    * ``residual`` — [(subscriber_fn, name)] left on the per-batch path;
    * ``share_sets`` — lists of endpoint indices referencing one window ring;
    * ``component`` — telemetry component (``stream.<S>.fusedgroup.<g>``);
    * ``plan_group`` — the plan's group entry (predicted dispatch reduction).

    Junctions with no entry fall back to the legacy all-or-nothing fused
    path. Never raises: any mismatch between the static plan and the live
    wiring simply drops that junction's config."""
    from siddhi_tpu.analysis.cost import iter_query_entries
    from siddhi_tpu.analysis.fusion import build_fusion_plan

    plan = build_fusion_plan(runtime.app)
    if not plan.groups:
        return {}
    shared_by_stream: dict[str, list] = {}
    for s in plan.shared_state:
        shared_by_stream.setdefault(s["stream"], []).append(s)
    targets = {
        qid: getattr(q.output_stream, "target", None)
        for qid, q, _in_part in iter_query_entries(runtime.app)
    }
    reach = _insert_reach(runtime.app)

    configs: dict = {}
    for gi, g in enumerate(plan.groups):
        sid = g["stream"]
        j = runtime.junctions.get(sid)
        if j is None:
            continue
        cand_by_qid = {}
        for ep in j.fuse_candidates:
            qid = getattr(ep.qr, "query_id", None)
            if qid is not None and qid not in cand_by_qid:
                cand_by_qid[qid] = ep
        group_qids = [q for q in g["queries"] if q in cand_by_qid]
        if len(group_qids) < 2:
            continue
        covered_names = {f"query.{q}" for q in group_qids}
        # endpoints in subscription order (fuse_candidates are appended as
        # queries subscribe), residual = every other live subscriber
        endpoints = [
            ep for ep in j.fuse_candidates
            if getattr(ep.qr, "query_id", None) in set(group_qids)
        ]
        residual = []
        unsafe = False
        covered_subs = 0
        for fn, name in zip(j.subscribers, j.subscriber_names):
            if name in covered_names:
                covered_subs += 1
                continue
            if name.startswith("query."):
                qid = name[len("query."):]
                if qid not in targets:
                    unsafe = True  # unmapped query subscriber: veto
                    break
                t = targets[qid]
                if t is not None and (
                    t == sid or sid in reach.get(t, ())
                ):
                    # the blocked query's output can re-enter this stream:
                    # post-chunk residual dispatch would reorder the group's
                    # input relative to the per-batch interleave
                    unsafe = True
                    break
            elif not name.startswith("aggregation."):
                unsafe = True  # unknown consumer kind: veto, stay legacy
                break
            residual.append((fn, name))
        if unsafe or covered_subs != len(endpoints):
            continue

        qid_to_idx = {
            getattr(ep.qr, "query_id", None): i
            for i, ep in enumerate(endpoints)
        }
        share_sets = []
        for entry in shared_by_stream.get(sid, ()):  # plan candidates
            members = [q for q in entry["queries"] if q in qid_to_idx]
            if len(members) < 2:
                continue
            keys = {
                _chain_share_key(endpoints[qid_to_idx[q]].qr)
                for q in members
            }
            if len(keys) != 1 or None in keys:
                continue  # runtime chains not provably identical
            share_sets.append(sorted(qid_to_idx[q] for q in members))

        configs[sid] = {
            "endpoints": endpoints,
            "residual": residual,
            "share_sets": share_sets,
            "component": g.get(
                "component", f"stream.{sid}.fusedgroup.{gi}"
            ),
            "plan_group": g,
        }
    return configs
