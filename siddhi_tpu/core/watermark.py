"""Event-time robustness: watermarks, bounded-disorder reordering, and
late-event policies (`@app:watermark`).

    @app:watermark(bound='5 sec', idle.timeout='30 sec',
                   late.policy='drop|stream|apply', allowed.lateness='1 min')

The annotation installs three cooperating pieces:

* A bounded columnar REORDER STAGE at every stream's ingress
  (`_WatermarkInputHandler` in app_runtime.py -> `ReorderTracker` here).
  Arrivals buffer up to `bound` of event-time slack; whenever the
  watermark (max event time seen minus `bound`) advances, all buffered
  rows at or below it are released in one stably-sorted columnar send, so
  the fused / pipelined / sharded send paths downstream all see ordered
  input. Rows older than the watermark at arrival are LATE and never reach
  the junction; they are metered and handled by `late.policy`.

* A WATERMARK CLOCK. Each source stream tracks its own watermark; the
  app-level watermark is the minimum over non-idle sources (classic
  min-propagation; a source that has been quiet for `idle.timeout` is
  flushed and excluded so it cannot stall the app). The clock drives an
  EventTimeScheduler, so window flushes, pattern within/absent deadlines
  and aggregation bucket closes fire on WATERMARK ADVANCE, not raw
  arrival. Insert-into targets inherit min-over-inputs watermarks
  (`watermark_of`), reported in snapshot_status()/explain().

* LATE-EVENT POLICIES — late events are never silently lost:
    drop    count + lateness histogram, then discard (the meter is the
            contract: `late_total == dropped`).
    stream  divert to the auto-defined `!S` side stream (the @OnError
            STREAM machinery) with `_error='late[<ms> ms]'`.
    apply   best-effort: within `allowed.lateness`, re-open the closed
            aggregation bucket the event belongs to (update duration
            tables in place) and emit the late event on `!S` flagged
            `_error='applied[<ms> ms]'` as the correction signal; beyond
            the allowance it is metered `expired` and emitted flagged
            `_error='expired[<ms> ms]'`.

Validation is ONE rule set (`iter_watermark_annotation_problems`) shared by
the runtime resolver and the analyzer's SA134 diagnostic, the same contract
as SA125-SA133. `SIDDHI_TPU_WATERMARK` overrides the annotation
process-wide (same spec grammar as the annotation, `;`-joined `k=v`; `off`
or `0` force-disables) so the CI disorder-parity leg can arm the reorder
stage without editing apps. With no annotation and no env the runtime
never instantiates any of this — the only cost is one `is None` check at
input-handler creation (the lineage/flight/stats zero-cost contract).
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Callable, Optional

import numpy as np

WATERMARK_ENV = "SIDDHI_TPU_WATERMARK"

_POLICIES = ("drop", "stream", "apply")
_OPTIONS = ("bound", "idle.timeout", "late.policy", "allowed.lateness")
DEFAULT_IDLE_TIMEOUT_MS = 30_000
DEFAULT_ALLOWED_LATENESS_MS = 60_000  # when late.policy='apply' and unset


@dataclasses.dataclass(frozen=True)
class WatermarkConfig:
    bound_ms: int
    idle_timeout_ms: int = DEFAULT_IDLE_TIMEOUT_MS
    late_policy: str = "drop"
    allowed_lateness_ms: int = 0


def _parse_time_ms(v) -> int:
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    return SiddhiCompiler.parse_time_constant(str(v))


def _iter_option_problems(pairs):
    """Shared over annotation elements AND the env-override spec so the two
    surfaces can never drift."""
    seen = {}
    for k, v in pairs:
        seen[k] = v
        if k in ("bound", "idle.timeout", "allowed.lateness"):
            try:
                ms = _parse_time_ms(v)
                ok = ms > 0 if k == "bound" else ms >= 0
            except Exception:
                ok = False
            if not ok:
                yield (
                    f"@app:watermark {k} '{v}' must be a "
                    f"{'positive ' if k == 'bound' else ''}time constant "
                    "(e.g. '5 sec')"
                )
        elif k == "late.policy":
            if str(v) not in _POLICIES:
                yield (
                    f"@app:watermark late.policy '{v}' must be one of "
                    f"{'|'.join(_POLICIES)}"
                )
        else:
            yield (
                f"unknown @app:watermark option '{k}' "
                f"(expected {', '.join(_OPTIONS)})"
            )
    if "bound" not in seen:
        yield (
            "@app:watermark needs bound='<time>' — the reorder slack and "
            "watermark lag (e.g. bound='5 sec')"
        )
    if "allowed.lateness" in seen and str(seen.get("late.policy", "drop")) != "apply":
        yield (
            "@app:watermark allowed.lateness only takes effect with "
            "late.policy='apply'"
        )


def _ann_pairs(ann):
    pairs = []
    for k, v in ann.elements:
        if k is None and len(ann.elements) == 1:
            k = "bound"  # @app:watermark('5 sec') shorthand
        pairs.append((k, v))
    return pairs


def iter_watermark_annotation_problems(ann):
    """Yield one message per malformed `@app:watermark` element — THE rule
    set, shared by the runtime resolver (raises on the first) and the
    analyzer's SA134 diagnostics (reports them all), so the two can never
    drift (same contract as SA113/SA114/SA125-SA133)."""
    yield from _iter_option_problems(_ann_pairs(ann))


def parse_watermark_spec(spec: str):
    """Parse a SIDDHI_TPU_WATERMARK override: `;`-joined `k=v` pairs in the
    annotation's option vocabulary, or `off`/`0`/`none` to force-disable.
    Returns 'off', a {option: value} dict, or None for an empty spec.
    Raises ValueError on malformed entries — a parity run with a typo'd
    override must fail loudly, not run watermark-free."""
    s = (spec or "").strip()
    if not s:
        return None
    if s.lower() in ("0", "off", "none"):
        return "off"
    out = {}
    for part in s.split(";"):
        part = part.strip()
        if not part:
            continue
        k, sep, v = part.partition("=")
        if not sep:
            raise ValueError(f"watermark option '{part}' is not k=v")
        out[k.strip()] = v.strip()
    return out


def resolve_watermark_annotation(ann, env: Optional[str] = None):
    """WatermarkConfig from `@app:watermark(...)` plus the
    SIDDHI_TPU_WATERMARK env override (which wins per option; `off`
    disables even an annotated app; a bare env spec with a bound arms an
    unannotated one — the CI disorder-parity leg). None = watermark off.
    Raises SiddhiAppCreationError on malformed options — the runtime
    analog of the analyzer's SA134 diagnostic."""
    import os

    from siddhi_tpu.core.errors import SiddhiAppCreationError

    if env is None:
        env = os.environ.get(WATERMARK_ENV, "")
    try:
        override = parse_watermark_spec(env)
    except ValueError as e:
        raise SiddhiAppCreationError(str(e)) from e
    if override == "off":
        return None
    opts = dict(_ann_pairs(ann)) if ann is not None else {}
    if override:
        opts.update(override)
    if not opts:
        return None
    for problem in _iter_option_problems(list(opts.items())):
        raise SiddhiAppCreationError(problem)
    policy = str(opts.get("late.policy", "drop"))
    allowed = opts.get("allowed.lateness")
    return WatermarkConfig(
        bound_ms=_parse_time_ms(opts["bound"]),
        idle_timeout_ms=(
            _parse_time_ms(opts["idle.timeout"])
            if "idle.timeout" in opts else DEFAULT_IDLE_TIMEOUT_MS
        ),
        late_policy=policy,
        allowed_lateness_ms=(
            _parse_time_ms(allowed) if allowed is not None
            else (DEFAULT_ALLOWED_LATENESS_MS if policy == "apply" else 0)
        ),
    )


# ---------------------------------------------------------------------------
# lateness histogram (log2 buckets; summary shape matches LatencyTracker's)
# ---------------------------------------------------------------------------


class LatenessHistogram:
    """Fixed log2-bucketed histogram over lateness in ms. Quantiles are
    bucket upper bounds — coarse but allocation-free on the late path."""

    _NBUCKETS = 48

    def __init__(self) -> None:
        self._counts = [0] * self._NBUCKETS
        self._sum = 0
        self._count = 0
        self._max = 0
        self._lock = threading.Lock()

    def record(self, ms: int) -> None:
        ms = int(ms)
        idx = min(max(ms, 0).bit_length(), self._NBUCKETS - 1)
        with self._lock:
            self._counts[idx] += 1
            self._sum += ms
            self._count += 1
            if ms > self._max:
                self._max = ms

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s, mx = self._count, self._sum, self._max
        out = {"count": total, "sum": s, "max": mx}
        for key, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99),
                       ("p999", 0.999), ("p9999", 0.9999)):
            if total == 0:
                out[key] = 0
                continue
            target = q * total
            acc = 0
            val = 0
            for i, c in enumerate(counts):
                acc += c
                if acc >= target:
                    val = min((1 << i) - 1, mx)
                    break
            out[key] = val
        return out


# ---------------------------------------------------------------------------
# the bounded reorder stage
# ---------------------------------------------------------------------------


class ReorderTracker:
    """Per-source-stream watermark + bounded columnar reorder buffer.

    `offer()` takes one columnar chunk, splits off rows already behind the
    watermark (late — handed to `on_late`), advances the watermark to
    `max event time - bound`, and releases everything at or below it in a
    single stably-sorted columnar `deliver()` call. The stable sort makes
    the released sequence a pure function of the row multiset and the
    watermark trajectory — the disorder-parity gate's foundation."""

    def __init__(
        self,
        stream_id: str,
        bound_ms: int,
        deliver: Callable,          # (ts: np.int64[n], cols: {name: np[n]})
        on_late: Callable,          # (ts, cols, lateness: np.int64[n])
    ) -> None:
        self.stream = stream_id
        self.bound = int(bound_ms)
        self._deliver = deliver
        self._on_late = on_late
        self._lock = threading.RLock()
        self._chunks: list = []     # [(ts array, {name: col array})]
        self.max_ts: Optional[int] = None
        self.wm: Optional[int] = None
        self.buffered = 0
        self.peak_buffered = 0
        self.released = 0
        self.late_total = 0
        self.idle = False
        self.last_event_monotonic: Optional[float] = None

    def offer(self, timestamps, cols) -> None:
        ts = np.asarray(timestamps, dtype=np.int64)
        if ts.size == 0:
            return
        cols = {k: np.asarray(v) for k, v in cols.items()}
        with self._lock:
            self.idle = False
            self.last_event_monotonic = _time.monotonic()
            if self.wm is not None:
                late = ts < self.wm
                if late.any():
                    lateness = (self.wm - ts[late]).astype(np.int64)
                    self.late_total += int(late.sum())
                    self._on_late(
                        ts[late], {k: v[late] for k, v in cols.items()},
                        lateness,
                    )
                    keep = ~late
                    ts = ts[keep]
                    cols = {k: v[keep] for k, v in cols.items()}
                    if ts.size == 0:
                        return
            self._chunks.append((ts, cols))
            self.buffered += int(ts.size)
            if self.buffered > self.peak_buffered:
                self.peak_buffered = self.buffered
            m = int(ts.max())
            if self.max_ts is None or m > self.max_ts:
                self.max_ts = m
            new_wm = self.max_ts - self.bound
            if self.wm is None or new_wm > self.wm:
                self.wm = new_wm
            self._release_locked()

    def flush(self) -> None:
        """Idle timeout / drain: advance the watermark to the newest event
        seen and release the whole buffer; the tracker goes idle (excluded
        from the app-level min) until the next arrival."""
        with self._lock:
            if self.max_ts is not None and (
                self.wm is None or self.max_ts > self.wm
            ):
                self.wm = self.max_ts
            self._release_locked()
            self.idle = True

    def _release_locked(self) -> None:
        if not self._chunks or self.wm is None:
            return
        if len(self._chunks) == 1:
            ts, cols = self._chunks[0]
        else:
            ts = np.concatenate([c[0] for c in self._chunks])
            names = list(self._chunks[0][1])
            cols = {
                k: np.concatenate([c[1][k] for c in self._chunks])
                for k in names
            }
        order = np.argsort(ts, kind="stable")
        ts = ts[order]
        cols = {k: v[order] for k, v in cols.items()}
        n = int(np.searchsorted(ts, self.wm, side="right"))
        if n == 0:
            self._chunks = [(ts, cols)]  # keep pre-sorted
            return
        rel_ts = ts[:n]
        rel_cols = {k: v[:n] for k, v in cols.items()}
        if n < ts.size:
            self._chunks = [(ts[n:], {k: v[n:] for k, v in cols.items()})]
        else:
            self._chunks = []
        self.buffered -= n
        self.released += n
        self._deliver(rel_ts, rel_cols)

    def describe(self) -> dict:
        with self._lock:
            return {
                "watermark_ms": self.wm,
                "max_event_ms": self.max_ts,
                "lag_ms": (
                    self.max_ts - self.wm
                    if self.wm is not None and self.max_ts is not None
                    else None
                ),
                "buffered": self.buffered,
                "peak_buffered": self.peak_buffered,
                "released": self.released,
                "late_total": self.late_total,
                "idle": self.idle,
            }


# ---------------------------------------------------------------------------
# app-level runtime: min-propagation, idle heartbeat, late policies
# ---------------------------------------------------------------------------


def _query_input_ids(query) -> list:
    """Source stream ids of a query's input (single / join / state)."""
    from siddhi_tpu.query_api.execution import (
        JoinInputStream,
        SingleInputStream,
        StateInputStream,
        iter_state_streams,
    )

    s = query.input_stream
    if isinstance(s, SingleInputStream):
        return [s.stream_id]
    if isinstance(s, JoinInputStream):
        return [s.left.stream_id, s.right.stream_id]
    if isinstance(s, StateInputStream):
        return [a.stream_id for a in iter_state_streams(s.state)]
    return []


class WatermarkRuntime:
    """Owns the per-stream `ReorderTracker`s, the watermark clock, the idle
    heartbeat, and the late-event policies for one app runtime."""

    def __init__(self, runtime, cfg: WatermarkConfig, clock) -> None:
        self.runtime = runtime
        self.cfg = cfg
        self.clock = clock          # EventTimeClock driven to the app watermark
        self.trackers: dict = {}
        self.meters: dict = {}      # stream -> policy counters
        self.lateness: dict = {}    # stream -> LatenessHistogram
        self._lock = threading.Lock()
        self._edges = None          # insert-into topology (lazy)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingress wiring ------------------------------------------------------

    def tracker(self, stream_id: str, deliver: Callable) -> ReorderTracker:
        with self._lock:
            tr = self.trackers.get(stream_id)
            if tr is None:
                tr = ReorderTracker(
                    stream_id, self.cfg.bound_ms, deliver,
                    on_late=lambda ts, cols, lat, _s=stream_id: (
                        self._handle_late(_s, ts, cols, lat)
                    ),
                )
                self.trackers[stream_id] = tr
                self.meters[stream_id] = {
                    "dropped": 0, "streamed": 0, "applied": 0, "expired": 0,
                }
                self.lateness[stream_id] = LatenessHistogram()
            return tr

    def advance_clock(self) -> None:
        """Drive the app watermark clock to min over non-idle source
        watermarks (all idle -> max, so a quiet app catches up fully)."""
        active = [
            tr.wm for tr in self.trackers.values()
            if tr.wm is not None and not tr.idle
        ]
        if active:
            self.clock.advance(min(active))
            return
        all_wm = [tr.wm for tr in self.trackers.values() if tr.wm is not None]
        if all_wm:
            self.clock.advance(max(all_wm))

    # -- late policies -------------------------------------------------------

    def _handle_late(self, stream_id, ts, cols, lateness) -> None:
        hist = self.lateness[stream_id]
        for v in lateness:
            hist.record(int(v))
        meters = self.meters[stream_id]
        policy = self.cfg.late_policy
        if policy == "drop":
            meters["dropped"] += int(len(ts))
            return
        if policy == "stream":
            meters["streamed"] += int(len(ts))
            self._divert(stream_id, ts, cols, lateness, "late")
            return
        # apply: re-open closed aggregation buckets within allowed.lateness
        allowed = self.cfg.allowed_lateness_ms
        aggs = self.runtime._aggregations_for_stream(stream_id)
        for i in range(len(ts)):
            lat = int(lateness[i])
            one = (ts[i : i + 1], {k: v[i : i + 1] for k, v in cols.items()})
            if lat > allowed or not aggs:
                meters["expired"] += 1
                self._divert(stream_id, one[0], one[1], [lat], "expired")
                continue
            row = {k: v[i] for k, v in cols.items()}
            for agg in aggs:
                agg.apply_late(int(ts[i]), row)
            meters["applied"] += 1
            self._divert(stream_id, one[0], one[1], [lat], "applied")

    def _divert(self, stream_id, ts, cols, lateness, tag: str) -> None:
        """Publish late rows on the stream's auto-defined `!S` side stream
        flagged `_error='<tag>[<ms> ms]'` (the @OnError STREAM contract)."""
        fj = self.runtime._fault_junction_for(stream_id)
        if fj is None:  # pragma: no cover - schemas are pre-defined
            return
        names = [a for a in fj.schema.attr_names if a != "_error"]
        rows = []
        for i in range(len(ts)):
            vals = tuple(
                v.item() if hasattr(cols[k][i], "item") else cols[k][i]
                for k, v in ((k, cols[k]) for k in names)
            )
            rows.append(vals + (f"{tag}[{int(lateness[i])} ms]",))
        now = self.clock.now()
        fj.send_rows([int(t) for t in ts], rows, now=now)

    # -- idle heartbeat / drain ---------------------------------------------

    def start(self) -> None:
        idle_ms = self.cfg.idle_timeout_ms
        if not idle_ms or self._thread is not None:
            return
        self._stop.clear()
        period = max(idle_ms / 4000.0, 0.05)

        def run():
            while not self._stop.wait(period):
                flushed = False
                for tr in list(self.trackers.values()):
                    with tr._lock:
                        quiet = (
                            not tr.idle
                            and tr.last_event_monotonic is not None
                            and (_time.monotonic() - tr.last_event_monotonic)
                            * 1000.0 >= idle_ms
                        )
                    if quiet:
                        tr.flush()
                        flushed = True
                if flushed:
                    self.advance_clock()

        self._thread = threading.Thread(
            target=run, daemon=True, name="siddhi-watermark-idle",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    def drain(self) -> None:
        """Release every buffered row and catch the clock up to the newest
        event seen — shutdown's tail-delivery guarantee."""
        for tr in list(self.trackers.values()):
            tr.flush()
        self.advance_clock()

    # -- propagation + introspection ----------------------------------------

    def _topology(self) -> dict:
        if self._edges is None:
            edges: dict = {}
            for qr in self.runtime.queries.values():
                target = getattr(qr.query.output_stream, "target", None)
                if not target:
                    continue
                edges.setdefault(target, set()).update(
                    _query_input_ids(qr.query)
                )
            self._edges = edges
        return self._edges

    def watermark_of(self, stream_id: str, _seen=None) -> Optional[int]:
        """Stream watermark with min-propagation through insert-into
        chains: a source stream reports its tracker's watermark; a derived
        stream the min over its contributing inputs."""
        tr = self.trackers.get(stream_id)
        if tr is not None:
            return tr.wm
        if _seen is None:
            _seen = set()
        if stream_id in _seen:
            return None
        _seen.add(stream_id)
        inputs = self._topology().get(stream_id)
        if not inputs:
            return None
        vals = [
            v for v in (self.watermark_of(i, _seen) for i in sorted(inputs))
            if v is not None
        ]
        return min(vals) if vals else None

    def describe_state(self) -> dict:
        streams = {}
        for sid in sorted(self.trackers):
            d = self.trackers[sid].describe()
            d.update(self.meters[sid])
            d["lateness_ms"] = self.lateness[sid].snapshot()
            streams[sid] = d
        derived = {}
        for target in sorted(self._topology()):
            if target in self.trackers or target.startswith("!"):
                continue
            wm = self.watermark_of(target)
            if wm is not None:
                derived[target] = {"watermark_ms": wm}
        return {
            "config": {
                "bound_ms": self.cfg.bound_ms,
                "idle_timeout_ms": self.cfg.idle_timeout_ms,
                "late_policy": self.cfg.late_policy,
                "allowed_lateness_ms": self.cfg.allowed_lateness_ms,
            },
            "clock_ms": self.clock.now(),
            "streams": streams,
            "derived": derived,
        }
