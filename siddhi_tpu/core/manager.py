"""SiddhiManager — top-level entry point.

Reference: core/SiddhiManager.java:45-243 — create/validate/shutdown app runtimes,
registry of extensions, persistence stores, data sources. Here it also owns the
host-side intern table shared by all apps it creates.
"""

from __future__ import annotations

from typing import Union

from siddhi_tpu.core.types import InternTable
from siddhi_tpu.query_api.siddhi_app import SiddhiApp


class SiddhiManager:
    def __init__(self) -> None:
        self.interner = InternTable()
        self.persistence_store = None
        self._runtimes: dict[str, object] = {}

    # app: SiddhiQL source text or a programmatic SiddhiApp AST
    def create_siddhi_app_runtime(self, app: Union[str, SiddhiApp]):
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
        from siddhi_tpu.core.app_runtime import SiddhiAppRuntime

        if isinstance(app, str):
            app = SiddhiCompiler.parse(app)
        runtime = SiddhiAppRuntime(app, self)
        old = self._runtimes.get(runtime.name)
        if old is not None:
            old.shutdown()
        self._runtimes[runtime.name] = runtime
        return runtime

    def get_siddhi_app_runtime(self, name: str):
        return self._runtimes.get(name)

    def shutdown_siddhi_app_runtime(self, name: str) -> bool:
        """Shut down and deregister one app; False when it does not exist
        (idempotent under concurrent callers)."""
        rt = self._runtimes.pop(name, None)
        if rt is None:
            return False
        rt.shutdown()
        return True

    def validate_siddhi_app(self, app: Union[str, SiddhiApp]) -> None:
        """Parse + compile, then dispose (reference: SiddhiManager.validateSiddhiApp)."""
        runtime = self.create_siddhi_app_runtime(app)
        runtime.shutdown()
        del self._runtimes[runtime.name]

    def set_persistence_store(self, store) -> None:
        self.persistence_store = store

    def set_config_manager(self, config_manager) -> None:
        """Deployment config SPI (reference: SiddhiManager.setConfigManager)."""
        self.config_manager = config_manager

    def persist(self) -> None:
        for rt in self._runtimes.values():
            rt.persist()

    def restore_last_state(self) -> None:
        for rt in self._runtimes.values():
            rt.restore_last_revision()

    def shutdown(self) -> None:
        for rt in list(self._runtimes.values()):
            rt.shutdown()
        self._runtimes.clear()
