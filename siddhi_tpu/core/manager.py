"""SiddhiManager — top-level entry point.

Reference: core/SiddhiManager.java:45-243 — create/validate/shutdown app runtimes,
registry of extensions, persistence stores, data sources. Here it also owns the
host-side intern table shared by all apps it creates.
"""

from __future__ import annotations

from typing import Union

from siddhi_tpu.core.types import InternTable
from siddhi_tpu.query_api.siddhi_app import SiddhiApp


class SiddhiManager:
    def __init__(self) -> None:
        self.interner = InternTable()
        self.persistence_store = None
        self._error_store = None
        self._runtimes: dict[str, object] = {}
        self._metrics_server = None
        self._supervisor = None
        # per-app churn ledgers (core/churn.ChurnStats): manager-owned so
        # deploy/undeploy/redeploy counters survive redeploys and restarts
        self._churn: dict[str, object] = {}

    # app: SiddhiQL source text or a programmatic SiddhiApp AST
    def create_siddhi_app_runtime(
        self, app: Union[str, SiddhiApp], strict: bool = False
    ):
        """Build a runtime for `app`. With `strict=True` the semantic
        analyzer (`siddhi_tpu.analysis`) runs first: every error diagnostic
        is aggregated into one `SiddhiAnalysisError` raise (warnings are
        logged), so a bad app fails with source locations instead of dying
        mid-construction — or worse, mid-traffic — on the first problem."""
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
        from siddhi_tpu.core.app_runtime import SiddhiAppRuntime

        if isinstance(app, str):
            app = SiddhiCompiler.parse(app)
        if strict:
            import logging

            from siddhi_tpu.analysis import analyze

            result = analyze(app)
            for w in result.warnings:
                logging.getLogger("siddhi_tpu.analysis").warning(
                    w.format(result.app_name)
                )
            result.raise_if_errors()
        runtime = SiddhiAppRuntime(app, self)
        old = self._runtimes.get(runtime.name)
        if old is not None:
            old.shutdown()
        self._runtimes[runtime.name] = runtime
        if self._supervisor is not None:
            self._supervisor.attach(runtime)
        return runtime

    # short alias, mirroring the analyzer docs: create_runtime(app, strict=...)
    create_runtime = create_siddhi_app_runtime

    def get_siddhi_app_runtime(self, name: str):
        return self._runtimes.get(name)

    def shutdown_siddhi_app_runtime(self, name: str) -> bool:
        """Shut down and deregister one app; False when it does not exist
        (idempotent under concurrent callers)."""
        rt = self._runtimes.pop(name, None)
        if rt is None:
            return False
        rt.shutdown()
        return True

    def validate_siddhi_app(self, app: Union[str, SiddhiApp]) -> None:
        """Parse + compile, then dispose (reference: SiddhiManager.validateSiddhiApp)."""
        runtime = self.create_siddhi_app_runtime(app)
        runtime.shutdown()
        del self._runtimes[runtime.name]

    def set_persistence_store(self, store) -> None:
        self.persistence_store = store

    # ---- zero-downtime churn (core/churn.py) ------------------------------

    def churn_stats(self, app_name: str, create: bool = True):
        """The app's churn ledger (deploys/undeploys/redeploys/rollbacks,
        last splice wall time, last state-seed outcomes). With
        `create=False`, returns None for apps that never churned."""
        stats = self._churn.get(app_name)
        if stats is None and create:
            from siddhi_tpu.core.churn import ChurnStats

            stats = self._churn[app_name] = ChurnStats()
        return stats

    def redeploy(self, name: str, app: Union[str, SiddhiApp], **kw) -> dict:
        """Rolling upgrade of one deployed app: checkpoint -> build the
        replacement off-line -> restore every structurally-compatible
        component's state -> atomic swap under the supervisor's rebuild
        guard, with ingress buffered (bounded, admission-metered) rather
        than dropped across the swap window, then drained into the new
        runtime in arrival order. Stale input handlers keep working (the
        released gates forward them). Returns the redeploy report; on
        failure the OLD deployment is rolled back to and keeps serving.
        See core/churn.redeploy for the knobs (`strict`, `gate_capacity`,
        `gate_block_s`)."""
        from siddhi_tpu.core.churn import redeploy as _redeploy

        return _redeploy(self, name, app, **kw)

    # ---- error store (reference: SiddhiManager.setErrorStore) -------------

    @property
    def error_store(self):
        """The shared ErrorStore. Lazily defaults to a bounded in-memory store
        the first time an @OnError(action='STORE') stream or on.error='STORE'
        sink needs one; call set_error_store() to plug a custom backend."""
        if self._error_store is None:
            from siddhi_tpu.core.error_store import InMemoryErrorStore

            self._error_store = InMemoryErrorStore()
        return self._error_store

    def set_error_store(self, store) -> None:
        self._error_store = store

    def replay_errors(
        self,
        entries=None,
        purge: bool = True,
        timeout: float | None = None,
        skip_unavailable: bool = False,
        mode: str = "live",
    ) -> int:
        """Re-drive stored erroneous events through their origin: stream
        entries re-enter the input handler, sink entries re-publish. Returns
        the number of entries replayed; replayed entries are purged by default
        (a replay that fails again re-enters the store through the normal
        failure path, so nothing is lost).

        `skip_unavailable=True` skips sink entries whose target transport is
        still disconnected instead of letting an `on.error='WAIT'` sink
        block the replay loop — the skipped entries stay stored for the
        next replay. `timeout` (seconds) bounds the whole loop: entries not
        reached before the deadline stay stored. Both exist so one wedged
        app cannot hold every other app's entries hostage (the supervisor's
        post-restart replay always passes skip_unavailable=True).

        `mode='paused'` pauses each target stream's ingress for the loop
        (an admission-gate HOLD — live sends buffer in arrival order, not
        drop; core/churn.IngressGate) so replayed entries land in strict
        stored order before live traffic resumes. The default `'live'`
        mode interleaves replays with concurrent traffic."""
        import time as _time

        if mode not in ("live", "paused"):
            raise ValueError(f"replay_errors mode '{mode}' (live|paused)")
        if self._error_store is None:
            return 0
        if entries is None:
            entries = self.error_store.load()
        gates: list = []
        if mode == "paused":
            from siddhi_tpu.core.churn import IngressGate
            from siddhi_tpu.core.error_store import ORIGIN_SINK, ORIGIN_TABLE

            paused = set()
            for e in entries:
                if e.origin == ORIGIN_SINK:
                    continue  # sink replays re-publish; no ingress involved
                sid = e.sink_ref if e.origin == ORIGIN_TABLE else e.stream_id
                rt = self._runtimes.get(e.app_name)
                if rt is None or sid is None or (e.app_name, sid) in paused:
                    continue
                j = rt.junctions.get(sid)
                if j is None or j.ingress_gate is not None:
                    continue
                g = IngressGate(j, admission=getattr(rt, "_admission", None))
                j.ingress_gate = g
                gates.append((j, g))
                paused.add((e.app_name, sid))
        deadline = _time.monotonic() + timeout if timeout is not None else None
        replayed = 0
        try:
            for e in entries:
                if deadline is not None and _time.monotonic() >= deadline:
                    break
                rt = self._runtimes.get(e.app_name)
                if rt is None:
                    continue
                if skip_unavailable and not rt.replay_target_available(e):
                    continue
                if rt.replay_error(e):
                    replayed += 1
                    if purge:
                        # purge only DISPATCHED entries: a replay that fails
                        # again re-enters the store as a fresh entry through
                        # the live failure path, while an undispatchable one
                        # (origin gone) must stay stored rather than
                        # silently vanish
                        self.error_store.purge([e.id])
        finally:
            # resume live traffic: drain the held backlog in arrival order
            # (behind every replayed entry), then open each gate
            for j, g in gates:
                g.release(target=None, redirect=None)
                j.ingress_gate = None
        return replayed

    def set_config_manager(self, config_manager) -> None:
        """Deployment config SPI (reference: SiddhiManager.setConfigManager)."""
        self.config_manager = config_manager

    # ---- supervision (core/supervision.py) --------------------------------

    def supervise(self, poll_interval_s: float = 0.25):
        """Start (or return) this manager's Supervisor: every registered app
        — current and future — is watched for crash signals (unguarded
        dispatch failures, dead drain workers) and restarted per its
        `@app:restart(...)` policy: shutdown -> rebuild from the retained
        AST -> `restore_last_revision()` -> replay this app's stored errors
        -> resume. Idempotent; `poll_interval_s` applies to the first call."""
        if self._supervisor is None:
            from siddhi_tpu.core.supervision import Supervisor

            self._supervisor = Supervisor(self, poll_interval_s)
            for rt in list(self._runtimes.values()):
                self._supervisor.attach(rt)
        return self._supervisor

    @property
    def supervisor(self):
        """The running Supervisor, or None when `supervise()` was never
        called."""
        return self._supervisor

    # ---- metrics exposition (observability/http_server.py) ----------------

    def serve_metrics(self, port: int = 9464, host: str = "127.0.0.1") -> int:
        """Serve Prometheus text (`/metrics`), raw reports (`/metrics.json`),
        sampled traces (`/traces`), live engine state (`/status`,
        `/status.json`), flight-recorder rings (`/flight`), the continuous
        profiler (`/profile`), EXPLAIN ANALYZE plans (`/explain`,
        `/explain.json`), the plan-vs-actual calibration ledger
        (`/calibration`, `/calibration.json`), SLO burn rates (`/slo`,
        `/slo.json`), and black-box incident bundles (`/incidents`,
        `/incidents/<id>.json`) for EVERY app runtime registered on this
        manager. Idempotent: a second call
        returns the already-bound port. Pass port=0 for an ephemeral port;
        the bound port is returned either way."""
        if self._metrics_server is not None:
            bound = self._metrics_server.port
            if port not in (0, bound):
                import logging

                logging.getLogger(__name__).warning(
                    "serve_metrics(%d): metrics are already served on port "
                    "%d; the manager exposes ONE endpoint for all apps — "
                    "point the scrape at %d", port, bound, bound,
                )
            return bound
        from siddhi_tpu.observability.http_server import MetricsServer

        self._metrics_server = MetricsServer(self, host=host, port=port)
        return self._metrics_server.port

    @property
    def metrics_port(self):
        """Bound metrics port, or None when no endpoint is being served."""
        return (
            self._metrics_server.port
            if self._metrics_server is not None
            else None
        )

    def stop_metrics(self) -> None:
        srv, self._metrics_server = self._metrics_server, None
        if srv is not None:
            srv.close()

    def observability_reports(self) -> list:
        """One `StatisticsManager.report()` dict per stats-enabled app."""
        return [
            rt.statistics_manager.report()
            for rt in list(self._runtimes.values())
            if getattr(rt, "statistics_manager", None) is not None
        ]

    def prometheus_text(self) -> str:
        from siddhi_tpu.observability.reporters import render_prometheus

        text = render_prometheus(self.observability_reports())
        # supervision + admission families live outside the per-app
        # statistics registries (they meter apps with statistics OFF too)
        if self._supervisor is not None:
            text += self._supervisor.prometheus_text()
        adm_lines = []
        for name, rt in list(self._runtimes.items()):
            ctl = getattr(rt, "_admission", None)
            if ctl is None:
                continue
            lab = f'{{app="{name}",policy="{ctl.config.policy}"}}'
            adm_lines.append(f"siddhi_admission_shed_total{lab} {ctl.shed}")
            adm_lines.append(
                f"siddhi_admission_blocked_ms_total{lab} "
                f"{round(ctl.blocked_ms, 3)}"
            )
        if adm_lines:
            text += (
                "# HELP siddhi_admission_shed_total Events shed by the "
                "per-app admission gate\n"
                "# TYPE siddhi_admission_shed_total counter\n"
                "# HELP siddhi_admission_blocked_ms_total Sender wall time "
                "spent blocked by admission back-pressure\n"
                "# TYPE siddhi_admission_blocked_ms_total counter\n"
                + "\n".join(adm_lines) + "\n"
            )
        # churn family (core/churn.py): manager-owned, so it meters apps
        # whose runtimes were replaced since
        churn_lines = []
        for name, stats in sorted(self._churn.items()):
            for op, v in (
                ("deploy", stats.deploys),
                ("undeploy", stats.undeploys),
                ("redeploy", stats.redeploys),
                ("rollback", stats.rollbacks),
            ):
                churn_lines.append(
                    f'siddhi_churn_total{{app="{name}",op="{op}"}} {v}'
                )
        if churn_lines:
            text += (
                "# HELP siddhi_churn_total Hot deploy/undeploy/redeploy/"
                "rollback operations per app\n"
                "# TYPE siddhi_churn_total counter\n"
                + "\n".join(churn_lines) + "\n"
            )
        # black-box families (observability/blackbox.py): incident counts
        # per armed trigger + per-stream ring totals
        from siddhi_tpu.observability.reporters import render_raw_family

        inc_lines, ring_lines = [], []
        for name, rt in list(self._runtimes.items()):
            bb = getattr(rt, "_blackbox", None)
            if bb is None:
                continue
            for trig, v in sorted(bb.incidents_total.items()):
                inc_lines.append(
                    f'siddhi_incidents_total{{app="{name}",trigger="{trig}"}}'
                    f" {v}"
                )
            for sid, j in list(rt.junctions.items()):
                if j.blackbox is not None:
                    ring_lines.append(
                        "siddhi_blackbox_ring_events"
                        f'{{app="{name}",stream="{sid}"}} '
                        f"{j.blackbox.describe_state()['total']}"
                    )
        text += render_raw_family(
            "siddhi_incidents_total", "counter",
            "Black-box incident bundles frozen, per armed trigger",
            inc_lines,
        )
        text += render_raw_family(
            "siddhi_blackbox_ring_events", "counter",
            "Events recorded into each stream's black-box ring",
            ring_lines,
        )
        return text

    def profile_reports(self) -> list:
        """One `profile_report()` dict per stats-enabled app (`/profile`):
        compile telemetry with cause taxonomy, top-K slowest chunk
        waterfalls, p99/p999/p9999 of every latency histogram, and the
        fused-group dispatch-reduction ledgers (core/fusion_exec.py)."""
        return [
            rep
            for rep in (
                rt.profile_report() for rt in list(self._runtimes.values())
            )
            if rep is not None
        ]

    def explain_reports(self) -> dict:
        """app name -> live-annotated dataflow plan (`/explain.json`)."""
        return {
            name: rt.explain_plan()
            for name, rt in list(self._runtimes.items())
        }

    def explain_text(self) -> str:
        """Rendered EXPLAIN ANALYZE for every app (`/explain`)."""
        from siddhi_tpu.observability.explain import render_text

        return (
            "\n\n".join(
                render_text(plan) for plan in self.explain_reports().values()
            )
            or "no apps registered\n"
        )

    def calibration_reports(self) -> dict:
        """app name -> plan-vs-actual calibration report
        (`/calibration.json`, observability/calibration.py); apps without
        `@app:statistics` have no ledger and are omitted."""
        out = {}
        for name, rt in list(self._runtimes.items()):
            rep = rt.calibration_report()
            if rep is not None:
                out[name] = rep
        return out

    def calibration_text(self) -> str:
        """Rendered calibration ledger for every app (`/calibration`)."""
        from siddhi_tpu.observability.calibration import (
            render_calibration_text,
        )

        reports = self.calibration_reports()
        if not reports:
            return "no calibration-enabled apps (add @app:statistics)\n"
        return render_calibration_text(reports)

    def slo_reports(self) -> dict:
        """app name -> SLO burn-rate report (`/slo.json`,
        observability/slo.py); apps without `@app:slo` are omitted."""
        out = {}
        for name, rt in list(self._runtimes.items()):
            rep = rt.slo_report()
            if rep is not None:
                out[name] = rep
        return out

    def slo_text(self) -> str:
        """Rendered SLO burn rates for every app (`/slo`)."""
        from siddhi_tpu.observability.slo import render_slo_text

        reports = self.slo_reports()
        if not reports:
            return "no slo-enabled apps (add @app:slo)\n"
        return render_slo_text(reports)

    # ---- state introspection (observability/introspect.py) ----------------

    def snapshot_status(self) -> dict:
        """Live engine state across every app on this manager plus the
        shared error store — served as `/status` (human text) and
        `/status.json` by `serve_metrics()`. Pull-only; see
        `SiddhiAppRuntime.snapshot_status()` for the per-app schema."""
        status: dict = {
            "apps": {
                name: rt.snapshot_status()
                for name, rt in list(self._runtimes.items())
            }
        }
        store = self._error_store
        if store is not None and hasattr(store, "describe_state"):
            status["error_store"] = store.describe_state()
        if self._supervisor is not None:
            status["supervisor"] = self._supervisor.describe_state()
        return status

    def status_text(self) -> str:
        from siddhi_tpu.observability.introspect import render_status

        return render_status(self.snapshot_status())

    def incidents(self) -> dict:
        """Every @app:blackbox-armed app's frozen incident bundles:
        app -> {"incidents": {trigger: count}, "bundles": [...]} — served
        as `/incidents(.json)` by `serve_metrics()`."""
        out = {}
        for name, rt in list(self._runtimes.items()):
            bb = getattr(rt, "_blackbox", None)
            if bb is None:
                continue
            out[name] = {
                "incidents": dict(bb.incidents_total),
                "bundles": bb.incident_index(),
            }
        return out

    def incident_detail(self, incident_id: str):
        """JSON-safe summary of one frozen bundle by id (checkpoint bytes
        and pickled AST elided) — `/incidents/<id>.json`; None when no
        recorder on this manager knows the id."""
        from siddhi_tpu.observability.blackbox import (
            bundle_summary,
            load_bundle,
        )

        for rt in list(self._runtimes.values()):
            bb = getattr(rt, "_blackbox", None)
            if bb is None:
                continue
            for rec in bb.incident_index():
                if rec["id"] == incident_id:
                    try:
                        return bundle_summary(load_bundle(rec["path"]))
                    except Exception as e:
                        return {
                            "id": incident_id,
                            "error": f"{type(e).__name__}: {e}",
                            "path": rec["path"],
                        }
        return None

    def flight_records(self) -> dict:
        """Every app's recorded flight rings: app -> stream -> [(ts, row)]."""
        out = {}
        for name, rt in list(self._runtimes.items()):
            recs = rt.flight_records()
            if recs:
                out[name] = recs
        return out

    def lineage_reports(self, resolve_recent: int = 1) -> dict:
        """Every lineage-enabled app's provenance report: app -> per-stream
        arenas, per-query fan-in + recent resolved chains (`/lineage.json`)."""
        out = {}
        for name, rt in list(self._runtimes.items()):
            rep = rt.lineage_report(resolve_recent=resolve_recent)
            if rep:
                out[name] = rep
        return out

    def lineage_text(self) -> str:
        """Human-readable lineage summary for every app (`/lineage`)."""
        from siddhi_tpu.observability.lineage import render_lineage_text

        reports = self.lineage_reports()
        if not reports:
            return "no lineage-enabled apps (add @app:lineage)\n"
        return render_lineage_text(reports)

    def persist(self) -> None:
        for rt in self._runtimes.values():
            rt.persist()

    def restore_last_state(self) -> None:
        for rt in self._runtimes.values():
            rt.restore_last_revision()

    def shutdown(self) -> None:
        # stop the supervisor FIRST: a mid-shutdown crash signal must not
        # race a restart against the teardown below
        sup, self._supervisor = self._supervisor, None
        if sup is not None:
            sup.stop()
        self.stop_metrics()
        for rt in list(self._runtimes.values()):
            rt.shutdown()
        self._runtimes.clear()
