"""External record-table SPI: tables backed by a pluggable store.

Reference: table/record/AbstractRecordTable.java + AbstractQueryableRecordTable
— the SPI external stores (RDBMS etc.) implement, with
`ExpressionBuilder`->`CompiledExpression` condition pushdown.

TPU-native shape: the device columnar arena IS the working copy (every query
keeps probing it with fused kernels); a `@store(type='...')` table loads its
initial contents from the record store at app creation and writes a row
snapshot through after every mutating step. Condition pushdown is unnecessary
— the dense on-device scan is the fast path, the external store is durability.
Stores register via @extension("store", name).
"""

from __future__ import annotations

import threading
from typing import Optional

from siddhi_tpu.core.errors import SiddhiAppCreationError


class RecordStore:
    """SPI: durable backing for one table.

    Two operating modes (reference: AbstractRecordTable vs
    AbstractQueryableRecordTable):
    - MATERIALIZED (default): `load()` returns the full row list; the device
      columnar arena is the working copy and every probe is a fused on-device
      scan — condition pushdown is unnecessary, the store is durability.
    - LAZY/QUERYABLE: `load()` returns None ("too big to materialize");
      store queries then push their on-condition down via `query()` and only
      the matching rows are staged onto the device for the select phase.
      Streaming writes into a lazy store are rejected at runtime."""

    def init(self, table_id: str, schema, options: dict) -> None:
        self.table_id = table_id
        self.schema = schema
        self.options = options

    def load(self) -> Optional[list[tuple]]:
        """Initial table contents (rows of python values, schema order), or
        None to stay lazy and serve finds through `query()`."""
        return []

    def query(self, on_expression, interner) -> Optional[list[tuple]]:
        """Condition pushdown for lazy stores: rows matching the store
        query's raw `on` Expression AST (None AST = all rows). Return None
        when the condition cannot be pushed down — the engine then raises
        (a lazy store without pushdown cannot be probed). The device re-checks
        the condition, so over-returning rows is always safe
        (reference: ExpressionBuilder -> CompiledExpression in
        AbstractQueryableRecordTable)."""
        return None

    def on_change(self, rows: list[tuple]) -> None:
        """Write-through: the table's full row snapshot after a mutation."""
        raise NotImplementedError

    def disconnect(self) -> None:
        pass


class InMemoryRecordStore(RecordStore):
    """Process-wide store keyed by `store.id` (or the table id) — survives app
    restarts within the process; the reference's test analog of an external
    store."""

    _lock = threading.Lock()
    _data: dict[str, list[tuple]] = {}

    def _key(self) -> str:
        return self.options.get("store.id", self.table_id)

    def load(self) -> list[tuple]:
        with self._lock:
            return list(self._data.get(self._key(), []))

    def on_change(self, rows: list[tuple]) -> None:
        with self._lock:
            self._data[self._key()] = list(rows)

    @classmethod
    def clear_all(cls) -> None:
        with cls._lock:
            cls._data.clear()


RECORD_STORES = {"memory": InMemoryRecordStore}


def build_record_store(ann, table_id: str, schema) -> Optional[RecordStore]:
    """From a table definition's @store(type='...', ...) annotation."""
    from siddhi_tpu.core.extension import lookup

    stype = ann.element("type")
    if stype is None:
        raise SiddhiAppCreationError("@store needs a type")
    cls = RECORD_STORES.get(stype.lower()) or lookup("store", stype)
    if cls is None:
        raise SiddhiAppCreationError(f"unknown store type '{stype}'")
    store = cls()
    store.init(table_id, schema, {k: v for k, v in ann.elements if k is not None})
    return store
