"""Zero-downtime churn: hot deploy/undeploy, rolling upgrade, rebalancing.

PR 9 delivered the supervision half of production serving (auto-checkpoint,
crash recovery, admission control, fault injection); this module is the
churn half — what lets a multi-tenant manager run for weeks while tenants
add and remove queries daily, without draining live traffic:

* **Hot deploy / undeploy of individual queries** —
  `runtime.add_query(siddhiql)` builds the new query runtime fully OFF-LINE
  (parse -> SA130 lint against the live app's symbols -> construct ->
  prewarm the jitted step so the compile never lands inside the splice
  window), then splices it into the junction fan-out under the app process
  lock (the same lock PR 9's torn-checkpoint fix established), seeding its
  windows/patterns from the last checkpoint through the existing snapshot
  SPI when a structurally-compatible `query:<id>` element exists.
  `runtime.remove_query(qid)` is the inverse. Both re-run fusion-group
  formation: the affected junctions' fused engines are torn down
  (unshare-then-reshare of shared rings, via PR 8's `_maybe_unshare`) and
  rebuilt from the NEW wiring + FusionPlan, so the group grows/shrinks
  while surviving queries' emissions stay byte-identical across the splice
  (their carried window states ride through untouched; the teardown window
  runs the per-batch path, whose byte parity with the fused path is the
  PR 8 CI contract).

* **Rolling app upgrade** — `manager.redeploy(name, new_app)` does
  checkpoint -> build the replacement runtime off-line -> restore every
  structurally-compatible component's state (per-component snapshot keys
  matched by id; incompatible or dropped components start cold, surfaced
  in the returned report) -> atomic swap under the supervisor's
  `_rebuilding` guard, with ingress BUFFERED (bounded `IngressGate`s on
  every stream junction, admission-metered) rather than dropped during the
  swap window, then drained into the new runtime in arrival order. Stale
  input handlers obtained before the swap keep working: the released gate
  forwards them to the new runtime.

* **Shard rebalancing** — when `@app:shard` mesh size changes on redeploy,
  partitioned `[P]` state migrates between device placements through the
  host snapshot (the `[P]` axis is capacity-shaped, not device-shaped, so
  the state restores bit-exact and the new mesh's `in_shardings` re-places
  it on first dispatch); the redeploy report carries the before/after
  placement and the per-device counters prove the new placement.

Everything is supervisor-aware (a failure mid-splice rolls back to the
pre-churn runtime; a failed swap rebuilds the old app from its retained
AST + the checkpoint just taken) and fault-injectable through the
`churn_splice` / `churn_restore` sites (testing/faults.py). Churn counters
live on the MANAGER (they must survive redeploys and supervised restarts)
and surface in `/status.json`, `runtime.explain()`, and the
`siddhi_churn_total{op=}` Prometheus family.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Union

from siddhi_tpu.core.errors import (
    DefinitionNotExistError,
    SiddhiAppCreationError,
)
from siddhi_tpu.testing import faults as _faults

log = logging.getLogger(__name__)

DEFAULT_GATE_CAPACITY = 8192
DEFAULT_GATE_BLOCK_S = 10.0


# ---------------------------------------------------------------------------
# churn counters (manager-owned: they outlive any one runtime)
# ---------------------------------------------------------------------------


@dataclass
class ChurnStats:
    """Per-app churn ledger, owned by the SiddhiManager so it survives both
    operator redeploys and supervised restarts."""

    deploys: int = 0
    undeploys: int = 0
    redeploys: int = 0
    rollbacks: int = 0
    last_splice_ms: Optional[float] = None
    # component -> outcome of the last state-seeding pass ('seeded',
    # 'restored', 'cold', 'incompatible', 'dropped', ...)
    last_seed: dict = field(default_factory=dict)
    events: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=32)
    )

    def record(self, op: str, detail: str = "") -> None:
        self.events.append((int(time.time() * 1000), op, detail))

    def describe_state(self) -> dict:
        d: dict = {
            "deploys": self.deploys,
            "undeploys": self.undeploys,
            "redeploys": self.redeploys,
            "rollbacks": self.rollbacks,
        }
        if self.last_splice_ms is not None:
            d["last_splice_ms"] = round(self.last_splice_ms, 3)
        if self.last_seed:
            d["last_seed"] = dict(self.last_seed)
        if self.events:
            d["events"] = [list(e) for e in self.events]
        return d


# ---------------------------------------------------------------------------
# SA130 — hot add_query candidate lint (shared rule set, like SA125-SA129)
# ---------------------------------------------------------------------------


def _candidate_info_name(query) -> Optional[str]:
    from siddhi_tpu.query_api.annotation import find_annotation

    info = find_annotation(query.annotations, "info")
    return info.element("name") if info else None


def _taken_query_ids(app) -> set:
    from siddhi_tpu.query_api.execution import assign_execution_ids

    taken = set()
    for ent in assign_execution_ids(app):
        if ent[0] == "query":
            taken.add(ent[1])
        else:
            taken.add(ent[1])  # partition id
            taken.update(qid for qid, _q in ent[3])
    return taken


def iter_add_query_problems(app, query):
    """Yield one message per problem with a hot `add_query` candidate
    against the LIVE app's symbols — THE validation rules, shared by the
    runtime (`runtime.add_query` raises on the first) and the analyzer's
    SA130 diagnostic (`siddhi_tpu.analysis.analyze_add_query` reports them
    all), following the SA125–SA129 shared-rule-set pattern.

    Rules: a hot-deployed query needs an explicit @info name (auto-numbered
    `queryN` ids are POSITIONAL over the AST — they renumber as other
    unnamed queries churn in and out and across supervised rebuilds, so an
    auto id is not a stable handle for remove_query/seeding/metrics); a
    duplicate query id would collide with a deployed query (ids key
    callbacks, metrics, and snapshot elements); an undeclared input stream
    would die at construction with less context — and hot deploy cannot
    define new streams, only consume declared ones."""
    from siddhi_tpu.analysis.symbols import build_symbols
    from siddhi_tpu.query_api.execution import (
        JoinInputStream,
        SingleInputStream,
        StateInputStream,
        iter_state_streams,
    )

    name = _candidate_info_name(query)
    if not name:
        yield (
            "hot add_query candidates need an explicit @info(name='...'): "
            "auto-numbered query ids renumber as unnamed queries churn"
        )
    elif name in _taken_query_ids(app):
        yield (
            f"duplicate query name '{name}': a query with this @info name "
            "is already deployed"
        )

    sym = build_symbols(app, [])  # diagnostics of the APP are not ours here
    stream = query.input_stream
    if isinstance(stream, SingleInputStream):
        sid = stream.stream_id
        if sid not in sym.streams and sid not in sym.windows:
            what = sym.describe(sid)
            hint = f" ('{sid}' is a {what})" if what else ""
            yield (
                f"undeclared stream '{sid}': hot add_query can only consume "
                f"streams/windows the live app defines{hint}"
            )
    elif isinstance(stream, JoinInputStream):
        for s in (stream.left, stream.right):
            sid = s.stream_id
            if (
                sid not in sym.streams
                and sid not in sym.windows
                and sid not in sym.tables
                and sid not in sym.aggregations
            ):
                yield (
                    f"undeclared stream '{sid}': hot add_query join sides "
                    "must reference declared streams, tables, windows, or "
                    "aggregations"
                )
    elif isinstance(stream, StateInputStream):
        for s in iter_state_streams(stream.state):
            if s.stream_id not in sym.streams:
                yield (
                    f"undeclared stream '{s.stream_id}': pattern streams "
                    "must be declared by the live app"
                )


def candidate_query_id(app, query) -> str:
    """The qid this candidate gets: its @info name, which
    iter_add_query_problems guarantees present and unique — the ONE id
    assignment that is stable across later splices and supervised
    rebuilds (assign_execution_ids reserves explicit names app-wide, so
    the rebuild derives the identical id; positional `queryN` ids would
    renumber)."""
    name = _candidate_info_name(query)
    if not name:  # belt and braces; the lint rejected this already
        raise SiddhiAppCreationError(
            "hot add_query candidates need an explicit @info(name='...')"
        )
    return name


# ---------------------------------------------------------------------------
# ingress gate: bounded buffered hold on a stream's input handlers
# ---------------------------------------------------------------------------


class IngressGate:
    """Bounded hold-then-drain gate in front of one junction's input
    handlers (`StreamJunction.ingress_gate`, checked by InputHandler.send/
    send_many/send_columns).

    States:
      * holding — sends buffer in arrival order; a full buffer BLOCKS the
        sender (admission-gate hold, not drop) until space frees or the
        hold ends; past `block_timeout_s` the overflow is shed and counted
        (and metered on the app's AdmissionController when one exists).
      * released with a redirect — stale handles bound to the OLD junction
        keep working: their sends forward to the redirect handler (the
        replacement runtime's input handler after a redeploy).
      * released without a redirect — pass-through (the paused-replay gate:
        the same junction resumes normal dispatch).

    The installing thread is exempt: the redeploy drain and the paused
    replay run on it and must reach the junction directly."""

    def __init__(
        self,
        junction,
        capacity: int = DEFAULT_GATE_CAPACITY,
        block_timeout_s: float = DEFAULT_GATE_BLOCK_S,
        admission=None,
    ):
        self.junction = junction
        self.capacity = int(capacity)
        self.block_timeout_s = float(block_timeout_s)
        self._admission = admission
        self._cv = threading.Condition()
        self._buf: collections.deque = collections.deque()
        self._buffered = 0  # events currently held
        self._owner = threading.current_thread()
        self.released = False
        self.redirect = None  # post-release forward target (InputHandler-like)
        self.held_total = 0
        self.shed = 0
        self.blocked_ms = 0.0

    # ---- sender side -----------------------------------------------------

    def intercept(self, kind: str, args: tuple, n: int) -> bool:
        """Called by InputHandler with one send. Returns True when the gate
        consumed it (buffered or forwarded); False = proceed normally."""
        if self.released:
            # post-release the redirect applies to EVERY thread (the owner
            # exemption below exists only so the drain/replay can reach
            # the junction while the hold is up)
            r = self.redirect
            if r is None:
                return False
            if kind == "rows":
                ts, rows, now = args
                r.send_many(rows, timestamps=ts)
            else:
                ts, cols, now = args
                r.send_columns(ts, cols, now)
            return True
        if threading.current_thread() is self._owner:
            return False
        t0 = time.monotonic()
        deadline = t0 + self.block_timeout_s
        with self._cv:
            while (
                not self.released
                and self._buffered + n > self.capacity
                and time.monotonic() < deadline
            ):
                self._cv.wait(timeout=min(0.05, self.block_timeout_s))
            self.blocked_ms += (time.monotonic() - t0) * 1000.0
            if self.released:
                pass  # re-enter the released branch below, outside the lock
            elif self._buffered + n > self.capacity:
                # held past the bound: shed, counted here AND on the app's
                # admission meter so operators see the loss where they
                # already watch overload
                self.shed += n
                if self._admission is not None:
                    self._admission.shed += n
                return True
            else:
                self._buf.append((kind, args))
                self._buffered += n
                self.held_total += n
                return True
        return self.intercept(kind, args, n)  # released while we waited

    # ---- owner side ------------------------------------------------------

    def release(self, target=None, redirect=None) -> int:
        """Drain every buffered send in arrival order into `target` (an
        InputHandler-like; defaults to direct junction delivery), then open
        the gate — with `redirect` set, later sends on stale handles
        forward there instead of hitting the (dead) junction. Returns the
        number of events drained. Buffering stays armed WHILE draining, so
        live senders cannot overtake the backlog."""
        drained = 0
        while True:
            with self._cv:
                if not self._buf:
                    self.redirect = redirect
                    self.released = True
                    self._cv.notify_all()
                    return drained
                kind, args = self._buf.popleft()
                n = len(args[0])
                self._buffered -= n
                self._cv.notify_all()
            drained += n
            try:
                if target is not None:
                    if kind == "rows":
                        ts, rows, now = args
                        target.send_many(rows, timestamps=ts)
                    else:
                        ts, cols, now = args
                        target.send_columns(ts, cols, now)
                else:
                    if kind == "rows":
                        ts, rows, now = args
                        self.junction.send_rows(ts, rows, now=now)
                    else:
                        ts, cols, now = args
                        from siddhi_tpu.core.stream_junction import (
                            InputHandler,
                        )

                        InputHandler(
                            self.junction, lambda _n=now: _n
                        ).send_columns(ts, cols, now)
            except Exception:
                log.exception(
                    "ingress gate for stream '%s': draining a buffered send "
                    "failed; the entry was dropped",
                    self.junction.schema.stream_id,
                )
                self.shed += n

    def describe_state(self) -> dict:
        return {
            "buffered": self._buffered,
            "held_total": self.held_total,
            "shed": self.shed,
            "blocked_ms": round(self.blocked_ms, 3),
            "released": self.released,
            "redirected": self.redirect is not None,
        }


def _gate_streams(runtime, capacity: int, block_timeout_s: float) -> dict:
    """Install an IngressGate on every DEFINED stream's junction (external
    ingress points; internal insert-into junctions keep flowing so the old
    runtime finishes what it already accepted)."""
    gates: dict = {}
    for sid in runtime.app.stream_definitions:
        j = runtime.junctions.get(sid)
        if j is None:
            j = runtime._junction(sid)
        g = IngressGate(
            j, capacity=capacity, block_timeout_s=block_timeout_s,
            admission=runtime._admission,
        )
        j.ingress_gate = g
        gates[sid] = g
    return gates


# ---------------------------------------------------------------------------
# state seeding through the snapshot SPI
# ---------------------------------------------------------------------------


def _tree_compatible(fresh, value) -> bool:
    """Structural compatibility of a snapshot element against a freshly
    initialized state tree: identical path sets, identical leaf shapes and
    dtypes. Anything else starts cold (surfaced, never guessed at)."""
    import numpy as np

    from siddhi_tpu.core.persistence import _flat_with_paths

    try:
        fa = _flat_with_paths(fresh)
        fb = _flat_with_paths(value)
    except Exception:
        return False
    if set(fa) != set(fb):
        return False
    for k, a in fa.items():
        b = fb[k]
        a_arr = hasattr(a, "shape")
        if a_arr != hasattr(b, "shape"):
            return False
        if a_arr and (
            tuple(a.shape) != tuple(b.shape)
            or np.dtype(a.dtype) != np.dtype(b.dtype)
        ):
            return False
    return True


def _fresh_state_of(qr):
    try:
        return qr.init_state()
    except TypeError:
        return qr.init_state(0)


def _element_component(rt, key: str):
    """Resolve a snapshot element key to (component_kind, live_object) in
    `rt`, or (kind, None) when the component no longer exists."""
    kind, _, name = key.partition(":")
    if kind in ("query", "rate"):
        return kind, rt.queries.get(name)
    if kind == "table":
        return kind, rt.tables.get(name)
    if kind == "window":
        return kind, rt.named_windows.get(name)
    if kind == "aggregation":
        return kind, rt.aggregations.get(name)
    if kind == "partition":
        idx = int(name.split(":")[0])
        return kind, rt.partitions[idx] if idx < len(rt.partitions) else None
    return kind, None


def seed_runtime_from_snapshot(rt, payload: dict) -> dict:
    """Restore every structurally-compatible element of a full-snapshot
    payload into runtime `rt` (per-component keys matched by id); returns
    {element_key: outcome} with outcomes 'restored' | 'incompatible' |
    'dropped' (component gone) plus 'cold' rows for new components the
    snapshot does not cover. Incompatible components START COLD — state is
    never coerced across a definition change."""
    svc = rt.snapshot_service
    report: dict = {}
    elements = dict(payload.get("elements", {}))
    rates = dict(payload.get("rates", {}))
    restorable: dict = {}
    for key, value in elements.items():
        kind, comp = _element_component(rt, key)
        if comp is None:
            report[key] = "dropped"
            continue
        if kind == "query":
            fresh = comp.state if comp.state is not None else _fresh_state_of(comp)
        elif kind == "partition":
            fresh = comp.ptable
        else:
            fresh = comp.state
        if _tree_compatible(fresh, value):
            restorable[key] = value
            report[key] = "restored"
        else:
            report[key] = "incompatible"
    for key, value in rates.items():
        _kind, comp = _element_component(rt, key)
        rl = getattr(comp, "rate_limiter", None) if comp is not None else None
        if rl is None:
            report[key] = "dropped"
        else:
            restorable[key] = value
            report[key] = "restored"
    with rt._process_lock:
        svc._restore_elements(
            {k: v for k, v in restorable.items() if not k.startswith("rate:")}
        )
        svc._restore_elements(
            {k: v for k, v in restorable.items() if k.startswith("rate:")}
        )
    # components the snapshot does not know start cold — surfaced so the
    # operator can tell "new component" from "lost state"
    for qid in rt.queries:
        report.setdefault(f"query:{qid}", "cold")
    for tid in rt.tables:
        report.setdefault(f"table:{tid}", "cold")
    for wid in rt.named_windows:
        report.setdefault(f"window:{wid}", "cold")
    for aid in rt.aggregations:
        report.setdefault(f"aggregation:{aid}", "cold")
    return report


def _seed_query_state(runtime, qid: str, qr, seed) -> str:
    """Seed a hot-deployed query's windows/patterns from the app's last
    checkpoint via the snapshot SPI. Returns the outcome: 'seeded' when a
    structurally-compatible `query:<qid>` element restored, 'cold'
    otherwise (no store / no revision / element absent / incompatible)."""
    import pickle

    if seed in (None, False, "cold"):
        return "cold"
    store = runtime.manager.persistence_store
    if store is None:
        return "cold"
    from siddhi_tpu.core.persistence import (
        _to_device,
        merge_snapshot_elements,
        merge_snapshot_interner,
    )

    try:
        last = store.get_last_revision(runtime.name)
        if last is None:
            return "cold"
        if getattr(store, "incremental", False):
            chain = runtime._incremental_chain(store, upto=last)
        else:
            data = store.load(runtime.name, last)
            chain = [data] if data is not None else []
        if not chain:
            return "cold"
        payloads = [pickle.loads(s) for s in chain]
        # interner first: a checkpoint from a PREVIOUS process carries ids
        # minted by that process's interner — without the merge the seeded
        # state's string ids would decode to the wrong (or no) strings.
        # Same helpers SnapshotService.restore uses, so the two cannot
        # drift.
        with runtime._process_lock:
            merge_snapshot_interner(runtime.interner, payloads[-1])
        elements, _rates = merge_snapshot_elements(payloads)
    except Exception:
        log.exception(
            "add_query '%s': reading the last checkpoint failed; starting "
            "cold", qid,
        )
        return "cold"
    value = elements.get(f"query:{qid}")
    if value is None:
        return "cold"
    # fault-injection site `churn_restore`: a failing seed is a failing
    # splice — the caller rolls back to the pre-churn runtime
    _faults.hit("churn_restore", f"{runtime.name}:{qid}")
    if not _tree_compatible(_fresh_state_of(qr), value):
        return "incompatible"
    qr.state = _to_device(value)
    return "seeded"


# ---------------------------------------------------------------------------
# prewarm: compile the jitted step(s) off the splice path
# ---------------------------------------------------------------------------


def _prewarm_query(runtime, qr) -> None:
    """Compile every per-batch jitted step of a freshly built query runtime
    with an all-invalid batch on THROWAWAY state, so the XLA compile
    happens BEFORE the splice (a cold compile inside the splice window
    would stall every live stream for seconds). The live jits are invoked
    directly rather than through `receive`: receive's table-state
    writeback would race live mutations of the shared tables the new
    query reads (lost update), and its carried-state update would need
    undoing. Table states are COPIED under the process lock first — live
    donated dispatches delete their old buffers, so the compile call must
    not read the live arrays off-lock. Best-effort: a prewarm failure
    only costs the first live batch the compile."""
    import jax
    import jax.numpy as jnp

    from siddhi_tpu.core.pattern_runtime import PatternQueryRuntime

    B = runtime.batch_size
    now = jnp.asarray(runtime.clock(), jnp.int64)
    try:
        with runtime._process_lock:
            tstates = jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True) if hasattr(x, "dtype") else x,
                qr._collect_table_states(),
            )
        if isinstance(qr, PatternQueryRuntime):
            for sid in qr.prog.stream_ids:
                st = qr._fresh(qr.init_state(int(now)))
                qr._steps[sid](
                    st, tstates, runtime.stream_schemas[sid].empty_batch(B),
                    now,
                )
        elif hasattr(qr, "side_schemas"):  # join runtime
            for side, schema in qr.side_schemas.items():
                st = qr._fresh(qr.init_state())
                qr._steps[side](st, tstates, schema.empty_batch(B), now)
        else:
            st = qr._fresh(qr.init_state())
            qr._step(st, tstates, qr.in_schema.empty_batch(B), now)
    except Exception:
        log.debug(
            "prewarm of query '%s' failed; the first live batch pays the "
            "compile", qr.query_id, exc_info=True,
        )


# ---------------------------------------------------------------------------
# hot deploy / undeploy
# ---------------------------------------------------------------------------


def add_query(runtime, query: Union[str, object], seed="checkpoint") -> str:
    """Hot-deploy one query into a (possibly running) app runtime. See the
    module docstring for the build-offline / splice-under-lock protocol.
    Returns the assigned query id."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
    from siddhi_tpu.query_api.execution import Query

    if isinstance(query, str):
        query = SiddhiCompiler.parse_query(query)
    if not isinstance(query, Query):
        raise SiddhiAppCreationError(
            f"add_query expects SiddhiQL text or a Query AST, got "
            f"{type(query).__name__}"
        )
    # SA130 lint against the LIVE app's symbols (shared rule set)
    for problem in iter_add_query_problems(runtime.app, query):
        raise SiddhiAppCreationError(problem)
    qid = candidate_query_id(runtime.app, query)
    stats = runtime.manager.churn_stats(runtime.name)
    t0 = time.perf_counter()

    # ---- build fully off-line: construct + stage the wiring. The build
    # is host-side compilation (no XLA jit — that's the prewarm below),
    # but it inserts into runtime.queries / junctions / stream_schemas,
    # which concurrent readers (auto-persist's _elements walk,
    # snapshot_status) iterate under the process lock — so the insertions
    # hold it too.
    pre_schemas = set(runtime.stream_schemas)
    pre_junctions = set(runtime.junctions)
    staged: list = []
    try:
        with runtime._process_lock:
            runtime._staged_wiring = staged
            runtime._add_query(qid, query)
    except BaseException:
        with runtime._process_lock:
            # pop only OUR half-built entry — a build that failed on a
            # collision must not evict the live query holding the key
            existing = runtime.queries.get(qid)
            if existing is not None and getattr(
                existing, "query", None
            ) is query:
                runtime.queries.pop(qid, None)
            for sid in set(runtime.stream_schemas) - pre_schemas:
                runtime.stream_schemas.pop(sid, None)
            for sid in set(runtime.junctions) - pre_junctions:
                runtime.junctions.pop(sid, None)
        raise
    finally:
        runtime._staged_wiring = None
    qr = runtime.queries[qid]
    if runtime._running:
        _prewarm_query(runtime, qr)

    seed_outcome = "cold"
    tore_down = False
    try:
        seed_outcome = _seed_query_state(runtime, qid, qr, seed)

        # ---- splice under the app process lock ---------------------------
        # The fused engines are disabled+closed OUTSIDE the lock first: a
        # pipelined sender holds the engine's send lock while taking the
        # process lock per chunk, so closing under the process lock would
        # deadlock. The per-batch path that covers the gap is byte-parity
        # with the fused path by the PR 8 CI contract.
        if runtime._running and runtime._fuse_enabled:
            runtime._teardown_fused_ingest()
            tore_down = True
        with runtime._process_lock:
            # fault-injection site `churn_splice`: fires mid-splice, after
            # construction and before the wiring commits — the except arm
            # below proves the rollback contract
            _faults.hit("churn_splice", f"{runtime.name}:+{qid}")
            for action in staged:
                action()
            runtime.app.execution_elements.append(query)
    except BaseException as e:
        # roll back to the pre-churn runtime: un-apply the wiring, drop the
        # query, rebuild the fused engines from the (restored) wiring
        with runtime._process_lock:
            _unwire_query(runtime, qid, qr)
            runtime.queries.pop(qid, None)
            if runtime.app.execution_elements and (
                runtime.app.execution_elements[-1] is query
            ):
                runtime.app.execution_elements.pop()
            for sid in set(runtime.stream_schemas) - pre_schemas:
                runtime.stream_schemas.pop(sid, None)
            for sid in set(runtime.junctions) - pre_junctions:
                runtime.junctions.pop(sid, None)
        if tore_down:
            runtime._build_fused_ingest()
        stats.rollbacks += 1
        stats.record("rollback", f"add_query {qid}: {type(e).__name__}: {e}")
        raise
    # ---- re-form fusion groups over the grown wiring ---------------------
    if runtime._running and runtime._fuse_enabled:
        runtime._build_fused_ingest()
    # arm schedulers / rate limiter exactly as start() would have
    if runtime._running:
        if getattr(qr, "needs_scheduler", False) and hasattr(qr, "prime"):
            aux = qr.prime(runtime.clock())
            runtime._maybe_schedule(qr, aux)
        if getattr(qr, "host_next_timer", None) and getattr(
            qr, "timer_target", None
        ):
            runtime._scheduler.start()
            runtime._scheduler.notify_at(
                qr.host_next_timer(runtime.clock()), qr.timer_target
            )
        runtime._arm_rate_limiter(qr)
    stats.deploys += 1
    stats.last_splice_ms = (time.perf_counter() - t0) * 1000.0
    stats.last_seed = {f"query:{qid}": seed_outcome}
    stats.record("deploy", f"{qid} (seed={seed_outcome})")
    return qid


def _unwire_query(runtime, qid: str, qr) -> None:
    """Remove every junction subscription and fuse candidate of one query
    (caller holds the process lock)."""
    name = f"query.{qid}"
    for j in list(runtime.junctions.values()):
        j.unsubscribe(name)
        j.fuse_candidates = [ep for ep in j.fuse_candidates if ep.qr is not qr]
    for nw in runtime.named_windows.values():
        nw.out_junction.unsubscribe(name)


def remove_query(runtime, qid: str) -> None:
    """Hot-undeploy one top-level query: unsplice it from the junction
    fan-out under the app process lock, drop it from the retained AST (a
    supervised rebuild must not resurrect it), and re-form fusion groups
    over the shrunk wiring. Queries inside partitions are not individually
    removable (their state shares one [P] table)."""
    qr = runtime.queries.get(qid)
    if qr is None:
        raise DefinitionNotExistError(
            f"no query '{qid}' in app '{runtime.name}'"
        )
    for pr in runtime.partitions:
        if qr in pr.queries:
            raise SiddhiAppCreationError(
                f"query '{qid}' lives inside a partition; redeploy the app "
                "to change partition contents"
            )
    stats = runtime.manager.churn_stats(runtime.name)
    t0 = time.perf_counter()
    if runtime._running and runtime._fuse_enabled:
        runtime._teardown_fused_ingest()  # outside the lock; see add_query
    with runtime._process_lock:
        # fault site `churn_splice` BEFORE any mutation: an injected fault
        # leaves the runtime exactly as it was (consistent, never torn)
        _faults.hit("churn_splice", f"{runtime.name}:-{qid}")
        _unwire_query(runtime, qid, qr)
        runtime.queries.pop(qid, None)
        qr._removed = True  # pending timer/rate-limit fires become no-ops
        runtime.app.execution_elements = [
            e for e in runtime.app.execution_elements if e is not qr.query
        ]
        runtime._user_callbacks = [
            (n, cb) for n, cb in runtime._user_callbacks if n != qid
        ]
    if runtime._running and runtime._fuse_enabled:
        runtime._build_fused_ingest()
    stats.undeploys += 1
    stats.last_splice_ms = (time.perf_counter() - t0) * 1000.0
    stats.record("undeploy", qid)


# ---------------------------------------------------------------------------
# rolling redeploy
# ---------------------------------------------------------------------------


def redeploy(
    manager,
    name: str,
    app,
    strict: bool = False,
    gate_capacity: int = DEFAULT_GATE_CAPACITY,
    gate_block_s: float = DEFAULT_GATE_BLOCK_S,
) -> dict:
    """Rolling upgrade of one deployed app: checkpoint -> build the
    replacement off-line -> restore compatible state -> atomic swap with
    ingress buffered (never dropped) across the swap window. Returns the
    redeploy report; raises (with the OLD app rolled back and serving)
    when the replacement cannot be built or started."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
    from siddhi_tpu.core.app_runtime import SiddhiAppRuntime

    old = manager.get_siddhi_app_runtime(name)
    if old is None:
        raise DefinitionNotExistError(f"no app '{name}' on this manager")
    if isinstance(app, str):
        app = SiddhiCompiler.parse(app)
    if strict:
        from siddhi_tpu.analysis import analyze

        analyze(app).raise_if_errors()
    new_name = app.name if app.name else None
    if new_name is not None and new_name != name:
        raise SiddhiAppCreationError(
            f"redeploy('{name}') got an app named '{new_name}'; a rename is "
            "a deploy of a new app, not a redeploy"
        )
    stats = manager.churn_stats(name)
    t0 = time.perf_counter()
    import pickle

    # 1. gate ingress FIRST: live senders buffer (bounded,
    # admission-metered) from here on, so nothing the old runtime
    # processes can slip in between the checkpoint below and the swap —
    # state it advanced past the snapshot would be silently discarded
    gates = _gate_streams(old, gate_capacity, gate_block_s)

    # 2. checkpoint the gated runtime (bytes; flushed like persist()).
    # snapshot() takes the process lock, serializing after any dispatch
    # already in flight when the gates went up. @async rings admitted
    # events before the gates: wait (bounded) for their workers to drain
    # so those events reach the snapshot instead of dying with the old
    # runtime.
    drain_deadline = time.monotonic() + 5.0
    while time.monotonic() < drain_deadline and any(
        g.junction.queued() for g in gates.values()
    ):
        time.sleep(0.005)
    for sid, g in gates.items():
        leftover = g.junction.queued()
        if leftover:
            # ring events the workers could not drain in time die with
            # the old runtime — they are metered as shed (never silent)
            g.shed += leftover
            log.warning(
                "redeploy of app '%s': stream '%s' still holds %d "
                "@async-queued events past the drain window; they are "
                "counted as shed", name, sid, leftover,
            )
    for t in old.tables.values():
        t.flush_record_store()
    snap = old.snapshot()
    shard_before = (
        old._shard.describe_state() if old._shard is not None else None
    )
    sup = manager.supervisor
    new_rt = None
    started = False
    try:
        # 3. build the replacement fully off-line (NOT registered yet)
        new_rt = SiddhiAppRuntime(app, manager)
        # 4. restore compatible state through the snapshot SPI
        # (fault site `churn_restore`: a failing restore aborts the
        # redeploy with the old app still serving)
        _faults.hit("churn_restore", name)
        seed_report = seed_runtime_from_snapshot(new_rt, pickle.loads(snap))
        # carry user callbacks / exception handler over (same contract as
        # the supervisor's restart)
        cb_failed = []
        for cb_name, cb in list(getattr(old, "_user_callbacks", [])):
            try:
                new_rt.add_callback(cb_name, cb)
            except Exception:
                cb_failed.append(cb_name)
        handler = getattr(old, "_exception_handler", None)
        if handler is not None:
            new_rt.set_exception_handler(handler)

        # 5. atomic swap under the supervisor's _rebuilding guard: the
        # supervisor must not race a crash-restart of `name` against the
        # teardown below (core/supervision.Supervisor._check_all skips the
        # app while the guard names it)
        if sup is not None:
            sup._rebuilding = name
        try:
            old.shutdown()
            manager._runtimes[name] = new_rt
        finally:
            if sup is not None:
                sup._rebuilding = None
        if sup is not None:
            # operator redeploy: fresh supervision life (attempt streak and
            # gave-up verdicts reset — Supervisor.attach documents this)
            sup.attach(new_rt)
        new_rt.start()
        started = True
    except BaseException as e:
        stats.rollbacks += 1
        stats.record("rollback", f"redeploy: {type(e).__name__}: {e}")
        if manager.get_siddhi_app_runtime(name) is new_rt or started is False:
            _rollback_redeploy(manager, name, old, snap, gates, sup)
        raise
    # 6. drain the gated backlog into the replacement IN ARRIVAL ORDER,
    # then leave each gate redirecting so stale handles keep working.
    # The DRAIN bypasses the new app's admission gate (these events were
    # admitted once already — re-charging the burst against the token
    # bucket would shed an already-accepted backlog, the same hazard
    # PR 9's replay bypass closed); the REDIRECT for later live sends is
    # the admitted handler, so new traffic pays admission as usual.
    from siddhi_tpu.core.stream_junction import InputHandler as _RawHandler

    drained = 0
    for sid, gate in gates.items():
        if sid in new_rt.stream_schemas:
            raw = _RawHandler(
                new_rt._junction(sid), lambda _rt=new_rt: _rt.clock()
            )
            drained += gate.release(
                target=raw, redirect=new_rt.get_input_handler(sid)
            )
        else:
            # the stream no longer exists: shed the backlog (counted)
            # BEFORE release — draining it into the shut-down old
            # junction would run dead query steps
            with gate._cv:
                gate.shed += gate._buffered
                gate._buf.clear()
                gate._buffered = 0
                gate._cv.notify_all()
            gate.release(target=None, redirect=None)
    stats.redeploys += 1
    stats.last_splice_ms = (time.perf_counter() - t0) * 1000.0
    stats.last_seed = dict(seed_report)
    stats.record("redeploy", f"{drained} buffered events drained")
    shard_after = (
        new_rt._shard.describe_state() if new_rt._shard is not None else None
    )
    report = {
        "app": name,
        "state": seed_report,
        "restored": sorted(
            k for k, v in seed_report.items() if v == "restored"
        ),
        "cold": sorted(k for k, v in seed_report.items() if v == "cold"),
        "incompatible": sorted(
            k for k, v in seed_report.items() if v == "incompatible"
        ),
        "dropped": sorted(
            k for k, v in seed_report.items() if v == "dropped"
        ),
        "buffered_events_drained": drained,
        "gates": {sid: g.describe_state() for sid, g in gates.items()},
        "wall_ms": round(stats.last_splice_ms, 3),
        "callbacks_not_reregistered": cb_failed,
    }
    if shard_before is not None or shard_after is not None:
        report["shard"] = {"before": shard_before, "after": shard_after}
    return report


def _rollback_redeploy(manager, name, old, snap, gates, sup) -> None:
    """A failed swap must leave the OLD app serving: if its runtime is
    still up, just release the gates; if it was already torn down, rebuild
    it from the retained AST and the checkpoint taken at redeploy entry
    (mirroring the supervisor's restart sequence)."""
    current = manager.get_siddhi_app_runtime(name)
    if current is old and old._running:
        for g in gates.values():
            g.release(target=None, redirect=None)
        for j in old.junctions.values():
            j.ingress_gate = None
        return
    try:
        from siddhi_tpu.core.app_runtime import SiddhiAppRuntime

        if sup is not None:
            sup._rebuilding = name
        try:
            rebuilt = SiddhiAppRuntime(old.app, manager)
            rebuilt.restore(snap)
            for cb_name, cb in list(getattr(old, "_user_callbacks", [])):
                try:
                    rebuilt.add_callback(cb_name, cb)
                except Exception:
                    pass
            handler = getattr(old, "_exception_handler", None)
            if handler is not None:
                rebuilt.set_exception_handler(handler)
            manager._runtimes[name] = rebuilt
        finally:
            if sup is not None:
                sup._rebuilding = None
        if sup is not None:
            sup.attach(rebuilt)
        rebuilt.start()
        from siddhi_tpu.core.stream_junction import InputHandler as _Raw

        for sid, gate in gates.items():
            if sid in rebuilt.stream_schemas:
                # raw drain (admitted once already) + admitted redirect,
                # same split as the success path
                gate.release(
                    target=_Raw(
                        rebuilt._junction(sid), lambda _rt=rebuilt: _rt.clock()
                    ),
                    redirect=rebuilt.get_input_handler(sid),
                )
            else:
                gate.release(target=None, redirect=None)
        log.warning(
            "redeploy of app '%s' failed; rolled back to the previous "
            "deployment (state from the redeploy-entry checkpoint)", name,
        )
    except Exception:
        for g in gates.values():
            g.release(target=None, redirect=None)
        log.exception(
            "redeploy rollback for app '%s' failed; the app is DOWN", name,
        )
