"""Framework exceptions (analog of reference core/exception/*)."""


class SiddhiAppCreationError(Exception):
    """App failed to parse/validate/compile (reference: SiddhiAppCreationException)."""


class SiddhiParserError(SiddhiAppCreationError):
    """SiddhiQL syntax error, with line/column context."""

    def __init__(self, message: str, line: int | None = None, col: int | None = None):
        loc = f" at line {line}:{col}" if line is not None else ""
        super().__init__(f"{message}{loc}")
        self.line, self.col = line, col


class SiddhiAppRuntimeError(Exception):
    """Runtime processing failure (reference: SiddhiAppRuntimeException)."""


class DefinitionNotExistError(SiddhiAppCreationError):
    pass


class StoreQueryCreationError(SiddhiAppCreationError):
    pass


class ConnectionUnavailableError(Exception):
    """Transport connection loss; triggers source/sink retry
    (reference: exception/ConnectionUnavailableException.java)."""
