"""Triggers: timestamp-event injection streams.

Reference: core/trigger/PeriodicTrigger.java:30-90, CronTrigger.java,
StartTrigger.java — `define trigger T at every 5 sec | 'cron expr' | 'start'`
creates a stream T(triggered_time long) and injects the trigger time into its
junction on schedule.
"""

from __future__ import annotations

from typing import Callable, Optional

from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.query_api.definition import TriggerDefinition


class TriggerRuntime:
    def __init__(
        self,
        definition: TriggerDefinition,
        junction,
        scheduler,
        clock: Callable[[], int],
    ):
        self.definition = definition
        self.id = definition.id
        self.junction = junction
        self.scheduler = scheduler
        self.clock = clock
        self._running = False
        self.cron = None
        if definition.at_cron is not None:
            from siddhi_tpu.utils.cron import CronSchedule

            try:
                self.cron = CronSchedule(definition.at_cron)
            except ValueError as e:
                raise SiddhiAppCreationError(
                    f"trigger '{self.id}': {e}"
                ) from None

    def start(self) -> None:
        self._running = True
        d = self.definition
        if d.at_start:
            now = self.clock()
            self.junction.send_rows([now], [(now,)], now=now)
            return
        self.scheduler.start()
        self.scheduler.notify_at(self._next_after(self.clock()), self._fire)

    def _next_after(self, t_ms: int) -> int:
        d = self.definition
        if d.at_every_ms is not None:
            return t_ms + d.at_every_ms
        return self.cron.next_fire_ms(t_ms)

    def _fire(self, t_ms: int) -> None:
        if not self._running:
            return
        self.junction.send_rows([t_ms], [(t_ms,)], now=t_ms)
        if self._running:
            self.scheduler.notify_at(self._next_after(t_ms), self._fire)

    def stop(self) -> None:
        self._running = False
